"""The pubsub core: subscribe/unsubscribe/publish/dispatch.

Behavioral reference: ``apps/emqx/src/emqx_broker.erl`` (``publish/1``,
``subscribe/3``, ``dispatch/2``), ``emqx_broker_helper.erl`` and the
publish call stack of SURVEY.md §3.4 [U].

Responsibilities kept from the reference:

* subscriber table: filter → {clientid → SubOpts} (the ETS
  ``emqx_subscriber`` analog), shared groups delegated to
  :class:`SharedSub`;
* route table updates on first/last subscriber of a filter
  (``emqx_router:do_add_route`` / ``do_delete_route``);
* publish pipeline: ``'message.publish'`` hook fold → route match →
  per-subscriber QoS cap → session delivery → ``message.delivered`` /
  ``message.dropped`` hooks;
* ``$SYS`` messages never match root wildcards (enforced by the match
  oracle/trie/kernel);
* No-Local (MQTT5 ``nl``) suppression.

The broker is single-node here; ``dest`` in the router is either this
node's name (non-shared) or ``(group, node)`` (shared) so that the
multi-node forwarding layer (``emqx_tpu.cluster``) can ship deliveries
across nodes using the same tables.  The device NFA mirror subscribes to
``router.deltas_since`` (SURVEY.md §3.3 note).
"""

from __future__ import annotations

import logging
import time
from dataclasses import replace
from typing import Any, Dict, List, Optional, Tuple

from .. import topic as T
from .hooks import Hooks, HOOK_POINTS, OK, STOP
from .message import Message, make_message
from .mqueue import MQueue
from .router import Router
from .session import Publish, Session, SubOpts
from .shared_sub import SharedSub

log = logging.getLogger(__name__)

__all__ = ["Broker", "DeliverResult"]


class DeliverResult:
    """Per-publish outcome: connection-layer sendouts + accounting."""

    __slots__ = ("publishes", "dropped", "matched", "no_subscribers")

    def __init__(self) -> None:
        self.publishes: Dict[str, List[Publish]] = {}  # clientid -> sends
        self.dropped: List[Tuple[str, Message]] = []   # (clientid, msg)
        self.matched: int = 0
        self.no_subscribers: bool = False


class Broker:
    def __init__(
        self,
        node: str = "local",
        hooks: Optional[Hooks] = None,
        shared_strategy: str = "random",
        session_defaults: Optional[dict] = None,
    ) -> None:
        self.node = node
        self.hooks = hooks if hooks is not None else Hooks()
        # MQTT 5 enhanced auth providers: method name -> provider
        # (start/continue_auth contract — see auth/scram.py)
        self.enhanced_auth: Dict[str, Any] = {}
        self.router = Router()
        self.shared = SharedSub(shared_strategy)
        self.sessions: Dict[str, Session] = {}
        # filter -> {clientid -> SubOpts}; non-shared local subscribers
        self.subscribers: Dict[str, Dict[str, SubOpts]] = {}
        # clientid -> username, maintained by the channel on CONNECT; lets
        # services (topic rewrite %u, ACL templates) resolve usernames
        self.usernames: Dict[str, Optional[str]] = {}
        self.session_defaults = session_defaults or {}
        # out-of-band deliveries (retained replay, delayed publish): the
        # serving layer sets on_deliver to push straight to connections;
        # otherwise they accumulate in outbox for take_outbox().
        self.on_deliver = None  # Optional[Callable[[str, List[Publish]], None]]
        self.outbox: Dict[str, List[Publish]] = {}
        # cluster forwarding seams (emqx_broker_proto_v1:forward analog):
        # set by emqx_tpu.cluster when this node joins a cluster
        self.on_forward = None         # (node, flt, msg) -> None
        self.on_forward_shared = None  # (node, group, flt, msg) -> None
        # device match seam: set by the node's MatchService — returns a
        # precomputed routes list for a topic when a fresh (same-epoch)
        # device answer exists, None otherwise (host trie then serves)
        self.device_match = None       # (topic) -> Optional[List[Route]]
        # batched publish→deliver pipeline (broker/fanout.py): set by the
        # node when broker.fanout.enable is on; the channel offers hot-path
        # publishes here and falls back to the sync publish() when refused
        self.fanout = None             # Optional[FanoutPipeline]
        # batched admission plane (broker/admission.py): set by
        # Admission.attach when admission.enable is on.  None keeps
        # every admission seam at one attr load + identity test.
        self.admission = None          # Optional[Admission]
        # counter table, set by observe(); broker-internal drop accounting
        # (outbox overflow) lands here when present
        self.metrics = None
        self._outbox_warned: set = set()  # clients already logged for drops
        # stage-level latency observatory (observe/hist.py): direct
        # histogram references for the PER-MESSAGE sync publish path —
        # None = zero-call recording sites.  The batched fanout drain
        # records its own spans; without these, traffic that bypasses
        # the pipeline (shape gate, fanout off, direct publish callers)
        # is invisible in the deliver/e2e histograms (ISSUE 13
        # observability follow-on (b)).  Same main-loop writer thread
        # as the fanout drain, so the single-writer discipline holds.
        self.hists = None
        self._h_deliver = None
        self._h_flush = None
        self._h_e2e = None

    # ------------------------------------------------------------------
    # session lifecycle (emqx_cm:open_session semantics, simplified here;
    # full takeover lives in emqx_tpu.broker.cm)
    # ------------------------------------------------------------------

    def open_session(
        self, clientid: str, clean_start: bool = True, **kw
    ) -> Tuple[Session, bool]:
        """Returns (session, session_present)."""
        old = self.sessions.get(clientid)
        if old is not None and not clean_start:
            # a resuming client renegotiates flow-control/expiry knobs
            if "max_inflight" in kw:
                old.inflight.max_size = kw["max_inflight"]
            if "expiry_interval" in kw:
                old.expiry_interval = kw["expiry_interval"]
            old.connected = True
            self.hooks.run("session.resumed", (clientid,))
            return old, True
        if old is not None:
            self._drop_session_state(old)
            self.hooks.run("session.discarded", (clientid,))
        opts = {**self.session_defaults, **kw}
        sess = Session(clientid, clean_start=clean_start, **opts)
        sess.metrics = self.metrics
        self.sessions[clientid] = sess
        self.hooks.run("session.created", (clientid,))
        return sess, False

    def close_session(self, clientid: str, discard: bool = False) -> None:
        sess = self.sessions.get(clientid)
        if sess is None:
            return
        if discard or sess.clean_start:
            self._drop_session_state(sess)
            del self.sessions[clientid]
            self.outbox.pop(clientid, None)
            self._outbox_warned.discard(clientid)
            self.usernames.pop(clientid, None)
            self.hooks.run("session.terminated", (clientid,))
        else:
            sess.connected = False  # deliveries queue until resume

    def _drop_session_state(self, sess: Session) -> None:
        for flt in list(sess.subscriptions):
            self._do_unsubscribe(sess.clientid, flt, sess.subscriptions[flt])

    # ------------------------------------------------------------------
    # subscribe / unsubscribe (SURVEY.md §3.3)
    # ------------------------------------------------------------------

    def subscribe(self, clientid: str, raw_filter: str, opts: SubOpts = SubOpts()) -> bool:
        T.validate(raw_filter, "filter")
        sess = self.sessions.get(clientid)
        if sess is None:
            raise KeyError(f"no session for {clientid!r}")
        share = T.parse_share(raw_filter)
        if share is not None:
            group, flt = share
            opts = replace(opts, share=group)
        else:
            group, flt = None, raw_filter
        is_new = sess.subscribe(raw_filter, opts)
        if group is not None:
            self.shared.subscribe(group, flt, clientid, self.node)
            self.router.add_route(flt, (group, self.node))
        else:
            subs = self.subscribers.setdefault(flt, {})
            first = not subs
            subs[clientid] = opts
            if first:
                self.router.add_route(flt, self.node)
        self.hooks.run("session.subscribed", (clientid, raw_filter, opts, is_new))
        return True

    def unsubscribe(self, clientid: str, raw_filter: str) -> bool:
        sess = self.sessions.get(clientid)
        if sess is None:
            return False
        opts = sess.subscriptions.get(raw_filter)
        if opts is None:
            return False
        sess.unsubscribe(raw_filter)
        self._do_unsubscribe(clientid, raw_filter, opts)
        self.hooks.run("session.unsubscribed", (clientid, raw_filter))
        return True

    def _do_unsubscribe(self, clientid: str, raw_filter: str, opts: SubOpts) -> None:
        share = T.parse_share(raw_filter)
        if share is not None:
            group, flt = share
            self.shared.unsubscribe(group, flt, clientid, self.node)
            if not self.shared.members(group, flt):
                self.router.delete_route(flt, (group, self.node))
        else:
            flt = raw_filter
            subs = self.subscribers.get(flt)
            if subs and clientid in subs:
                del subs[clientid]
                if not subs:
                    del self.subscribers[flt]
                    self.router.delete_route(flt, self.node)

    # ------------------------------------------------------------------
    # publish / dispatch (SURVEY.md §3.4 — THE hot path)
    # ------------------------------------------------------------------

    def publish(self, msg: Message) -> DeliverResult:
        T.validate(msg.topic, "name")
        res = DeliverResult()
        adm = self.admission
        if adm is not None and msg.qos == 0 \
                and adm.shed_qos0(msg.sender):
            # quarantined sender: QoS0 is best-effort by contract, so
            # the shed happens BEFORE the publish fold (no retainer /
            # delayed side effects for dropped attack traffic); QoS1/2
            # ride the throttled token bucket instead of a drop path
            res.no_subscribers = True
            self.hooks.run("message.dropped", (msg, "admission_shed"))
            return res
        msg = self.hooks.run_fold("message.publish", (), msg)
        if msg is None or msg.headers.get("allow_publish") is False:
            res.no_subscribers = True
            return res
        return self._publish_folded(msg, res)

    def publish_folded(self, msg: Message) -> DeliverResult:
        """Dispatch a message whose ``'message.publish'`` fold ALREADY ran
        (fanout-pipeline fallback after stage 1) — re-running the fold
        here would fire retainer/delayed/rewrite side effects twice."""
        return self._publish_folded(msg, DeliverResult())

    def attach_hists(self, hists) -> None:
        """Wire the sync publish path's span recording sites (node
        startup; no-op cost when never called)."""
        self.hists = hists
        self._h_deliver = hists.hist("obs.stage.deliver")
        self._h_flush = hists.hist("obs.stage.flush")
        self._h_e2e = hists.hist("obs.e2e.publish_deliver")

    def _publish_folded(self, msg: Message, res: DeliverResult) -> DeliverResult:
        # the TPU hot path (SURVEY.md §3.4): a fresh micro-batched device
        # answer replaces the per-publish host trie walk; stale/absent
        # hints fall back so correctness never depends on the device
        t0 = time.perf_counter_ns() if self._h_deliver is not None else 0
        routes = None
        if self.device_match is not None:
            routes = self.device_match(msg.topic)
        if routes is None:
            routes = self.router.match_routes(msg.topic)
        if not routes:
            res.no_subscribers = True
            self.hooks.run("message.dropped", (msg, "no_subscribers"))
            return res
        seen_shared: set = set()
        for flt, dest in routes:
            if isinstance(dest, tuple):  # (group, node) shared route
                group, _node = dest
                if (group, flt) in seen_shared:
                    continue
                seen_shared.add((group, flt))
                self._dispatch_shared(group, flt, msg, res)
            elif dest == self.node:
                self._dispatch(flt, msg, res)
            elif self.on_forward is not None:
                # remote node owns subscribers of flt: ship the delivery
                if self.on_forward(dest, flt, msg):
                    res.matched += 1
        # push the fan-out to the connection layer (or the outbox when no
        # serving layer is attached — unit tests read res.publishes instead)
        t1 = time.perf_counter_ns() if self._h_deliver is not None else 0
        for clientid, pubs in res.publishes.items():
            self.emit(clientid, pubs)
        if self._h_deliver is not None:
            # per-message spans for bypass traffic: match+deliver as one
            # deliver span, the emit fan-out as flush, plus the e2e
            # publish→deliver sample when anything was delivered — the
            # same three histograms the batched drain writes, so bypass
            # rates climbing no longer hollow out the distributions
            t2 = time.perf_counter_ns()
            self._h_deliver.record(t1 - t0)
            self._h_flush.record(t2 - t1)
            if res.matched and self._h_e2e is not None:
                self._h_e2e.record_s(time.time() - msg.timestamp)
        return res

    def _dispatch(self, flt: str, msg: Message, res: DeliverResult) -> None:
        for clientid, opts in self.subscribers.get(flt, {}).items():
            if opts.nl and msg.sender == clientid:
                continue  # MQTT5 No-Local
            self._deliver_to(clientid, opts, msg, res)

    def _shared_try_deliver(
        self, group: str, flt: str, msg: Message, res: DeliverResult
    ):
        """The per-member acceptance probe shared by single-message and
        batched $share dispatch (ack-aware redispatch calls it until a
        member accepts)."""
        def try_deliver(member: Tuple[str, str]) -> bool:
            clientid, node = member
            if node != self.node:
                if self.on_forward_shared is not None:
                    # remote candidate: that node's shared table picks the
                    # concrete member (two-level cluster dispatch).  A
                    # False return (peer down) lets dispatch_with_ack try
                    # the next member; remote acceptance after a
                    # successful send is optimistic (async cast, like the
                    # reference's gen_rpc async dispatch).
                    if self.on_forward_shared(node, group, flt, msg):
                        res.matched += 1
                        return True
                return False
            sess = self.sessions.get(clientid)
            if sess is None:
                return False
            # $queue/... sessions store the raw legacy key, not $share form
            opts = sess.subscriptions.get(T.make_share(group, flt))
            if opts is None and group == T.QUEUE_PREFIX:
                opts = sess.subscriptions.get(f"{T.QUEUE_PREFIX}/{flt}")
            if opts is None:
                return False
            return self._deliver_to(clientid, opts, msg, res)

        return try_deliver

    def _dispatch_shared(
        self, group: str, flt: str, msg: Message, res: DeliverResult
    ) -> None:
        try_deliver = self._shared_try_deliver(group, flt, msg, res)
        extra = []
        if self.on_forward_shared is not None:
            # remote nodes holding members of this group, from the route
            # table's (group, node) dests — ("", node) candidate markers
            extra = [
                ("", d[1]) for d in self.router.routes_of(flt)
                if isinstance(d, tuple) and d[0] == group and d[1] != self.node
            ]
        member = self.shared.dispatch_with_ack(
            group, flt, msg.topic, try_deliver, msg.sender, self.node,
            extra=extra,
        )
        if member is None:
            self.hooks.run("message.dropped", (msg, "shared_no_available"))

    def _dispatch_shared_batch(
        self, group: str, flt: str, msgs: List[Message], res: DeliverResult
    ) -> None:
        """Batched $share dispatch (fanout pipeline): ONE ``pick_batch``
        call assigns a member per message — advancing round-robin/
        sticky/hash state exactly as per-message picks would — then all
        messages picked onto one member deliver through a single
        ``Session.deliver``.  Anything the batch cannot keep faithful
        (cluster candidates for this group, a member that nacks) falls
        back to the per-message ack-aware redispatch for the affected
        messages only."""
        if self.on_forward_shared is not None and any(
            isinstance(d, tuple) and d[0] == group and d[1] != self.node
            for d in self.router.routes_of(flt)
        ):
            # remote members exist: keep the two-level cluster pick
            for m in msgs:
                self._dispatch_shared(group, flt, m, res)
            return
        picks = self.shared.pick_batch(
            group, flt,
            [(m.topic, m.sender) for m in msgs], self.node,
        )
        by_member: Dict[Tuple[str, str], List[Message]] = {}
        for m, member in zip(msgs, picks):
            if member is None:
                self.hooks.run("message.dropped", (m, "shared_no_available"))
                continue
            bucket = by_member.get(member)
            if bucket is None:
                bucket = by_member[member] = []
            bucket.append(m)
        hooks = self.hooks
        for member, mlist in by_member.items():
            clientid, node = member
            sess = self.sessions.get(clientid) if node == self.node else None
            opts = None
            if sess is not None:
                opts = sess.subscriptions.get(T.make_share(group, flt))
                if opts is None and group == T.QUEUE_PREFIX:
                    opts = sess.subscriptions.get(f"{T.QUEUE_PREFIX}/{flt}")
            if sess is None or opts is None:
                # picked member can't take it (gone / unsubscribed /
                # remote): redispatch each message excluding it
                for m in mlist:
                    self._redispatch_shared(group, flt, m, res, member)
                continue
            effs = [self._effective(m, opts) for m in mlist]
            mu = sess.mutex
            if mu is None:
                sends, dropped = sess.deliver(effs)
            else:
                with mu:
                    sends, dropped = sess.deliver(effs)
            if sends:
                res.matched += len(sends)
                if self.metrics is not None:
                    self.metrics.inc("messages.delivered", len(sends))
                res.publishes.setdefault(clientid, []).extend(sends)
                if hooks.has("message.delivered"):
                    for p in sends:
                        hooks.run("message.delivered", (clientid, p.msg))
            if not dropped:
                continue
            dropped_ids = set()
            for d in dropped:
                dropped_ids.add(d.id)
                res.dropped.append((clientid, d))
                hooks.run("message.dropped", (d, "queue_full"))
            # a message of THIS batch whose delivery was dropped (queue
            # rejection, or eviction by a later message of the same
            # batch) was never sent → redispatch it to another member;
            # victims from earlier batches just count as drops, like the
            # per-message path
            for m, eff in zip(mlist, effs):
                if eff.id in dropped_ids:
                    self._redispatch_shared(group, flt, m, res, member)

    def _redispatch_shared(
        self,
        group: str,
        flt: str,
        msg: Message,
        res: DeliverResult,
        nacked: Tuple[str, str],
    ) -> None:
        """Ack-aware redispatch of one message after ``nacked`` refused
        it (the batch-path analog of dispatch_with_ack's retry loop)."""
        member = self.shared.dispatch_with_ack(
            group, flt, msg.topic,
            self._shared_try_deliver(group, flt, msg, res),
            msg.sender, self.node, exclude=(nacked,),
        )
        if member is None:
            self.hooks.run("message.dropped", (msg, "shared_no_available"))

    @staticmethod
    def _effective(msg: Message, opts: SubOpts) -> Message:
        """The per-subscription view of a routed message: QoS capped at
        the granted QoS, Retain-As-Published, Subscription-Identifier.
        Returns ``msg`` itself when no transform applies, so a fan-out
        shares one Message (and its payload) across subscribers."""
        eff = msg.with_qos(min(msg.qos, opts.qos))
        if not opts.rap:
            # Retain-As-Published off → clear retain flag on forward
            eff = eff.clone(retain=False) if eff.retain else eff
        if opts.subid is not None:
            # MQTT5 §3.3.4: echo the Subscription-Identifier with deliveries
            eff = eff.clone(
                properties={**eff.properties, "Subscription-Identifier": opts.subid}
            )
        return eff

    def _deliver_to(
        self, clientid: str, opts: SubOpts, msg: Message, res: DeliverResult
    ) -> bool:
        """Returns True iff *this* message was accepted (sent or queued) —
        a queue eviction of an older message is not a nack."""
        sess = self.sessions.get(clientid)
        if sess is None:
            return False
        eff = self._effective(msg, opts)
        mu = sess.mutex
        if mu is None:
            sends, dropped = sess.deliver([eff])
        else:
            # shard-owned session: exclude the owning shard loop's ack
            # handling for the duration of the window admission
            with mu:
                sends, dropped = sess.deliver([eff])
        if sends:
            res.matched += 1
            res.publishes.setdefault(clientid, []).extend(sends)
            if self.metrics is not None:
                self.metrics.inc("messages.delivered")
            self.hooks.run("message.delivered", (clientid, eff))
        for d in dropped:
            res.dropped.append((clientid, d))
            self.hooks.run("message.dropped", (d, "queue_full"))
        return all(d.id != eff.id for d in dropped)

    # ------------------------------------------------------------------
    # cluster ingress (receiving side of on_forward / on_forward_shared)
    # ------------------------------------------------------------------

    def dispatch_remote(self, flt: str, msg: Message) -> int:
        """Dispatch a delivery forwarded from another node to local
        subscribers of ``flt`` (emqx_broker:dispatch on the receiving
        node).  Returns the number of sessions that accepted."""
        res = DeliverResult()
        self._dispatch(flt, msg, res)
        for clientid, pubs in res.publishes.items():
            self.emit(clientid, pubs)
        return res.matched

    def dispatch_shared_remote(self, group: str, flt: str, msg: Message) -> bool:
        """Second level of cross-node shared dispatch: pick among LOCAL
        members only (the sender already chose this node)."""
        res = DeliverResult()

        def try_deliver(member: Tuple[str, str]) -> bool:
            clientid, node = member
            if node != self.node:
                return False
            sess = self.sessions.get(clientid)
            if sess is None:
                return False
            opts = sess.subscriptions.get(T.make_share(group, flt))
            if opts is None and group == T.QUEUE_PREFIX:
                opts = sess.subscriptions.get(f"{T.QUEUE_PREFIX}/{flt}")
            if opts is None:
                return False
            return self._deliver_to(clientid, opts, msg, res)

        member = self.shared.dispatch_with_ack(
            group, flt, msg.topic, try_deliver, msg.sender, self.node
        )
        for clientid, pubs in res.publishes.items():
            self.emit(clientid, pubs)
        if member is None:
            self.hooks.run("message.dropped", (msg, "shared_no_available"))
        return member is not None

    # ------------------------------------------------------------------
    # out-of-band delivery (retained replay, delayed publish, ...)
    # ------------------------------------------------------------------

    def deliver_direct(self, clientid: str, opts: SubOpts, msgs: List[Message]) -> None:
        """Deliver ``msgs`` to one session outside a publish fan-out and
        emit the resulting sends to the connection layer."""
        sess = self.sessions.get(clientid)
        if sess is None:
            return
        effs = [m.with_qos(min(m.qos, opts.qos)) for m in msgs]
        mu = sess.mutex
        if mu is None:
            sends, dropped = sess.deliver(effs)
        else:
            with mu:
                sends, dropped = sess.deliver(effs)
        for d in dropped:
            self.hooks.run("message.dropped", (d, "queue_full"))
        if sends:
            if self.metrics is not None:
                self.metrics.inc("messages.delivered", len(sends))
            for pub in sends:   # only actually-sent messages, not queued
                self.hooks.run("message.delivered", (clientid, pub.msg))
            self.emit(clientid, sends)

    OUTBOX_MAX = 1000  # per client; oldest dropped beyond this

    def emit(self, clientid: str, pubs: List[Publish]) -> None:
        if self.on_deliver is not None:
            self.on_deliver(clientid, pubs)
        else:
            self.outbox_put(clientid, pubs)

    def outbox_put(self, clientid: str, pubs: List[Publish]) -> None:
        """Capped outbox append — the single fallback path for deliveries
        with no live connection.  Overflow evicts oldest-first, counted
        in ``broker.outbox.dropped`` and logged once per client (a silent
        drop here cost a round of debugging — VERDICT lineage)."""
        box = self.outbox.setdefault(clientid, [])
        box.extend(pubs)
        over = len(box) - self.OUTBOX_MAX
        if over > 0:
            del box[:over]
            if self.metrics is not None:
                self.metrics.inc("broker.outbox.dropped", over)
            if clientid not in self._outbox_warned:
                self._outbox_warned.add(clientid)
                log.warning(
                    "outbox overflow for %r: dropped %d oldest "
                    "(cap %d; further drops counted in "
                    "broker.outbox.dropped, logged once per client)",
                    clientid, over, self.OUTBOX_MAX,
                )

    def take_outbox(self, clientid: str) -> List[Publish]:
        return self.outbox.pop(clientid, [])

    # ------------------------------------------------------------------

    def match_filters(self, topic: str) -> List[str]:
        """All filters (wildcard + exact) with local state matching topic —
        parity surface for the device mirror."""
        return [flt for flt, _ in self.router.match_routes(topic)]

    def stats(self) -> Dict[str, int]:
        return {
            "sessions.count": len(self.sessions),
            "subscriptions.count": sum(
                len(s.subscriptions) for s in self.sessions.values()
            ),
            "subscribers.count": sum(len(v) for v in self.subscribers.values()),
            "routes.count": self.router.route_count(),
            "shared_groups.count": len(self.shared.groups()),
        }
