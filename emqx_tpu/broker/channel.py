"""Channel: the per-connection MQTT protocol state machine.

Behavioral reference: ``apps/emqx/src/emqx_channel.erl`` (``handle_in/2``,
``handle_out/3``) [U] (SURVEY.md §2.1, §3.2-3.4): CONNECT/auth flow,
keepalive, will message, topic aliasing, QoS flows, takeover.

IO-free: :meth:`handle_in` consumes a parsed packet and returns a list of
actions for the connection layer::

    ("send", pkt)          serialize + write
    ("close", reason)      shut the transport (after flushing sends)

Routed deliveries enter through :meth:`handle_deliver`; timers call
:meth:`check_keepalive` / :meth:`retry_deliveries`.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .. import topic as T
from ..mqtt import frame as F
from ..mqtt import packet as P
from .broker import Broker
from .cm import ConnectionManager
from .message import Message, make_message
from .session import Publish, Session, SubOpts

__all__ = ["Channel"]

Action = Tuple[str, Any]

# v5 reason code → v3 CONNACK return code
_V3_CONNACK = {
    P.RC.SUCCESS: 0,
    P.RC.UNSPECIFIED_ERROR: 3,
    P.RC.BAD_USER_NAME_OR_PASSWORD: 4,
    P.RC.NOT_AUTHORIZED: 5,
    P.RC.SERVER_UNAVAILABLE: 3,
    P.RC.BANNED: 5,
}


class Channel:
    def __init__(
        self,
        broker: Broker,
        cm: ConnectionManager,
        conninfo: Optional[Dict[str, Any]] = None,
        max_topic_alias: int = 16,
        max_inflight: int = 32,
        server_keepalive: Optional[int] = None,
    ) -> None:
        self.broker = broker
        self.cm = cm
        self.conninfo = conninfo or {}
        self.state = "idle"          # idle → connected → disconnected
        self.proto_ver = 4
        self.clientid: Optional[str] = None
        self.username: Optional[str] = None
        self.session: Optional[Session] = None
        self.will: Optional[P.Will] = None
        self.keepalive = 0
        self.server_keepalive = server_keepalive
        self.max_topic_alias = max_topic_alias
        self.max_inflight = max_inflight
        self._aliases: Dict[int, str] = {}   # inbound alias → topic
        self.last_rx = time.time()
        # peeked-but-uncommitted retry batch (see retry_deliveries /
        # retry_commit): (entries, now) until the flush is confirmed
        self._retry_pending = None

    # ------------------------------------------------------------------

    def handle_in(self, pkt: Any) -> List[Action]:
        self.last_rx = time.time()
        if self.state == "idle":
            if pkt.type != P.CONNECT:
                return [("close", "protocol_error: packet before CONNECT")]
            return self._handle_connect(pkt)
        if pkt.type == P.CONNECT:
            return [("close", "protocol_error: duplicate CONNECT")]
        if self.state == "authenticating" and pkt.type != P.AUTH:
            # nothing but the AUTH exchange is legal mid-handshake
            return [("close", "protocol_error: packet during auth")]
        handler = {
            P.PUBLISH: self._handle_publish,
            P.PUBACK: self._handle_puback,
            P.PUBREC: self._handle_pubrec,
            P.PUBREL: self._handle_pubrel,
            P.PUBCOMP: self._handle_pubcomp,
            P.SUBSCRIBE: self._handle_subscribe,
            P.UNSUBSCRIBE: self._handle_unsubscribe,
            P.PINGREQ: lambda _: [("send", P.PingResp())],
            P.DISCONNECT: self._handle_disconnect,
            P.AUTH: self._handle_auth,
        }.get(pkt.type)
        if handler is None:
            return [("close", f"unexpected packet type {pkt.type}")]
        return handler(pkt)

    def deny_in(self, pkt: Any, rc: int) -> List[Action]:
        """Refuse an inbound packet with the protocol-correct response —
        the surface an async advisory stage (exhook) uses to veto a
        CONNECT / PUBLISH / SUBSCRIBE without entering normal handling."""
        if pkt.type == P.CONNECT:
            if self.state == "idle":  # duplicate CONNECT stays a close
                self.proto_ver = pkt.proto_ver
                return self._connack_error(rc)
            return [("close", "protocol_error: duplicate CONNECT")]
        if pkt.type == P.PUBLISH:
            return self._puback_for(pkt, rc)
        if pkt.type == P.SUBSCRIBE:
            rcs = [self._sub_rc(rc)] * len(pkt.topic_filters)
            return [("send", P.Suback(packet_id=pkt.packet_id, reason_codes=rcs))]
        return [("close", f"denied 0x{rc:02x}")]

    def _sub_rc(self, rc: int) -> int:
        """SUBACK code for this protocol version: 3.1.1 only knows
        granted-QoS 0/1/2 and 0x80 failure (spec §3.9.3)."""
        return 0x80 if rc >= 0x80 and self.proto_ver < 5 else rc

    def peek_topic(self, pkt: P.Publish) -> Optional[str]:
        """Resolve the effective topic of an inbound PUBLISH without
        mutating alias state — for advisory stages that run pre-handle_in."""
        alias = pkt.properties.get("Topic-Alias")
        if alias is not None and not pkt.topic:
            return self._aliases.get(alias)
        return pkt.topic or None

    # ------------------------------------------------------------------
    # CONNECT
    # ------------------------------------------------------------------

    def _handle_connect(self, pkt: P.Connect) -> List[Action]:
        self.proto_ver = pkt.proto_ver
        props: Dict[str, Any] = {}
        # clientid assignment (v5 §3.1.3.1)
        clientid = pkt.clientid
        if not clientid:
            if pkt.proto_ver < 5 and not pkt.clean_start:
                return self._connack_error(P.RC.UNSPECIFIED_ERROR)
            clientid = f"emqx_tpu_{uuid.uuid4().hex[:12]}"
            if pkt.proto_ver == 5:
                props["Assigned-Client-Identifier"] = clientid

        if self.broker.hooks.run("client.connect", (clientid, pkt)) == "stop":
            return self._connack_error(P.RC.NOT_AUTHORIZED)

        # MQTT 5 enhanced auth (§4.12): an Authentication-Method property
        # swaps the password check for a challenge/response AUTH exchange
        method = pkt.properties.get("Authentication-Method") \
            if pkt.proto_ver == 5 else None
        if method is not None:
            provider = self.broker.enhanced_auth.get(method)
            if provider is None:
                return self._connack_error(P.RC.BAD_AUTH_METHOD)
            # the ban/flapping checks ride this fold (the normal
            # client.authenticate fold never runs on this path)
            pre = self.broker.hooks.run_fold(
                "client.enhanced_authenticate",
                (clientid, pkt.username, None, self.conninfo),
                True,
            )
            if pre is not True:
                rc = pre if isinstance(pre, int) else P.RC.NOT_AUTHORIZED
                return self._connack_error(rc)
            verdict = provider.start(
                clientid, pkt.username,
                pkt.properties.get("Authentication-Data", b""),
            )
            if verdict[0] == "continue":
                self._auth_pending = (pkt, props, clientid, method,
                                      provider, verdict[2])
                self.state = "authenticating"
                return [("send", P.Auth(
                    reason_code=P.RC.CONTINUE_AUTHENTICATION,
                    properties={"Authentication-Method": method,
                                "Authentication-Data": verdict[1]},
                ))]
            if verdict[0] == "ok":
                props["Authentication-Method"] = method
                if verdict[3]:
                    props["Authentication-Data"] = verdict[3]
                self._record_enhanced(clientid, method, verdict)
                return self._complete_connect(pkt, props, clientid,
                                              username=verdict[1])
            return self._connack_error(P.RC.NOT_AUTHORIZED)

        ok = self.broker.hooks.run_fold(
            "client.authenticate",
            (clientid, pkt.username, pkt.password, self.conninfo),
            True,
        )
        if ok is not True:
            adm = self.broker.admission
            if adm is not None:
                # admission feature seam: auth-failure rate (a
                # credential-stuffing storm never reaches
                # client.connected, so the connect hook can't see it);
                # the peerhost rides along so host-keyed rows catch
                # rotating-clientid stuffing from one source
                adm.note_auth_failure(clientid,
                                      self.conninfo.get("peerhost"))
            rc = ok if isinstance(ok, int) else P.RC.NOT_AUTHORIZED
            return self._connack_error(rc)
        return self._complete_connect(pkt, props, clientid)

    def _record_enhanced(self, clientid: str, method: str,
                         verdict: Tuple) -> None:
        """Both completion paths (single- and multi-round) record the
        authenticated identity, incl. peerhost for ip-scoped authz."""
        self._auth_method = method
        self.broker.hooks.run(
            "client.enhanced_authenticated",
            (clientid, verdict[1], bool(verdict[2]),
             self.conninfo.get("peerhost")),
        )

    def _handle_auth(self, pkt: P.Auth) -> List[Action]:
        """AUTH from the client: the response/re-auth legs of enhanced
        auth (MQTT 5 §4.12; re-authentication §4.12.1)."""
        if (self.state == "connected"
                and pkt.reason_code == P.RC.REAUTHENTICATE):
            method = getattr(self, "_auth_method", None)
            if method is None or pkt.properties.get(
                "Authentication-Method", method
            ) != method:
                return [("send", P.Disconnect(
                    reason_code=P.RC.PROTOCOL_ERROR)),
                    ("close", "re-auth method mismatch")]
            provider = self.broker.enhanced_auth.get(method)
            if provider is None:  # deregistered while connected
                return [("send", P.Disconnect(
                    reason_code=P.RC.BAD_AUTH_METHOD)),
                    ("close", "auth method no longer available")]
            verdict = provider.start(
                self.clientid, self.username,
                pkt.properties.get("Authentication-Data", b""),
            )
            if verdict[0] == "continue":
                self._auth_pending = (None, {}, self.clientid, method,
                                      provider, verdict[2])
                return [("send", P.Auth(
                    reason_code=P.RC.CONTINUE_AUTHENTICATION,
                    properties={"Authentication-Method": method,
                                "Authentication-Data": verdict[1]},
                ))]
            if verdict[0] == "ok":
                self._record_enhanced(self.clientid, method, verdict)
                return [("send", P.Auth(
                    reason_code=P.RC.SUCCESS,
                    properties={"Authentication-Method": method,
                                "Authentication-Data": verdict[3] or b""},
                ))]
            return [("send", P.Disconnect(
                reason_code=P.RC.NOT_AUTHORIZED)),
                ("close", "re-auth denied")]
        pending = getattr(self, "_auth_pending", None)
        if pending is None or self.state not in ("authenticating",
                                                 "connected"):
            return [("close", "unexpected AUTH")]
        cpkt, props, clientid, method, provider, state = pending
        if pkt.properties.get("Authentication-Method", method) != method:
            return self._connack_error(P.RC.BAD_AUTH_METHOD)
        verdict = provider.continue_auth(
            state, pkt.properties.get("Authentication-Data", b""))
        if verdict[0] == "continue":  # multi-round methods
            self._auth_pending = (cpkt, props, clientid, method, provider,
                                  verdict[2])
            return [("send", P.Auth(
                reason_code=P.RC.CONTINUE_AUTHENTICATION,
                properties={"Authentication-Method": method,
                            "Authentication-Data": verdict[1]},
            ))]
        self._auth_pending = None
        if verdict[0] != "ok":
            if self.state == "connected":   # re-auth continue leg failed
                return [("send", P.Disconnect(
                    reason_code=P.RC.NOT_AUTHORIZED)),
                    ("close", "re-auth denied")]
            return self._connack_error(P.RC.NOT_AUTHORIZED)
        self._record_enhanced(clientid, method, verdict)
        if self.state == "connected":       # re-auth continue leg done
            return [("send", P.Auth(
                reason_code=P.RC.SUCCESS,
                properties={"Authentication-Method": method,
                            "Authentication-Data": verdict[3] or b""},
            ))]
        props["Authentication-Method"] = method
        if verdict[3]:
            props["Authentication-Data"] = verdict[3]
        return self._complete_connect(cpkt, props, clientid,
                                      username=verdict[1])

    def _complete_connect(self, pkt: P.Connect, props: Dict[str, Any],
                          clientid: str,
                          username: Optional[str] = None) -> List[Action]:
        self.clientid = clientid
        # enhanced auth carries the identity in the SASL exchange, not
        # the CONNECT username field
        self.username = username if username is not None else pkt.username
        self.will = pkt.will
        self.keepalive = pkt.keepalive
        if self.server_keepalive is not None and pkt.proto_ver == 5:
            self.keepalive = self.server_keepalive
            props["Server-Keep-Alive"] = self.server_keepalive

        recv_max = pkt.properties.get("Receive-Maximum", self.max_inflight)
        if recv_max == 0:  # MQTT5 §3.1.2.11: value 0 is a protocol error
            return self._connack_error(P.RC.PROTOCOL_ERROR)
        expiry = pkt.properties.get("Session-Expiry-Interval")
        kw = {"max_inflight": min(recv_max, self.max_inflight)}
        if expiry is not None:
            kw["expiry_interval"] = float(expiry)
        elif pkt.proto_ver == 5 or pkt.clean_start:
            # v5 default: session ends at disconnect (§3.1.2.11)
            kw["expiry_interval"] = 0.0
        # else: 3.1.1 clean_session=0 has no expiry on the wire — the
        # configured mqtt.session_expiry_interval default applies
        sess, present, old_chan = self.cm.open_session(
            clientid, pkt.clean_start, self, **kw
        )
        self.session = sess
        self.state = "connected"
        if pkt.proto_ver == 5:
            props["Topic-Alias-Maximum"] = self.max_topic_alias
            props["Shared-Subscription-Available"] = 1
            props["Wildcard-Subscription-Available"] = 1
            props["Subscription-Identifier-Available"] = 1
        actions: List[Action] = []
        if old_chan is not None and old_chan is not self:
            actions.append(("takeover", old_chan))
        actions.append(
            (
                "send",
                P.Connack(
                    session_present=present,
                    reason_code=P.RC.SUCCESS if self.proto_ver == 5 else 0,
                    properties=props,
                ),
            )
        )
        self.broker.usernames[clientid] = self.username
        self.broker.hooks.run("client.connected", (clientid, self.conninfo))
        if present:
            for pub in sess.resume_publishes():
                actions.append(("send", self._to_publish_pkt(pub)))
        return actions

    def _connack_error(self, rc: int) -> List[Action]:
        code = rc if self.proto_ver == 5 else _V3_CONNACK.get(rc, 3)
        return [
            ("send", P.Connack(session_present=False, reason_code=code)),
            ("close", f"connack error 0x{rc:02x}"),
        ]

    # ------------------------------------------------------------------
    # PUBLISH (inbound)
    # ------------------------------------------------------------------

    def _resolve_alias(self, pkt: P.Publish) -> Optional[str]:
        alias = pkt.properties.get("Topic-Alias")
        if alias is not None:
            if not 1 <= alias <= self.max_topic_alias:
                return None
            if pkt.topic:
                self._aliases[alias] = pkt.topic
                return pkt.topic
            return self._aliases.get(alias)
        return pkt.topic or None

    def _handle_publish(self, pkt: P.Publish) -> List[Action]:
        topic = self._resolve_alias(pkt)
        if topic is None:
            return [("close", "topic alias invalid")]
        adm = self.broker.admission
        if adm is not None:
            # admission feature seam: publish rate / bytes / topic fan,
            # noted BEFORE validity/authz so denied floods register too
            adm.note_publish(self.clientid, topic, len(pkt.payload))
        if not T.is_valid(topic, "name"):
            return self._puback_for(pkt, P.RC.TOPIC_NAME_INVALID)
        allowed = self.broker.hooks.run_fold(
            "client.authorize",
            (self.clientid, "publish", topic,
             {"qos": pkt.qos, "retain": pkt.retain}),
            True,
        )
        if allowed is not True:
            return self._puback_for(pkt, P.RC.NOT_AUTHORIZED)
        # an advisory stage (exhook message.publish) may re-route without
        # touching the wire topic / alias registration
        route_topic = getattr(pkt, "route_topic", None) or topic
        msg = make_message(
            self.clientid, route_topic, pkt.payload, qos=pkt.qos,
            retain=pkt.retain, properties=dict(pkt.properties),
        )
        if getattr(pkt, "allow_publish", True) is False:
            # vetoed upstream (exhook advisory): ack normally, never route
            msg = msg.clone(headers={**msg.headers, "allow_publish": False})
        # batched fanout pipeline (broker/fanout.py): the hot path offers
        # the message and acks immediately — PUBACK/PUBREC mean "broker
        # took responsibility", so acking before the batch flushes is
        # spec-faithful (NO_MATCHING_SUBSCRIBERS is a MAY, §3.4.2.1).
        # A refusal (disabled / low-rate bypass / overload) falls back to
        # the synchronous per-message path unchanged.
        fanout = self.broker.fanout
        if pkt.qos == 2:
            st = self.session.publish_qos2(pkt.packet_id, msg)
            if st == "full":
                return [("send", P.PubAck(P.PUBREC, pkt.packet_id, P.RC.QUOTA_EXCEEDED))]
            if st == "ok" and not (fanout is not None and fanout.offer(msg)):
                self.broker.publish(msg)
            return [("send", P.PubAck(P.PUBREC, pkt.packet_id))]
        if fanout is not None and fanout.offer(msg):
            if pkt.qos == 1:
                return [("send", P.PubAck(P.PUBACK, pkt.packet_id))]
            return []
        res = self.broker.publish(msg)
        if pkt.qos == 1:
            rc = (
                P.RC.NO_MATCHING_SUBSCRIBERS
                if res.no_subscribers and self.proto_ver == 5
                else P.RC.SUCCESS
            )
            return [("send", P.PubAck(P.PUBACK, pkt.packet_id, rc))]
        return []

    def handle_publish_run(
        self, run: P.PublishRun
    ) -> Tuple[bytes, List[Action], List[P.Publish]]:
        """Consume a contiguous same-QoS (1/2) PUBLISH run wholesale
        (the parser's publish-run fast path, the ingest mirror of
        :meth:`handle_ack_run`): the topic-validity check and the
        ``client.authorize`` fold run once per unique (topic, retain)
        in the run instead of once per packet, the QoS2 receiver
        transition runs per packet, and the PUBACK/PUBREC burst is
        built inline (4 bytes per rc-0 ack, no serializer pass).

        Returns ``(reply_bytes, actions, rest)``.  The caller emits
        ``reply_bytes``, runs ``actions``, then feeds ``rest`` (still
        unprocessed packets) through normal per-packet handling —
        together byte-for-byte what the per-packet path would emit, in
        order.  The fast loop only engages while every message is
        GUARANTEED to enter the fanout pipeline
        (:meth:`FanoutPipeline.will_accept`): pipeline deliveries
        happen after the whole burst, so grouping the acks preserves
        order.  Anything that would take the synchronous publish path
        (whose deliveries interleave with acks) lands in ``rest``
        before any side effect runs for it."""
        self.last_rx = time.time()
        broker = self.broker
        fanout = broker.fanout
        pkts = run.pkts
        if fanout is None or not fanout.will_accept(len(pkts)):
            return b"", [], pkts
        adm = broker.admission
        if adm is not None:
            # admission feature seam, batch form: one row lookup for
            # the whole publish run
            adm.note_publish_batch(self.clientid, pkts)
        sess = self.session
        v5 = self.proto_ver == 5
        run_fold = broker.hooks.run_fold
        # (topic, retain) → True | rc   (qos is constant across the run)
        verdicts: Dict[Tuple[str, bool], Any] = {}
        qos = run.qos
        out = bytearray()
        ack_head = P.PUBREC << 4 if qos == 2 else P.PUBACK << 4
        for i, pkt in enumerate(pkts):
            topic = self._resolve_alias(pkt)
            if topic is None:
                return bytes(out), [("close", "topic alias invalid")], []
            key = (topic, pkt.retain)
            rc = verdicts.get(key)
            if rc is None:
                if not T.is_valid(topic, "name"):
                    rc = P.RC.TOPIC_NAME_INVALID
                else:
                    allowed = run_fold(
                        "client.authorize",
                        (self.clientid, "publish", topic,
                         {"qos": qos, "retain": pkt.retain}),
                        True,
                    )
                    rc = True if allowed is True else P.RC.NOT_AUTHORIZED
                verdicts[key] = rc
            pid = pkt.packet_id
            if rc is not True:
                # refusal acks carry the reason code only on a v5 wire
                if v5:
                    out += F.serialize(P.PubAck(
                        P.PUBREC if qos == 2 else P.PUBACK, pid, rc),
                        ver=5)
                else:
                    out += bytes((ack_head, 2, pid >> 8, pid & 0xFF))
                continue
            msg = make_message(
                self.clientid, topic, pkt.payload, qos=qos,
                retain=pkt.retain, properties=dict(pkt.properties),
            )
            if qos == 2:
                st = sess.publish_qos2(pid, msg)
                if st == "full":
                    if v5:
                        out += F.serialize(P.PubAck(
                            P.PUBREC, pid, P.RC.QUOTA_EXCEEDED), ver=5)
                        continue
                    out += bytes((ack_head, 2, pid >> 8, pid & 0xFF))
                    continue
                if st == "ok" and not fanout.offer(msg):
                    # can't happen after will_accept (no await between
                    # check and offers), but never lose the message
                    broker.publish(msg)
                out += bytes((ack_head, 2, pid >> 8, pid & 0xFF))
                continue
            # QoS1
            if not fanout.offer(msg):  # same: guaranteed-accept guard
                broker.publish(msg)
            out += bytes((ack_head, 2, pid >> 8, pid & 0xFF))
        return bytes(out), [], []

    def _puback_for(self, pkt: P.Publish, rc: int) -> List[Action]:
        if pkt.qos == 1:
            return [("send", P.PubAck(P.PUBACK, pkt.packet_id, rc))]
        if pkt.qos == 2:
            return [("send", P.PubAck(P.PUBREC, pkt.packet_id, rc))]
        if rc == P.RC.NOT_AUTHORIZED and self.proto_ver == 5:
            return [("send", P.Disconnect(reason_code=rc)), ("close", "not authorized")]
        return []

    # ------------------------------------------------------------------
    # QoS acks (outbound flow)
    # ------------------------------------------------------------------

    def _handle_puback(self, pkt: P.PubAck) -> List[Action]:
        msg, more = self.session.puback(pkt.packet_id)
        if msg is not None:
            self.broker.hooks.run("message.acked", (self.clientid, msg))
        return [("send", self._to_publish_pkt(p)) for p in more]

    def handle_puback_batch(self, pkts: List[P.PubAck]) -> List[Publish]:
        """A run of consecutive PUBACKs from one TCP read (the batched
        datapath calls this instead of per-packet :meth:`handle_in`):
        one window-refill cycle covers the whole burst.  Returns the
        refill publishes for the caller's bulk send path — the same
        packets per-ack handling would emit, in the same order."""
        self.last_rx = time.time()
        acked, more = self.session.puback_batch(
            [pkt.packet_id for pkt in pkts])
        if acked:
            hooks = self.broker.hooks
            if hooks.has("message.acked"):
                for msg in acked:
                    hooks.run("message.acked", (self.clientid, msg))
        return more

    # one reply head per inbound ack type that answers with an ack
    _ACK_REPLY_HEAD = {
        P.PUBREC: ((P.PUBREL << 4) | 2, P.PUBREL),
        P.PUBREL: (P.PUBCOMP << 4, P.PUBCOMP),
    }

    def handle_ack_run(self, run: P.AckRun) -> Tuple[bytes, List[Publish]]:
        """Consume a packed same-type ack run wholesale (the parser's
        ack-run fast path): one batched session transition covers the
        whole burst.  Returns ``(reply_bytes, refill)`` — the exact ack
        frames the per-packet path would have sent back, pre-serialized
        in order, plus the window-refill publishes for the caller's
        bulk send path."""
        self.last_rx = time.time()
        sess = self.session
        t = run.type
        if t == P.PUBACK:
            acked, more = sess.puback_batch(run.pids)
            if acked:
                hooks = self.broker.hooks
                if hooks.has("message.acked"):
                    for msg in acked:
                        hooks.run("message.acked", (self.clientid, msg))
            return b"", more
        if t == P.PUBCOMP:
            _known, more = sess.pubcomp_batch(run.pids)
            return b"", more
        if t == P.PUBREC:
            oks = sess.pubrec_batch(run.pids)
        else:  # PUBREL (inbound QoS2 release)
            oks = sess.pubrel_received_batch(run.pids)
        head, rtype = self._ACK_REPLY_HEAD[t]
        out = bytearray()
        v5 = self.proto_ver == 5
        for pid, ok in zip(run.pids, oks):
            if ok or not v5:
                # 4-byte pid-only ack: rc 0 (or a v3/4 peer, where the
                # reason code never hits the wire) — built inline, no
                # serializer pass
                out += bytes((head, 2, pid >> 8, pid & 0xFF))
            else:
                out += F.serialize(
                    P.PubAck(rtype, pid, P.RC.PACKET_ID_NOT_FOUND), ver=5)
        return bytes(out), []

    def _handle_pubrec(self, pkt: P.PubAck) -> List[Action]:
        if self.session.pubrec(pkt.packet_id):
            return [("send", P.PubAck(P.PUBREL, pkt.packet_id))]
        return [("send", P.PubAck(P.PUBREL, pkt.packet_id, P.RC.PACKET_ID_NOT_FOUND))]

    def _handle_pubrel(self, pkt: P.PubAck) -> List[Action]:
        if self.session.pubrel_received(pkt.packet_id):
            return [("send", P.PubAck(P.PUBCOMP, pkt.packet_id))]
        return [("send", P.PubAck(P.PUBCOMP, pkt.packet_id, P.RC.PACKET_ID_NOT_FOUND))]

    def _handle_pubcomp(self, pkt: P.PubAck) -> List[Action]:
        known, more = self.session.pubcomp(pkt.packet_id)
        return [("send", self._to_publish_pkt(p)) for p in more]

    # ------------------------------------------------------------------
    # SUBSCRIBE / UNSUBSCRIBE
    # ------------------------------------------------------------------

    def _handle_subscribe(self, pkt: P.Subscribe) -> List[Action]:
        if self.broker.hooks.run("client.subscribe", (self.clientid, pkt)) == "stop":
            rcs = [self._sub_rc(P.RC.NOT_AUTHORIZED)] * len(pkt.topic_filters)
            return [("send", P.Suback(packet_id=pkt.packet_id, reason_codes=rcs))]
        subid = pkt.properties.get("Subscription-Identifier")
        denied = getattr(pkt, "denied_filters", ())
        rcs: List[int] = []
        for i, (flt, o) in enumerate(pkt.topic_filters):
            if i in denied:  # vetoed upstream (exhook advisory)
                rcs.append(self._sub_rc(P.RC.NOT_AUTHORIZED))
                continue
            if not T.is_valid(flt, "filter"):
                rcs.append(self._sub_rc(P.RC.TOPIC_FILTER_INVALID))
                continue
            allowed = self.broker.hooks.run_fold(
                "client.authorize",
                (self.clientid, "subscribe", flt, {"qos": o.get("qos", 0)}),
                True,
            )
            if allowed is not True:
                rcs.append(self._sub_rc(P.RC.NOT_AUTHORIZED))
                continue
            opts = SubOpts(
                qos=o.get("qos", 0), nl=bool(o.get("nl", 0)),
                rap=bool(o.get("rap", 0)), rh=o.get("rh", 0), subid=subid,
            )
            self.broker.subscribe(self.clientid, flt, opts)
            rcs.append(opts.qos)  # granted qos
        return [("send", P.Suback(packet_id=pkt.packet_id, reason_codes=rcs))]

    def _handle_unsubscribe(self, pkt: P.Unsubscribe) -> List[Action]:
        # hooks may rewrite pkt.topic_filters in place (topic-rewrite rules)
        self.broker.hooks.run("client.unsubscribe", (self.clientid, pkt))
        rcs = []
        for flt in pkt.topic_filters:
            ok = self.broker.unsubscribe(self.clientid, flt)
            rcs.append(P.RC.SUCCESS if ok else 0x11)  # no-subscription-existed
        return [("send", P.Unsuback(packet_id=pkt.packet_id, reason_codes=rcs))]

    # ------------------------------------------------------------------
    # DISCONNECT / close / will
    # ------------------------------------------------------------------

    def _handle_disconnect(self, pkt: P.Disconnect) -> List[Action]:
        # MQTT5 §3.1.2.5/§3.14: only a normal disconnect (0x00) deletes the
        # will; 0x04 and every other non-zero reason publish it on close.
        if pkt.reason_code == 0:
            self.will = None
        expiry = pkt.properties.get("Session-Expiry-Interval")
        if expiry is not None and self.session is not None:
            self.session.expiry_interval = float(expiry)
        self.state = "disconnected"
        return [("close", "client disconnect")]

    def handle_close(self, reason: str = "closed") -> None:
        """Transport gone: publish will (if any), unregister, run hooks."""
        if self.state == "connected":
            self.state = "disconnected"
        if self.will is not None:
            wmsg = make_message(
                self.clientid, self.will.topic, self.will.payload,
                qos=self.will.qos, retain=self.will.retain,
                properties=dict(self.will.properties),
            )
            self.broker.publish(wmsg)
            self.will = None
        if self.clientid is not None:
            # Only the owning channel may tear down broker-side state; a
            # displaced channel closing late must not destroy its
            # successor's live session.
            owner = self.cm.lookup_channel(self.clientid) is self
            self.cm.unregister_channel(self.clientid, self)
            if owner:
                self.broker.close_session(self.clientid)
                self.broker.hooks.run(
                    "client.disconnected", (self.clientid, reason)
                )

    def handle_takeover(self) -> List[Action]:
        """This channel is displaced by a newer CONNECT of the same id."""
        self.will = None  # takeover does not fire the will
        self.state = "disconnected"
        out: List[Action] = []
        if self.proto_ver == 5:
            out.append(("send", P.Disconnect(reason_code=P.RC.SESSION_TAKEN_OVER)))
        out.append(("close", "session taken over"))
        return out

    # ------------------------------------------------------------------
    # outbound deliveries & timers
    # ------------------------------------------------------------------

    def handle_deliver(self, pubs: List[Publish]) -> List[Action]:
        return [("send", self._to_publish_pkt(p)) for p in pubs]

    # MQTT5 §3.3.2.3: publish properties forwarded to subscribers
    # (hoisted — this filter runs once per delivery/retry/resume leg,
    # the per-leg hot path of the acknowledged-delivery stack)
    _FWD_PROPS = frozenset((
        "Payload-Format-Indicator", "Message-Expiry-Interval",
        "Content-Type", "Response-Topic", "Correlation-Data",
        "User-Property", "Subscription-Identifier",
    ))

    def _to_publish_pkt(self, p: Publish) -> P.Publish:
        m = p.msg
        props: Dict[str, Any] = {}
        if self.proto_ver == 5 and m.properties:
            fwd = self._FWD_PROPS
            props = {k: v for k, v in m.properties.items() if k in fwd}
        return P.Publish(
            dup=m.dup, qos=m.qos, retain=m.retain, topic=m.topic,
            packet_id=p.pid, payload=m.payload, properties=props,
        )

    def check_keepalive(self, now: Optional[float] = None) -> List[Action]:
        """MQTT §3.1.2.10: close after 1.5 × keepalive of silence."""
        if self.state != "connected" or self.keepalive == 0:
            return []
        now = now if now is not None else time.time()
        if now - self.last_rx > self.keepalive * 1.5:
            return [("close", "keepalive timeout")]
        return []

    def retry_deliveries(self, now: Optional[float] = None) -> List[Action]:
        """Resend actions for due inflight entries.  Peek-only: the DUP
        clone / age-clock commit is deferred until the connection layer
        confirms the flush with :meth:`retry_commit` — a dead transport
        must not burn clones (and silently swallow a retry interval)
        for resends that never left the process."""
        if self.session is None:
            return []
        entries = self.session.retry_peek(now)
        self._retry_pending = (entries, now)
        out: List[Action] = []
        for pid, kind, msg in entries:
            if kind == "publish":
                pkt = self._to_publish_pkt(Publish(pid, msg))
                pkt.dup = True
                out.append(("send", pkt))
            else:
                out.append(("send", P.PubAck(P.PUBREL, pid)))
        return out

    def retry_wire_batch(self, now: Optional[float] = None) -> List[bytes]:
        """Batched-resend path (``broker.fanout.enable`` datapaths):
        the same due entries as :meth:`retry_deliveries`, rendered as
        wire bytes through the PR-2 QoS1/2 template cache — patch the
        2 pid bytes and set the DUP bit instead of a full serializer
        pass per resend — for ONE coalesced flush per tick.  Commit
        rides :meth:`retry_commit` exactly like the action path."""
        sess = self.session
        if sess is None:
            return []
        entries = sess.retry_peek(now)
        self._retry_pending = (entries, now)
        if not entries:
            return []
        out: List[bytes] = []
        ver = self.proto_ver
        pubrel_head = (P.PUBREL << 4) | 2
        for pid, kind, msg in entries:
            if kind != "publish":
                # PUBREL resend: 4-byte pid-only shape in any version
                out.append(bytes((pubrel_head, 2, pid >> 8, pid & 0xFF)))
                continue
            data = None
            cache = msg.__dict__.get("_wire1")
            ent = cache.get(ver) if cache is not None else None
            if ent is not None:
                tpl, off = ent
                buf = bytearray(tpl)
                buf[0] |= 0x08           # DUP bit (fixed header, §3.3.1.1)
                buf[off] = pid >> 8
                buf[off + 1] = pid & 0xFF
                data = bytes(buf)
            else:
                pkt = self._to_publish_pkt(Publish(pid, msg))
                pkt.dup = True
                data = F.serialize(pkt, ver=ver)
            out.append(data)
        return out

    def retry_commit(self) -> None:
        """Commit the last peeked retry batch (clone/touch) — called by
        the connection layer once the resend flush went through."""
        pending = getattr(self, "_retry_pending", None)
        self._retry_pending = None
        if pending and self.session is not None:
            entries, now = pending
            self.session.retry_commit(entries, now)
