"""Overload protection: shed load when the control plane runs hot.

Behavioral reference: ``emqx_olp.erl`` / ``emqx_vm_mon`` / ``emqx_os_mon``
[U] (SURVEY.md §2.1): scheduler-usage-based shedding of new connections
and low-priority work, with alarms on sustained overload.  Our signals:
event-loop lag (sampled by :class:`LoopLagProbe`, the ``emqx_vm_mon``
scheduler-usage analog), pending publish-queue depth, and match-kernel
backlog — pushed in via :meth:`Olp.report`.

The lag probe closes the PR-3 gap: the fanout drain reports queue depth,
but a CPU-saturated loop with an *empty* queue (every cycle spent inside
connection handlers) never grew a queue to observe.  Sleep drift is the
direct measurement — ``asyncio.sleep(t)`` wakes ``t + lag`` after it was
scheduled, where ``lag`` is exactly how far behind the loop is running.

**Brownout ladder** (the serve-plane extension): sustained overload
escalates through three stages instead of flipping one binary, so the
match serve plane degrades *latency-first* — stage 1 shrinks the serve
batch caps (smaller kernels, lower fill latency), stage 2 sheds QoS0
prefetches to the CPU trie (the device budget goes to acknowledged
traffic), stage 3 is full CPU serve.  :meth:`Olp.brownout_level` derives
the stage from how long the current overload episode has lasted: level 1
on entry, +1 per ``escalate`` seconds hot (default: the cooloff window),
capped at 3.  De-escalation rides the existing cooloff — once reports go
quiet the episode ends and the level drops straight to 0.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Callable, Optional

from ..observe.alarm import Alarms

__all__ = ["Olp", "LoopLagProbe"]


class Olp:
    def __init__(
        self,
        alarms: Optional[Alarms] = None,
        max_loop_lag: float = 0.5,
        max_queue_depth: int = 100_000,
        cooloff: float = 5.0,
        escalate: Optional[float] = None,
    ) -> None:
        self.alarms = alarms
        self.max_loop_lag = max_loop_lag
        self.max_queue_depth = max_queue_depth
        self.cooloff = cooloff
        # seconds of sustained overload per brownout stage; defaults to
        # the cooloff window so the ladder and recovery share one clock
        self.escalate = escalate if escalate is not None else cooloff
        self._overloaded_at: Optional[float] = None
        self._hot_since: Optional[float] = None  # current episode start
        self.shed_count = 0

    def report(
        self, loop_lag: float = 0.0, queue_depth: int = 0,
        now: Optional[float] = None,
    ) -> None:
        now = now if now is not None else time.time()
        hot = loop_lag > self.max_loop_lag or queue_depth > self.max_queue_depth
        if hot:
            if self._hot_since is None or (
                self._overloaded_at is not None
                and now - self._overloaded_at > self.cooloff
            ):
                # first hot report, or overload resuming after a silent
                # gap longer than the cooloff: a NEW episode — the ladder
                # must not inherit the old episode's escalation
                self._hot_since = now
            self._overloaded_at = now
            if self.alarms is not None:
                self.alarms.activate(
                    "overload",
                    {"loop_lag": loop_lag, "queue_depth": queue_depth},
                    "control plane overloaded",
                )
        elif (
            self._overloaded_at is not None
            and now - self._overloaded_at > self.cooloff
        ):
            self._overloaded_at = None
            self._hot_since = None
            if self.alarms is not None:
                self.alarms.deactivate("overload")

    def overloaded(self, now: Optional[float] = None) -> bool:
        if self._overloaded_at is None:
            return False
        now = now if now is not None else time.time()
        return now - self._overloaded_at <= self.cooloff

    def brownout_level(self, now: Optional[float] = None) -> int:
        """Staged-brownout stage (0–3) for the serve plane.

        0 = healthy; 1 on overload entry (shrink serve batch caps); one
        more stage per ``escalate`` seconds of sustained overload —
        2 sheds QoS0 prefetches to CPU, 3 is full CPU serve.  Returns to
        0 as soon as :meth:`overloaded` clears (cooloff elapsed)."""
        now = now if now is not None else time.time()
        if not self.overloaded(now) or self._hot_since is None:
            return 0
        if self.escalate <= 0:
            return 3
        return 1 + min(2, int((now - self._hot_since) / self.escalate))

    def should_shed_connect(self, now: Optional[float] = None) -> bool:
        """New CONNECTs are the first thing shed under overload."""
        if self.overloaded(now):
            self.shed_count += 1
            return True
        return False


class LoopLagProbe:
    """Sleep-drift sampler feeding :meth:`Olp.report`.

    Each tick schedules ``asyncio.sleep(interval)`` and measures how
    late it woke; an EWMA (``alpha``) smooths scheduler jitter so one
    GC pause doesn't trip overload, while sustained saturation does.
    Runs as a supervised child (``olp.lag_probe``); the clock and sleep
    are injectable so tests drive it deterministically.
    """

    def __init__(
        self,
        olp: Olp,
        metrics: Any = None,
        interval: float = 0.1,
        alpha: float = 0.3,
        clock: Optional[Callable[[], float]] = None,
        sleep: Optional[Callable[[float], Any]] = None,
    ) -> None:
        self.olp = olp
        self.metrics = metrics
        self.interval = interval
        self.alpha = alpha
        self._clock = clock if clock is not None else time.monotonic
        self._sleep = sleep if sleep is not None else asyncio.sleep
        self.lag = 0.0       # EWMA-smoothed drift (seconds)
        self.last_raw = 0.0  # most recent un-smoothed sample
        self.samples = 0

    def observe(self, raw_lag: float) -> float:
        """Fold one drift sample in and report it; returns the EWMA.
        Split out from :meth:`run` so tests feed samples directly."""
        raw_lag = max(0.0, raw_lag)
        self.last_raw = raw_lag
        self.samples += 1
        self.lag = (raw_lag if self.samples == 1
                    else self.lag * (1.0 - self.alpha)
                    + raw_lag * self.alpha)
        self.olp.report(loop_lag=self.lag)
        if self.metrics is not None:
            self.metrics.set("broker.olp.loop_lag_us",
                             int(self.lag * 1e6))
        return self.lag

    async def run(self) -> None:
        """The supervised sampler loop."""
        while True:
            t0 = self._clock()
            await self._sleep(self.interval)
            self.observe(self._clock() - t0 - self.interval)

    def info(self) -> dict:
        return {
            "lag_ms": round(self.lag * 1e3, 3),
            "last_raw_ms": round(self.last_raw * 1e3, 3),
            "samples": self.samples,
        }
