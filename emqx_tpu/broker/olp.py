"""Overload protection: shed load when the control plane runs hot.

Behavioral reference: ``emqx_olp.erl`` / ``emqx_vm_mon`` / ``emqx_os_mon``
[U] (SURVEY.md §2.1): scheduler-usage-based shedding of new connections
and low-priority work, with alarms on sustained overload.  Our signals:
event-loop lag (reported by the serving loop), pending publish-queue
depth, and match-kernel backlog — pushed in via :meth:`report`.
"""

from __future__ import annotations

import time
from typing import Optional

from ..observe.alarm import Alarms

__all__ = ["Olp"]


class Olp:
    def __init__(
        self,
        alarms: Optional[Alarms] = None,
        max_loop_lag: float = 0.5,
        max_queue_depth: int = 100_000,
        cooloff: float = 5.0,
    ) -> None:
        self.alarms = alarms
        self.max_loop_lag = max_loop_lag
        self.max_queue_depth = max_queue_depth
        self.cooloff = cooloff
        self._overloaded_at: Optional[float] = None
        self.shed_count = 0

    def report(
        self, loop_lag: float = 0.0, queue_depth: int = 0,
        now: Optional[float] = None,
    ) -> None:
        now = now if now is not None else time.time()
        hot = loop_lag > self.max_loop_lag or queue_depth > self.max_queue_depth
        if hot:
            self._overloaded_at = now
            if self.alarms is not None:
                self.alarms.activate(
                    "overload",
                    {"loop_lag": loop_lag, "queue_depth": queue_depth},
                    "control plane overloaded",
                )
        elif (
            self._overloaded_at is not None
            and now - self._overloaded_at > self.cooloff
        ):
            self._overloaded_at = None
            if self.alarms is not None:
                self.alarms.deactivate("overload")

    def overloaded(self, now: Optional[float] = None) -> bool:
        if self._overloaded_at is None:
            return False
        now = now if now is not None else time.time()
        return now - self._overloaded_at <= self.cooloff

    def should_shed_connect(self, now: Optional[float] = None) -> bool:
        """New CONNECTs are the first thing shed under overload."""
        if self.overloaded(now):
            self.shed_count += 1
            return True
        return False
