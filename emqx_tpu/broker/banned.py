"""Banned-clients table.

Behavioral reference: ``apps/emqx/src/emqx_banned.erl`` [U] (SURVEY.md
§2.1): bans keyed by clientid, username or peerhost with an `until`
expiry; checked during CONNECT.  Attached as a high-priority
``client.authenticate`` hook returning the BANNED reason code.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..mqtt.packet import RC
from .broker import Broker
from .hooks import STOP

__all__ = ["Banned", "BanEntry"]

WHO_KINDS = ("clientid", "username", "peerhost")


@dataclass
class BanEntry:
    kind: str           # clientid | username | peerhost
    who: str
    by: str = "mgmt"
    reason: str = ""
    at: float = 0.0
    until: Optional[float] = None   # None = permanent

    def expired(self, now: float) -> bool:
        return self.until is not None and now >= self.until


class Banned:
    def __init__(self) -> None:
        self._tab: Dict[Tuple[str, str], BanEntry] = {}

    def add(
        self, kind: str, who: str, duration: Optional[float] = None,
        by: str = "mgmt", reason: str = "", now: Optional[float] = None,
    ) -> BanEntry:
        """``now`` lets clock-injected callers (flapping, admission
        tests) keep the expiry on their deterministic clock."""
        if kind not in WHO_KINDS:
            raise ValueError(f"bad ban kind {kind!r}")
        now = now if now is not None else time.time()
        e = BanEntry(
            kind, who, by, reason, now,
            None if duration is None else now + duration,
        )
        self._tab[(kind, who)] = e
        return e

    def delete(self, kind: str, who: str) -> bool:
        return self._tab.pop((kind, who), None) is not None

    def check(
        self,
        clientid: Optional[str] = None,
        username: Optional[str] = None,
        peerhost: Optional[str] = None,
        now: Optional[float] = None,
    ) -> bool:
        """True if any identity dimension is banned (and not expired)."""
        now = now if now is not None else time.time()
        for kind, who in (
            ("clientid", clientid), ("username", username), ("peerhost", peerhost)
        ):
            if who is None:
                continue
            e = self._tab.get((kind, who))
            if e is not None:
                if e.expired(now):
                    del self._tab[(kind, who)]
                else:
                    return True
        return False

    def list(self) -> List[BanEntry]:
        now = time.time()
        return [e for e in self._tab.values() if not e.expired(now)]

    def clean_expired(self, now: Optional[float] = None) -> int:
        now = now if now is not None else time.time()
        stale = [k for k, e in self._tab.items() if e.expired(now)]
        for k in stale:
            del self._tab[k]
        return len(stale)

    def attach(self, broker: Broker) -> "Banned":
        def on_auth(clientid, username, password, conninfo, acc):
            peer = conninfo.get("peerhost") if isinstance(conninfo, dict) else None
            if self.check(clientid, username, peer):
                return (STOP, RC.BANNED)
            return acc

        broker.hooks.add("client.authenticate", on_auth, priority=1000,
                         name="banned.check")
        # enhanced-auth CONNECTs skip the authn-chain fold; the ban
        # check must still run on their dedicated pre-auth fold
        broker.hooks.add("client.enhanced_authenticate", on_auth,
                         priority=1000, name="banned.check_enhanced")
        return self
