"""Flapping detection → temporary ban.

Behavioral reference: ``apps/emqx/src/emqx_flapping.erl`` [U] (SURVEY.md
§2.1): count a client's disconnects inside a sliding window; crossing
``max_count`` bans the clientid for ``ban_time`` via the banned table.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, Optional

from .banned import Banned
from .broker import Broker

__all__ = ["Flapping"]


class Flapping:
    def __init__(
        self,
        banned: Banned,
        max_count: int = 15,
        window_time: float = 60.0,
        ban_time: float = 300.0,
        enable: bool = True,
    ) -> None:
        self.banned = banned
        self.max_count = max_count
        self.window_time = window_time
        self.ban_time = ban_time
        self.enable = enable
        self._events: Dict[str, Deque[float]] = {}
        self.detected = 0

    def record_disconnect(self, clientid: str, now: Optional[float] = None) -> bool:
        """Returns True if this event tripped the detector (ban issued)."""
        if not self.enable:
            return False
        now = now if now is not None else time.time()
        self._gc_tick = getattr(self, "_gc_tick", 0) + 1
        if self._gc_tick % 256 == 0:
            # amortized sweep: drop clientids whose whole window elapsed,
            # else the table grows with every clientid ever seen
            stale = [
                cid for cid, evs in self._events.items()
                if not evs or now - evs[-1] > self.window_time
            ]
            for cid in stale:
                del self._events[cid]
        q = self._events.setdefault(clientid, deque())
        q.append(now)
        while q and now - q[0] > self.window_time:
            q.popleft()
        if len(q) >= self.max_count:
            self.banned.add(
                "clientid", clientid, duration=self.ban_time,
                by="flapping", reason="flapping detected",
            )
            self.detected += 1
            del self._events[clientid]
            return True
        return False

    def attach(self, broker: Broker) -> "Flapping":
        broker.hooks.add(
            "client.disconnected",
            lambda clientid, reason: self.record_disconnect(clientid),
            name="flapping.detect",
        )
        return self
