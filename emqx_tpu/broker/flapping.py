"""Flapping detection → temporary ban.

Behavioral reference: ``apps/emqx/src/emqx_flapping.erl`` [U] (SURVEY.md
§2.1): count a client's disconnects inside a sliding window; crossing
``max_count`` bans the clientid for ``ban_time`` via the banned table.

The clock is injectable (the ``supervise.py`` discipline): tests drive
window slides, ban expiry and idle sweeps with a fake clock instead of
sleeping, and the ban handed to :class:`Banned` carries the SAME ``now``
so the whole decision chain is deterministic.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Dict, Optional

from .banned import Banned
from .broker import Broker

__all__ = ["Flapping"]


class Flapping:
    def __init__(
        self,
        banned: Banned,
        max_count: int = 15,
        window_time: float = 60.0,
        ban_time: float = 300.0,
        enable: bool = True,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.banned = banned
        self.max_count = max_count
        self.window_time = window_time
        self.ban_time = ban_time
        self.enable = enable
        self._clock = clock if clock is not None else time.time
        self._events: Dict[str, Deque[float]] = {}
        self._gc_tick = 0
        self.detected = 0

    def record_disconnect(self, clientid: str, now: Optional[float] = None) -> bool:
        """Returns True if this event tripped the detector (ban issued)."""
        if not self.enable:
            return False
        now = now if now is not None else self._clock()
        self._gc_tick += 1
        if self._gc_tick % 256 == 0:
            # amortized sweep: drop clientids whose whole window elapsed,
            # else the table grows with every clientid ever seen
            self.sweep(now)
        q = self._events.setdefault(clientid, deque())
        q.append(now)
        while q and now - q[0] > self.window_time:
            q.popleft()
        if len(q) >= self.max_count:
            self.banned.add(
                "clientid", clientid, duration=self.ban_time,
                by="flapping", reason="flapping detected", now=now,
            )
            self.detected += 1
            del self._events[clientid]
            return True
        return False

    def sweep(self, now: Optional[float] = None) -> int:
        """Drop clientids whose whole window elapsed.  Runs amortized
        from :meth:`record_disconnect` AND from node housekeeping — the
        amortized path alone only fires while disconnects keep coming,
        so a churn burst followed by silence would pin its table
        forever (the per-client-state growth audit)."""
        now = now if now is not None else self._clock()
        stale = [
            cid for cid, evs in self._events.items()
            if not evs or now - evs[-1] > self.window_time
        ]
        for cid in stale:
            del self._events[cid]
        return len(stale)

    def tracked(self) -> int:
        return len(self._events)

    def attach(self, broker: Broker) -> "Flapping":
        broker.hooks.add(
            "client.disconnected",
            lambda clientid, reason: self.record_disconnect(clientid),
            name="flapping.detect",
        )
        return self
