"""Inflight window: unacked QoS1/2 deliveries awaiting client response.

Behavioral reference: ``apps/emqx/src/emqx_inflight.erl`` [U] (SURVEY.md
§2.1): bounded insertion-ordered map packet-id → record, with
retry/expiry iteration in insertion order.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["Inflight", "InflightFullError"]


class InflightFullError(Exception):
    pass


class Inflight:
    def __init__(self, max_size: int = 32) -> None:
        self.max_size = max_size
        self._d: Dict[int, Tuple[float, Any]] = {}  # pid -> (ts, value)

    def __len__(self) -> int:
        return len(self._d)

    def is_full(self) -> bool:
        return self.max_size > 0 and len(self._d) >= self.max_size

    def is_empty(self) -> bool:
        return not self._d

    def contains(self, pid: int) -> bool:
        return pid in self._d

    def insert(self, pid: int, value: Any) -> None:
        if self.is_full():
            raise InflightFullError(f"inflight window full ({self.max_size})")
        if pid in self._d:
            raise KeyError(f"packet id {pid} already inflight")
        self._d[pid] = (time.time(), value)

    def update(self, pid: int, value: Any) -> None:
        if pid not in self._d:
            raise KeyError(pid)
        ts, _ = self._d[pid]
        self._d[pid] = (ts, value)

    def touch(self, pid: int, now: Optional[float] = None) -> None:
        """Reset the age clock (after a retransmission)."""
        if pid not in self._d:
            raise KeyError(pid)
        _, v = self._d[pid]
        self._d[pid] = (now if now is not None else time.time(), v)

    def delete(self, pid: int) -> Optional[Any]:
        item = self._d.pop(pid, None)
        return item[1] if item is not None else None

    def lookup(self, pid: int) -> Optional[Any]:
        item = self._d.get(pid)
        return item[1] if item is not None else None

    def items(self) -> Iterator[Tuple[int, float, Any]]:
        """(pid, inserted_at, value) in insertion order."""
        for pid, (ts, v) in self._d.items():
            yield pid, ts, v

    def older_than(self, age_s: float, now: Optional[float] = None) -> List[int]:
        now = now if now is not None else time.time()
        return [pid for pid, (ts, _) in self._d.items() if now - ts >= age_s]
