"""Inflight window: unacked QoS1/2 deliveries awaiting client response.

Behavioral reference: ``apps/emqx/src/emqx_inflight.erl`` [U] (SURVEY.md
§2.1): bounded insertion-ordered map packet-id → record, with
retry/expiry iteration in insertion order.

The retry scan is incremental: entries also ride an expiry-ordered lazy
heap, so :meth:`older_than` pops only the entries actually due instead
of walking the full window every timer tick (with thousands of sessions
× a 1 s retry tick, the full-window walk was pure per-tick overhead —
the acknowledged-delivery analog of the per-message publish walk the
fanout pipeline amortized).  Heap entries are invalidated lazily on
``delete``/``touch``; the map stays the source of truth.
"""

from __future__ import annotations

import heapq
import time
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

from .. import faultinject as _fi

__all__ = ["Inflight", "InflightFullError"]


class InflightFullError(Exception):
    pass


class Inflight:
    def __init__(self, max_size: int = 32) -> None:
        self.max_size = max_size
        self._d: Dict[int, Tuple[float, Any]] = {}  # pid -> (ts, value)
        # lazy expiry heap of (ts, pid); an entry is live iff the map
        # still holds this pid at exactly this ts
        self._exp: List[Tuple[float, int]] = []

    def __len__(self) -> int:
        return len(self._d)

    def is_full(self) -> bool:
        return self.max_size > 0 and len(self._d) >= self.max_size

    def is_empty(self) -> bool:
        return not self._d

    def contains(self, pid: int) -> bool:
        return pid in self._d

    def insert(self, pid: int, value: Any, now: Optional[float] = None) -> None:
        if _fi._injector is not None:
            _fi._injector.check("inflight.insert")
        if self.is_full():
            raise InflightFullError(f"inflight window full ({self.max_size})")
        if pid in self._d:
            raise KeyError(f"packet id {pid} already inflight")
        ts = time.time() if now is None else now
        self._d[pid] = (ts, value)
        heapq.heappush(self._exp, (ts, pid))

    def insert_many(
        self, items: Iterable[Tuple[int, Any]], now: Optional[float] = None
    ) -> None:
        """Bulk :meth:`insert` sharing ONE timestamp — the fanout
        pipeline admits a whole per-session batch with a single clock
        read and heap extension instead of one of each per message."""
        if _fi._injector is not None:
            _fi._injector.check("inflight.insert")
        items = list(items)
        if not items:
            return
        if self.max_size > 0 and len(self._d) + len(items) > self.max_size:
            raise InflightFullError(
                f"inflight window full ({self.max_size})")
        ts = time.time() if now is None else now
        d = self._d
        for pid, _ in items:
            if pid in d:
                raise KeyError(f"packet id {pid} already inflight")
        for pid, value in items:
            d[pid] = (ts, value)
            heapq.heappush(self._exp, (ts, pid))

    def update(self, pid: int, value: Any) -> None:
        if pid not in self._d:
            raise KeyError(pid)
        ts, _ = self._d[pid]
        self._d[pid] = (ts, value)

    def update_many(self, pids: Iterable[int], value: Any) -> None:
        """Bulk phase transition: every ``pid`` takes the SAME new value
        with its timestamp preserved — the QoS2 state machine moves a
        whole PUBREC run from ``publish`` to ``pubrel`` in one pass."""
        d = self._d
        for pid in pids:
            ts, _ = d[pid]
            d[pid] = (ts, value)

    def touch(self, pid: int, now: Optional[float] = None) -> None:
        """Reset the age clock (after a retransmission)."""
        if pid not in self._d:
            raise KeyError(pid)
        _, v = self._d[pid]
        ts = now if now is not None else time.time()
        self._d[pid] = (ts, v)
        heapq.heappush(self._exp, (ts, pid))  # old heap entry goes stale

    def delete(self, pid: int) -> Optional[Any]:
        item = self._d.pop(pid, None)
        # stale heap entries collect until a compaction threshold; the
        # rebuild is amortized O(1) per delete
        if len(self._exp) > 64 and len(self._exp) > 4 * len(self._d):
            self._exp = [(ts, p) for p, (ts, _) in self._d.items()]
            heapq.heapify(self._exp)
        return item[1] if item is not None else None

    def lookup(self, pid: int) -> Optional[Any]:
        item = self._d.get(pid)
        return item[1] if item is not None else None

    def items(self) -> Iterator[Tuple[int, float, Any]]:
        """(pid, inserted_at, value) in insertion order."""
        for pid, (ts, v) in self._d.items():
            yield pid, ts, v

    def older_than(self, age_s: float, now: Optional[float] = None) -> List[int]:
        """Pids due for retry, in age order (oldest first).

        Incremental: pops the expiry heap only while the head is due, so
        an idle tick is O(1) instead of O(window).  Due entries are
        pushed back — a caller that neither ``touch``es nor ``delete``s
        them sees them again next call, exactly like the full scan did.
        """
        if _fi._injector is not None:
            _fi._injector.check("inflight.retry")
        now = now if now is not None else time.time()
        cutoff = now - age_s
        exp = self._exp
        d = self._d
        out: List[int] = []
        seen: set = set()
        push_back: List[Tuple[float, int]] = []
        while exp and exp[0][0] <= cutoff:
            ts, pid = heapq.heappop(exp)
            cur = d.get(pid)
            if cur is None or cur[0] != ts or pid in seen:
                continue  # deleted / touched since / duplicate heap entry
            seen.add(pid)
            out.append(pid)
            push_back.append((ts, pid))
        for e in push_back:
            heapq.heappush(exp, e)
        return out
