"""Shared lazy-TCP wire-client base for the minimal protocol clients
(PostgreSQL, MongoDB, LDAP auth backends; Kafka bridge).

Each client speaks its own protocol but shares the connection
discipline: parse ``host:port``, connect lazily on first use, serialize
request/response exchanges under an asyncio lock with a deadline, and
drop the connection on ANY error so the next call reconnects cleanly
(half-read protocol streams are never resumable).  Centralized here so
reconnect/timeout fixes land once (same motivation as auth/_backend.py).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Optional, TypeVar

__all__ = ["LazyTcpClient"]

T = TypeVar("T")


class LazyTcpClient:
    """One async connection; guarded exchanges; lazy reconnect."""

    def __init__(self, server: str, default_port: int,
                 timeout: float = 5.0) -> None:
        host, _, port = server.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port or default_port)
        self.timeout = timeout
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    @property
    def connected(self) -> bool:
        return self._writer is not None

    async def _ensure(self) -> None:
        """Open the transport + run the protocol handshake if needed.
        Subclasses with a handshake override :meth:`_on_connect`."""
        if self._writer is None:
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port)
            await self._on_connect()

    async def _on_connect(self) -> None:
        pass

    async def _guarded(self, op: Callable[[], Awaitable[T]]) -> T:
        """Serialize one exchange: lock, lazy connect, deadline, and
        drop-on-error (the stream is mid-message after a failure)."""
        async with self._lock:
            try:
                return await asyncio.wait_for(self._with_conn(op),
                                              self.timeout)
            except Exception:
                self._drop()
                raise

    async def _with_conn(self, op):
        await self._ensure()
        return await op()

    def _drop(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._reader = self._writer = None

    async def close(self) -> None:
        async with self._lock:
            self._drop()
