"""The TPU match sidecar — a HookProvider gRPC server.

The north-star deployment (SURVEY.md §0, §3.6): an external broker (a
stock EMQX or this one) points its exhook at this server; the sidecar

* negotiates the hook set at ``OnProviderLoaded`` — the session
  subscribe/unsubscribe events are exactly the delta feed the device
  NFA mirror needs (SURVEY.md §3.3 note);
* maintains the mirror **incrementally**: every filter add/remove is an
  O(filter) mutation of the live :class:`IncrementalNfa` (the
  ``emqx_trie:insert/delete`` analog [U]), drained to the device as
  bounded scatter deltas by a debounced sync loop — NO full recompiles
  on the steady-state path (VERDICT.md round-1 item 1);
* serves ``OnMessagePublish`` through a deadline micro-batching loop
  (SURVEY.md §7.5) so concurrent publishes ride one device kernel call;
* serves ``MirrorSync.MatchBatch`` for bulk match queries (the bench /
  broker-integration fast path — one RPC, one kernel call);
* **fails open per row**: rows whose device answer spilled (active-set
  or match-count overflow) are re-run on the authoritative host trie,
  so answers are exact even when the kernel truncates (SURVEY.md §5.3;
  VERDICT.md weak item 1) — counted in ``Stats``;
* filters deeper than the device table ride host-side under *alias*
  ids in the same accept-id space, merged into device rows.

Run standalone: ``python -m emqx_tpu.exhook.server --port 9000``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..broker.trie import FilterTrie
from .rpc import (
    add_hook_provider_to_server,
    add_mirror_sync_to_server,
    pb,
)

log = logging.getLogger(__name__)

__all__ = ["TpuMatchSidecar", "serve"]


def _bucket_batch(n: int, minimum: int = 64) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class _IncEngine:
    """The serving engine: host-authoritative incremental NFA + device
    mirror + deep-filter (alias) host path.

    Threading: all mutations and encodes happen on the event loop; the
    device mirror's apply/match dispatch may run on worker threads
    (DeviceNfa serializes device ops internally)."""

    def __init__(
        self, depth: int, active_slots: int = 16,
        max_matches: Optional[int] = None
    ) -> None:
        from ..ops import IncrementalNfa
        from ..ops.device_table import DeviceNfa

        self.depth = depth
        self.inc = IncrementalNfa(depth=depth)
        if max_matches is None:
            # the shipped serving K (one source of truth in config.py;
            # hand-copied literals drifted — review finding, round 5)
            from ..config import SCHEMA

            max_matches = SCHEMA["tpu.max_matches"].default
        self.dev = DeviceNfa(
            self.inc, active_slots=active_slots, max_matches=max_matches,
            lazy=True,
        )
        self.deep_aid: Dict[str, int] = {}   # deep filter -> alias aid
        self.deep_trie = FilterTrie()

    # -- mutation (event loop) --------------------------------------------

    def add(self, flt: str) -> None:
        try:
            self.inc.add(flt)
        except ValueError:
            if flt not in self.deep_aid:
                self.deep_aid[flt] = self.inc.alloc_alias(flt)
                self.deep_trie.insert(flt)

    def remove(self, flt: str) -> None:
        aid = self.deep_aid.pop(flt, None)
        if aid is not None:
            self.inc.free_alias(aid)
            self.deep_trie.delete(flt)
        else:
            self.inc.remove(flt)

    def live_filters(self) -> List[str]:
        return self.inc.filters() + sorted(self.deep_aid)

    def aid_of(self, flt: str) -> int:
        aid = self.deep_aid.get(flt)
        return aid if aid is not None else self.inc.aid_of(flt)

    def encode(self, topics: List[str], batch: int):
        from ..ops import encode_batch

        return encode_batch(self.inc, topics, batch=batch)

    def deep_matches(self, topic: str) -> List[int]:
        if not self.deep_aid:
            return []
        return [self.deep_aid[f] for f in self.deep_trie.match(topic)]


class TpuMatchSidecar:
    """HookProvider + MirrorSync servicer (grpc.aio, async methods)."""

    def __init__(
        self,
        depth: int = 8,
        batch_window_ms: float = 0.2,
        max_batch: int = 4096,
        rebuild_debounce_s: float = 0.1,
        annotate: bool = False,
        node: str = "tpu-sidecar",
        checkpoint_path: str = "",
        active_slots: int = 16,
        max_matches: Optional[int] = None,
    ) -> None:
        self.depth = depth
        self.batch_window_s = batch_window_ms / 1000.0
        self.max_batch = max_batch
        self.rebuild_debounce_s = rebuild_debounce_s
        self.annotate = annotate
        self.node = node
        self.checkpoint_path = checkpoint_path

        self._ref: Dict[str, int] = {}        # filter -> refcount
        self._epoch = 0
        self._eng = _IncEngine(
            depth, active_slots=active_slots, max_matches=max_matches
        )
        self._eng_ready = False               # device mirror serveable
        self._dirty = asyncio.Event()
        self._pending: List[Tuple[str, asyncio.Future]] = []
        self._batch_wake = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        self._running = False
        # stats
        self.batches = 0
        self.topics_matched = 0
        self.spill_fallbacks = 0   # rows re-run on the host trie
        self.syncs = 0
        self._lat_ms: List[float] = []   # rolling batch latency samples

    # engine visible only once the device mirror can serve (tests and the
    # bench gate on `sidecar._engine is not None`)
    @property
    def _engine(self) -> Optional[_IncEngine]:
        return self._eng if self._eng_ready else None

    @property
    def _table_version(self) -> int:
        return self._eng.inc.epoch

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        if self.checkpoint_path:
            self._restore_checkpoint()
        # supervised when a host sets .supervisor before start (embedded
        # use); the standalone sidecar process has no supervision tree
        # and falls back to raw tasks
        sup = getattr(self, "supervisor", None)
        if sup is not None:
            self._tasks = [
                sup.start_child("exhook.sidecar.sync", self._sync_loop),
                sup.start_child("exhook.sidecar.batch", self._batch_loop),
            ]
        else:
            self._tasks = [
                asyncio.ensure_future(self._sync_loop()),
                asyncio.ensure_future(self._batch_loop()),
            ]

    def _restore_checkpoint(self) -> None:
        """Re-adopt the checkpointed filter set so the mirror serves
        immediately; the live feed (hooks / InstallSnapshot) reconciles
        afterwards (InstallSnapshot diffs against engine contents, which
        drops filters whose subscribers vanished while we were down)."""
        try:
            from ..storage.checkpoint import load_table

            table = load_table(self.checkpoint_path)
            if table is None:
                return
            t0 = time.perf_counter()
            for flt in table.accept_filters:
                if flt is not None:
                    self._eng.add(flt)
            self._eng.dev.sync(full=True)
            self._warm(self._eng)
            self._eng_ready = True
            log.info(
                "checkpoint restored: %d filters, %d states, %.1f ms "
                "(stale until first sync)",
                self._eng.inc.n_filters + len(self._eng.deep_aid),
                self._eng.inc.n_states,
                (time.perf_counter() - t0) * 1e3,
            )
        except Exception:
            log.exception("checkpoint restore failed; cold start")

    async def stop(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks = []

    # ------------------------------------------------------------------
    # mirror mutation (event loop only)
    # ------------------------------------------------------------------

    def _add_filter(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        self._ref[flt] = n + 1
        if n == 0:
            self._eng.add(flt)
            self._epoch += 1
            self._dirty.set()

    def _del_filter(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        if n <= 1:
            if n == 1:
                del self._ref[flt]
                self._eng.remove(flt)
                self._epoch += 1
                self._dirty.set()
        else:
            self._ref[flt] = n - 1

    async def _sync_loop(self) -> None:
        """Debounced host→device delta shipping (the mria rlog-replay
        analog).  Steady state is O(delta): scatter a few rows, no XLA
        recompile, no table rebuild."""
        while True:
            await self._dirty.wait()
            await asyncio.sleep(self.rebuild_debounce_s)  # debounce bursts
            self._dirty.clear()
            eng = self._eng
            t0 = time.perf_counter()
            try:
                first = not self._eng_ready
                pending = eng.dev.drain(full=first)  # loop-side: O(delta)
                if pending.full is not None:
                    # a full upload changes table shapes ⇒ match jit
                    # recompiles; serve from the host until re-warmed so
                    # queued matches never stall behind the compile
                    # (ADVICE.md round-2 high item 2)
                    self._eng_ready = False
                # device work off the loop: a growth re-upload or a jit
                # warm takes long enough to stall hook RPCs otherwise
                await asyncio.to_thread(eng.dev.apply_pending, pending)
                if first or pending.full is not None:
                    await asyncio.to_thread(self._warm, eng)
                self._eng_ready = True
                self.syncs += 1
                dt = (time.perf_counter() - t0) * 1e3
                log.info(
                    "mirror sync: epoch %d (%s), %.1f ms",
                    pending.epoch,
                    "full upload" if pending.full is not None else
                    f"{len(pending.delta.state_idx)}+"
                    f"{len(pending.delta.bucket_idx)} rows",
                    dt,
                )
                if self.checkpoint_path:
                    await asyncio.to_thread(self._save_checkpoint)
            except Exception:
                # the drained delta is lost and the device mirror may be
                # poisoned (DeviceNfa dropped its arrays): re-mark dirty
                # so the next pass re-uploads in full, after a breather
                log.exception(
                    "mirror sync failed; host fallback serves, full "
                    "re-upload scheduled"
                )
                await asyncio.sleep(1.0)
                self._dirty.set()

    def _warm(self, eng: _IncEngine) -> None:
        """Warm the match jit for the smallest batch bucket (larger
        buckets compile on first use).  Uses pre-encoded inert rows so no
        live host state is read off-loop."""
        from ..ops.match_kernel import SERVE_FLAT_MULT

        words, lens, is_sys = eng.encode([], 64)  # inert padding rows
        # flat_cap is jit-static: warm the SAME variant serving uses
        eng.dev.match(words, lens, is_sys, flat_cap=SERVE_FLAT_MULT * 64)

    def _save_checkpoint(self) -> None:
        try:
            from ..storage.checkpoint import save_table

            if self._eng.inc.n_filters or self._eng.deep_aid:
                save_table(self._eng.inc.snapshot(), self.checkpoint_path)
            elif os.path.exists(self.checkpoint_path):
                # an emptied mirror must not resurrect the old table on
                # the next restart
                os.remove(self.checkpoint_path)
        except Exception:
            log.exception("checkpoint save failed")

    # ------------------------------------------------------------------
    # match paths
    # ------------------------------------------------------------------

    def _host_row(self, topic: str) -> List[int]:
        """Authoritative host answer as accept/alias ids — walks the
        live incremental trie directly (the single source of truth, so
        fail-open answers are exact even mid-restore)."""
        eng = self._eng
        row = eng.inc.match_host(topic)
        row.extend(eng.deep_matches(topic))
        return row

    def _device_rows(self, eng: _IncEngine, enc, n: int):
        """WORKER THREAD: kernel dispatch + readback.  Returns (rows,
        spilled_row_indexes).  ONE bundled device→host fetch of the
        FLAT-compacted output (~fan-out·4 bytes/topic instead of K·4):
        on a remote-attached device readback bytes are the serving
        bottleneck (BASELINE.md tunnel table)."""
        import jax

        from ..ops.match_kernel import SERVE_FLAT_MULT, decode_flat

        B = enc[0].shape[0]
        res = eng.dev.match(*enc, flat_cap=SERVE_FLAT_MULT * B)
        # OR the spill flags on host — res.spilled_rows() would build new
        # lazy device ops, adding a dispatch round trip to every readback
        matches, counts, aover, mover = jax.device_get(
            (res.matches, res.n_matches, res.active_overflow,
             res.match_overflow)
        )
        sp = (aover > 0) | (mover > 0)
        rows = [seg.tolist()
                for seg in decode_flat(matches, counts,
                                       eng.dev.max_matches)[:n]]
        return rows, np.flatnonzero(sp[:n]).tolist()

    async def _match_rows(self, topics: List[str]) -> List[List[int]]:
        """Match a batch to accept-id rows: device kernel + per-row
        fail-open + deep merge.  Encode and all host-trie reads stay on
        the loop; only device dispatch/readback runs in a thread."""
        eng = self._eng
        if not self._eng_ready or not topics:
            return [self._host_row(t) for t in topics]
        B = _bucket_batch(min(len(topics), self.max_batch))
        enc = eng.encode(topics, B)
        # aid-reuse guard: device rows decoded through a mutated
        # accept_filters after an id was recycled would name the wrong
        # filter — discard the batch and answer from the host trie
        reuses0 = eng.inc.aid_reuses
        try:
            rows, spilled = await asyncio.to_thread(
                self._device_rows, eng, enc, len(topics)
            )
            if eng.inc.aid_reuses != reuses0:
                raise RuntimeError("aid reused mid-flight")
        except Exception:
            log.exception("device match failed; host fallback")
            return [self._host_row(t) for t in topics]
        if spilled:
            self.spill_fallbacks += len(spilled)
            for r in spilled:
                rows[r] = self._host_row(topics[r])
        if eng.deep_aid:
            spset = set(spilled)
            for r, t in enumerate(topics):
                if r not in spset:
                    rows[r].extend(eng.deep_matches(t))
        return rows

    def _ids_to_filters(self, rows: List[List[int]]) -> List[List[str]]:
        table = self._eng.inc.accept_filters
        return [[table[a] for a in row if table[a] is not None]
                for row in rows]

    async def _queue_match(self, topic: str) -> List[str]:
        """Micro-batched single-topic match; returns filter strings."""
        if not self._eng_ready:
            return self._ids_to_filters([self._host_row(topic)])[0]
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending.append((topic, fut))
        self._batch_wake.set()
        try:
            # bounded wait: a stalled device (growth re-upload compile)
            # degrades to the authoritative host answer, never blocks
            # the hook RPC past its deadline
            return await asyncio.wait_for(fut, 2.0)
        except asyncio.TimeoutError:
            return self._ids_to_filters([self._host_row(topic)])[0]

    async def _batch_loop(self) -> None:
        while True:
            await self._batch_wake.wait()
            self._batch_wake.clear()
            if not self._pending:
                continue
            # deadline micro-batching: let concurrent arrivals pile up
            await asyncio.sleep(self.batch_window_s)
            pending, self._pending = self._pending[: self.max_batch], \
                self._pending[self.max_batch:]
            if self._pending:
                self._batch_wake.set()
            topics = [t for t, _ in pending]
            t0 = time.perf_counter()
            try:
                results = self._ids_to_filters(await self._match_rows(topics))
            except Exception:
                log.exception("batch match failed; host fallback")
                results = self._ids_to_filters(
                    [self._host_row(t) for t in topics]
                )
            dt_ms = (time.perf_counter() - t0) * 1e3
            self.batches += 1
            self.topics_matched += len(topics)
            self._lat_ms.append(dt_ms)
            if len(self._lat_ms) > 1024:
                del self._lat_ms[:512]
            for (_, fut), res in zip(pending, results):
                if not fut.done():
                    fut.set_result(res)

    # ------------------------------------------------------------------
    # HookProvider service (async grpc.aio handlers)
    # ------------------------------------------------------------------

    async def OnProviderLoaded(self, request, context):
        log.info("provider loaded by node %s", request.meta.node)
        wanted = [
            "session.subscribed", "session.unsubscribed",
            "message.publish",
        ]
        return pb.LoadedResponse(
            hooks=[pb.HookSpec(name=h) for h in wanted]
        )

    async def OnProviderUnloaded(self, request, context):
        return pb.EmptySuccess()

    async def OnSessionSubscribed(self, request, context):
        # the mirror tracks routing filters; $share group load-balancing
        # stays broker-side, so the broker sends the stripped filter here
        self._add_filter(request.topic)
        return pb.EmptySuccess()

    async def OnSessionUnsubscribed(self, request, context):
        self._del_filter(request.topic)
        return pb.EmptySuccess()

    async def OnMessagePublish(self, request, context):
        matched = await self._queue_match(request.message.topic)
        if not self.annotate:
            return pb.ValuedResponse(type=pb.ValuedResponse.CONTINUE)
        msg = pb.Message()
        msg.CopyFrom(request.message)
        msg.headers["matched_filters"] = str(len(matched))
        return pb.ValuedResponse(
            type=pb.ValuedResponse.STOP_AND_RETURN, message=msg
        )

    # ------------------------------------------------------------------
    # MirrorSync service
    # ------------------------------------------------------------------

    async def InstallSnapshot(self, request_iterator, context):
        """Bulk bootstrap: reconcile the mirror to exactly the streamed
        filter set (diff-apply through the same incremental machinery —
        also drops stale checkpoint-restored filters)."""
        ref: Dict[str, int] = {}
        epoch = 0
        async for chunk in request_iterator:
            epoch = max(epoch, chunk.epoch)
            counts = list(chunk.refcounts)
            for i, flt in enumerate(chunk.filters):
                ref[flt] = counts[i] if i < len(counts) else 1
        current = set(self._eng.live_filters())
        for flt in current - set(ref):
            self._eng.remove(flt)
        for flt in set(ref) - current:
            self._eng.add(flt)
        self._ref = ref
        self._epoch = epoch
        self._dirty.set()
        return pb.SnapshotAck(
            epoch=epoch, n_filters=len(ref), rebuilt=False
        )

    async def ApplyDeltas(self, request, context):
        for d in request.deltas:
            if d.op == pb.DeltaBatch.Delta.ADD:
                self._add_filter(d.filter)
            else:
                self._del_filter(d.filter)
        self._epoch = max(self._epoch, request.to_epoch)
        return pb.SnapshotAck(
            epoch=self._epoch, n_filters=len(self._ref), rebuilt=False
        )

    async def MatchBatch(self, request, context):
        topics = list(request.topics)
        t0 = time.perf_counter()
        resp = pb.MatchBatchResponse(
            epoch=self._epoch, table_version=self._table_version
        )
        rows = await self._match_rows(topics)
        for row in rows:
            resp.results.add(filter_ids=row)
        dt_ms = (time.perf_counter() - t0) * 1e3
        self.batches += 1
        self.topics_matched += len(topics)
        self._lat_ms.append(dt_ms)
        return resp

    async def FilterTable(self, request, context):
        return pb.FilterTableResponse(
            table_version=self._table_version,
            filters=self.filter_table(),
        )

    async def Stats(self, request, context):
        lat = sorted(self._lat_ms) or [0.0]
        eng = self._eng
        return pb.StatsResponse(
            epoch=self._epoch,
            n_filters=len(self._ref),
            n_states=eng.inc.n_states if self._eng_ready else 0,
            batches=self.batches,
            topics_matched=self.topics_matched,
            p50_batch_ms=lat[len(lat) // 2],
            p99_batch_ms=lat[min(len(lat) - 1, int(len(lat) * 0.99))],
            pending_deltas=int(self._dirty.is_set()),
            extra={
                "table_version": str(self._table_version),
                "spill_fallbacks": str(self.spill_fallbacks),
                "device_uploads": str(eng.dev.uploads),
                "device_delta_applies": str(eng.dev.delta_applies),
                "syncs": str(self.syncs),
            },
        )

    # ------------------------------------------------------------------

    def filter_table(self) -> List[str]:
        """id -> filter for MatchBatch results; freed ids resolve to ""."""
        return [f or "" for f in self._eng.inc.accept_filters]


async def serve(
    port: int = 9000,
    host: str = "127.0.0.1",
    sidecar: Optional[TpuMatchSidecar] = None,
) -> Tuple[Any, TpuMatchSidecar]:
    """Start a grpc.aio server hosting the sidecar; returns (server, sidecar)."""
    import grpc.aio

    sidecar = sidecar if sidecar is not None else TpuMatchSidecar()
    server = grpc.aio.server()
    add_hook_provider_to_server(sidecar, server)
    add_mirror_sync_to_server(sidecar, server)
    server.add_insecure_port(f"{host}:{port}")
    await sidecar.start()
    await server.start()
    return server, sidecar


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description="TPU match sidecar")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--annotate", action="store_true")
    ap.add_argument("--checkpoint", default="",
                    help="path for the compiled-table checkpoint")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        server, _ = await serve(
            port=args.port, host=args.host,
            sidecar=TpuMatchSidecar(depth=args.depth, annotate=args.annotate,
                                    checkpoint_path=args.checkpoint),
        )
        await server.wait_for_termination()

    asyncio.run(run())


if __name__ == "__main__":
    main()
