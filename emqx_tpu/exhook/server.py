"""The TPU match sidecar — a HookProvider gRPC server.

The north-star deployment (SURVEY.md §0, §3.6): an external broker (a
stock EMQX or this one) points its exhook at this server; the sidecar

* negotiates the hook set at ``OnProviderLoaded`` — the session
  subscribe/unsubscribe events are exactly the delta feed the device
  NFA mirror needs (SURVEY.md §3.3 note);
* maintains a refcounted filter table mirror, recompiled into the
  flattened-NFA device table in the background with debounce (the mria
  bootstrap-then-replay-rlog pattern, SURVEY.md §5.4 — bulk install via
  ``MirrorSync.InstallSnapshot``, steady-state deltas via the hook feed
  or ``MirrorSync.ApplyDeltas``);
* serves ``OnMessagePublish`` through a deadline micro-batching loop
  (SURVEY.md §7.5) so concurrent publishes ride one device kernel call;
* serves ``MirrorSync.MatchBatch`` for bulk match queries (the bench /
  broker-integration fast path — one RPC, one kernel call);
* fails open: with no compiled table (cold start, rebuild in flight) it
  falls back to the host trie match so answers stay correct.

Run standalone: ``python -m emqx_tpu.exhook.server --port 9000``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..broker.trie import FilterTrie
from .rpc import (
    add_hook_provider_to_server,
    add_mirror_sync_to_server,
    pb,
)

log = logging.getLogger(__name__)

__all__ = ["TpuMatchSidecar", "serve"]


class _Engine:
    """One compiled epoch: device table + jitted matcher, immutable.

    ``deep`` filters (more levels than the device table depth) can't ride
    the NFA; they are matched host-side per batch and merged in, so the
    combined answer stays exactly the oracle's.  Their ids follow the
    device filters: ``filter_table = filters + deep``.
    """

    def __init__(
        self, filters: List[str], deep: List[str], depth: int, version: int,
        table=None,
    ) -> None:
        import jax
        import jax.numpy as jnp

        from ..ops import build_matcher, compile_filters

        self.filters = filters  # id -> filter string (table_version scope)
        self.deep = deep
        self.version = version
        # a checkpointed table skips the compile (SURVEY.md §5.4)
        self.table = table if table is not None \
            else compile_filters(filters, depth=depth)
        self.args = [jnp.asarray(a) for a in self.table.device_arrays()]
        self._fn = jax.jit(build_matcher())
        self._jnp = jnp
        # accept-id -> our filter id (compile_filters dedups+sorts)
        fid = {f: i for i, f in enumerate(filters)}
        self._accept_to_id = np.asarray(
            [fid[f] for f in self.table.accept_filters], np.int32
        )
        self._deep_trie = FilterTrie()
        self._deep_id = {}
        for i, f in enumerate(deep):
            self._deep_trie.insert(f)
            self._deep_id[f] = len(filters) + i

    def filter_table(self) -> List[str]:
        return self.filters + self.deep

    def match(self, topics: List[str], batch: int) -> List[List[int]]:
        from ..ops import encode_topics

        words, lens, is_sys = encode_topics(self.table, topics, batch=batch)
        jnp = self._jnp
        res = self._fn(
            jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *self.args,
        )
        matches = np.asarray(res.matches)
        counts = np.asarray(res.n_matches)
        out: List[List[int]] = []
        for r, topic in enumerate(topics):
            row = [int(self._accept_to_id[a]) for a in matches[r, : counts[r]]]
            if self.deep:
                row.extend(
                    self._deep_id[f] for f in self._deep_trie.match(topic)
                )
            out.append(row)
        return out


def _bucket_batch(n: int, minimum: int = 64) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class TpuMatchSidecar:
    """HookProvider + MirrorSync servicer (grpc.aio, async methods)."""

    def __init__(
        self,
        depth: int = 8,
        batch_window_ms: float = 0.2,
        max_batch: int = 4096,
        rebuild_debounce_s: float = 0.1,
        annotate: bool = False,
        node: str = "tpu-sidecar",
        checkpoint_path: str = "",
    ) -> None:
        self.depth = depth
        self.batch_window_s = batch_window_ms / 1000.0
        self.max_batch = max_batch
        self.rebuild_debounce_s = rebuild_debounce_s
        self.annotate = annotate
        self.node = node
        self.checkpoint_path = checkpoint_path

        self._ref: Dict[str, int] = {}       # filter -> refcount
        self._trie = FilterTrie()             # host fallback (fail-open)
        self._epoch = 0
        self._table_version = 0
        self._engine: Optional[_Engine] = None
        self._dirty = asyncio.Event()
        self._pending: List[Tuple[str, asyncio.Future]] = []
        self._batch_wake = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        self._running = False
        # stats
        self.batches = 0
        self.topics_matched = 0
        self._lat_ms: List[float] = []   # rolling batch latency samples

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        if self.checkpoint_path:
            self._restore_checkpoint()
        self._tasks = [
            asyncio.ensure_future(self._rebuild_loop()),
            asyncio.ensure_future(self._batch_loop()),
        ]

    def _restore_checkpoint(self) -> None:
        """Serve the checkpointed table immediately; the subscription feed
        (hooks / InstallSnapshot) reconciles the mirror afterwards."""
        try:
            from ..storage.checkpoint import load_table

            table = load_table(self.checkpoint_path)
            if table is None:
                return
            filters = sorted(table.accept_filters)
            self._table_version += 1
            engine = _Engine(
                filters, [], self.depth, self._table_version, table=table
            )
            engine.match(["warm/up"], batch=64)
            self._engine = engine
            # deliberately do NOT seed _ref/_trie from the checkpoint:
            # the live feed (hooks / InstallSnapshot) is authoritative,
            # and ghost refcounts would pin filters whose subscribers
            # vanished while we were down.  The checkpointed engine
            # serves (possibly stale) answers until the first rebuild.
            log.info(
                "checkpoint restored: %d filters, %d states (stale until "
                "first sync)", len(filters), table.n_states,
            )
        except Exception:
            log.exception("checkpoint restore failed; cold start")

    async def stop(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks = []

    # ------------------------------------------------------------------
    # mirror mutation
    # ------------------------------------------------------------------

    def _add_filter(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        self._ref[flt] = n + 1
        if n == 0:
            self._trie.insert(flt)
            self._epoch += 1
            self._dirty.set()

    def _del_filter(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        if n <= 1:
            if n == 1:
                del self._ref[flt]
                self._trie.delete(flt)
                self._epoch += 1
                self._dirty.set()
        else:
            self._ref[flt] = n - 1

    async def _rebuild_loop(self) -> None:
        while True:
            await self._dirty.wait()
            await asyncio.sleep(self.rebuild_debounce_s)  # debounce bursts
            self._dirty.clear()
            from .. import topic as T

            filters, deep = [], []
            for f in sorted(self._ref):
                (filters if len(T.words(f)) <= self.depth else deep).append(f)
            version = self._table_version + 1
            t0 = time.perf_counter()
            try:
                if filters:
                    # build + jit-warm off the event loop: XLA compilation
                    # takes hundreds of ms and would stall every hook RPC
                    # (deny-policy brokers would veto traffic per rebuild)
                    def build():
                        engine = _Engine(filters, deep, self.depth, version)
                        engine.match(["warm/up"], batch=64)  # warm the jit
                        return engine

                    engine = await asyncio.to_thread(build)
                else:
                    engine = None
                self._engine = engine
                self._table_version = version
                log.info(
                    "mirror rebuilt: %d filters (+%d host-side deep), "
                    "version %d, %.1f ms",
                    len(filters), len(deep), version,
                    (time.perf_counter() - t0) * 1e3,
                )
                if self.checkpoint_path:
                    try:
                        from ..storage.checkpoint import save_table

                        if engine is not None:
                            save_table(engine.table, self.checkpoint_path)
                        elif os.path.exists(self.checkpoint_path):
                            # an emptied mirror must not resurrect the
                            # old table on the next restart
                            os.remove(self.checkpoint_path)
                    except Exception:
                        log.exception("checkpoint save failed")
            except Exception:
                log.exception("mirror rebuild failed; host fallback serves")

    # ------------------------------------------------------------------
    # match paths
    # ------------------------------------------------------------------

    def _host_match(self, topic: str) -> List[str]:
        return self._trie.match(topic)

    async def _queue_match(self, topic: str) -> List[str]:
        """Micro-batched single-topic match; returns filter strings."""
        if self._engine is None:
            return self._host_match(topic)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending.append((topic, fut))
        self._batch_wake.set()
        return await fut

    async def _batch_loop(self) -> None:
        while True:
            await self._batch_wake.wait()
            self._batch_wake.clear()
            if not self._pending:
                continue
            # deadline micro-batching: let concurrent arrivals pile up
            await asyncio.sleep(self.batch_window_s)
            pending, self._pending = self._pending[: self.max_batch], \
                self._pending[self.max_batch:]
            if self._pending:
                self._batch_wake.set()
            engine = self._engine
            topics = [t for t, _ in pending]
            t0 = time.perf_counter()
            try:
                if engine is None:
                    results = [self._host_match(t) for t in topics]
                else:
                    table = engine.filter_table()
                    ids = engine.match(topics, _bucket_batch(len(topics)))
                    results = [
                        [table[i] for i in row] for row in ids
                    ]
            except Exception:
                log.exception("batch match failed; host fallback")
                results = [self._host_match(t) for t in topics]
            dt_ms = (time.perf_counter() - t0) * 1e3
            self.batches += 1
            self.topics_matched += len(topics)
            self._lat_ms.append(dt_ms)
            if len(self._lat_ms) > 1024:
                del self._lat_ms[:512]
            for (_, fut), res in zip(pending, results):
                if not fut.done():
                    fut.set_result(res)

    # ------------------------------------------------------------------
    # HookProvider service (async grpc.aio handlers)
    # ------------------------------------------------------------------

    async def OnProviderLoaded(self, request, context):
        log.info("provider loaded by node %s", request.meta.node)
        wanted = [
            "session.subscribed", "session.unsubscribed",
            "message.publish",
        ]
        return pb.LoadedResponse(
            hooks=[pb.HookSpec(name=h) for h in wanted]
        )

    async def OnProviderUnloaded(self, request, context):
        return pb.EmptySuccess()

    async def OnSessionSubscribed(self, request, context):
        # the mirror tracks routing filters; $share group load-balancing
        # stays broker-side, so the broker sends the stripped filter here
        self._add_filter(request.topic)
        return pb.EmptySuccess()

    async def OnSessionUnsubscribed(self, request, context):
        self._del_filter(request.topic)
        return pb.EmptySuccess()

    async def OnMessagePublish(self, request, context):
        matched = await self._queue_match(request.message.topic)
        if not self.annotate:
            return pb.ValuedResponse(type=pb.ValuedResponse.CONTINUE)
        msg = pb.Message()
        msg.CopyFrom(request.message)
        msg.headers["matched_filters"] = str(len(matched))
        return pb.ValuedResponse(
            type=pb.ValuedResponse.STOP_AND_RETURN, message=msg
        )

    # ------------------------------------------------------------------
    # MirrorSync service
    # ------------------------------------------------------------------

    async def InstallSnapshot(self, request_iterator, context):
        ref: Dict[str, int] = {}
        epoch = 0
        async for chunk in request_iterator:
            epoch = max(epoch, chunk.epoch)
            counts = list(chunk.refcounts)
            for i, flt in enumerate(chunk.filters):
                ref[flt] = counts[i] if i < len(counts) else 1
        self._ref = ref
        trie = FilterTrie()
        for flt in ref:
            trie.insert(flt)
        self._trie = trie
        self._epoch = epoch
        self._dirty.set()
        return pb.SnapshotAck(
            epoch=epoch, n_filters=len(ref), rebuilt=False
        )

    async def ApplyDeltas(self, request, context):
        for d in request.deltas:
            if d.op == pb.DeltaBatch.Delta.ADD:
                self._add_filter(d.filter)
            else:
                self._del_filter(d.filter)
        self._epoch = max(self._epoch, request.to_epoch)
        return pb.SnapshotAck(
            epoch=self._epoch, n_filters=len(self._ref), rebuilt=False
        )

    async def MatchBatch(self, request, context):
        topics = list(request.topics)
        engine = self._engine
        resp = pb.MatchBatchResponse(
            epoch=self._epoch, table_version=self._table_version
        )
        t0 = time.perf_counter()
        if engine is None:
            # host fallback: ids are indexes into a sorted filter list
            filters = sorted(self._ref)
            index = {f: i for i, f in enumerate(filters)}
            for t in topics:
                resp.results.add(
                    filter_ids=[index[f] for f in self._host_match(t)
                                if f in index]
                )
        else:
            for row in engine.match(topics, _bucket_batch(len(topics) or 1)):
                resp.results.add(filter_ids=row)
        dt_ms = (time.perf_counter() - t0) * 1e3
        self.batches += 1
        self.topics_matched += len(topics)
        self._lat_ms.append(dt_ms)
        return resp

    async def FilterTable(self, request, context):
        return pb.FilterTableResponse(
            table_version=self._table_version,
            filters=self.filter_table(),
        )

    async def Stats(self, request, context):
        lat = sorted(self._lat_ms) or [0.0]
        engine = self._engine
        return pb.StatsResponse(
            epoch=self._epoch,
            n_filters=len(self._ref),
            n_states=engine.table.n_states if engine is not None else 0,
            batches=self.batches,
            topics_matched=self.topics_matched,
            p50_batch_ms=lat[len(lat) // 2],
            p99_batch_ms=lat[min(len(lat) - 1, int(len(lat) * 0.99))],
            pending_deltas=int(self._dirty.is_set()),
            extra={"table_version": str(self._table_version)},
        )

    # ------------------------------------------------------------------

    def filter_table(self) -> List[str]:
        """id -> filter for the current table_version (MatchBatch ids)."""
        engine = self._engine
        return engine.filter_table() if engine is not None else sorted(self._ref)


async def serve(
    port: int = 9000,
    host: str = "127.0.0.1",
    sidecar: Optional[TpuMatchSidecar] = None,
) -> Tuple[Any, TpuMatchSidecar]:
    """Start a grpc.aio server hosting the sidecar; returns (server, sidecar)."""
    import grpc.aio

    sidecar = sidecar if sidecar is not None else TpuMatchSidecar()
    server = grpc.aio.server()
    add_hook_provider_to_server(sidecar, server)
    add_mirror_sync_to_server(sidecar, server)
    server.add_insecure_port(f"{host}:{port}")
    await sidecar.start()
    await server.start()
    return server, sidecar


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description="TPU match sidecar")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--annotate", action="store_true")
    ap.add_argument("--checkpoint", default="",
                    help="path for the compiled-table checkpoint")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        server, _ = await serve(
            port=args.port, host=args.host,
            sidecar=TpuMatchSidecar(depth=args.depth, annotate=args.annotate,
                                    checkpoint_path=args.checkpoint),
        )
        await server.wait_for_termination()

    asyncio.run(run())


if __name__ == "__main__":
    main()
