"""Broker-side ExHook manager — streams hook points to gRPC servers.

Behavioral reference: ``apps/emqx_exhook/src/emqx_exhook_handler.erl`` /
``emqx_exhook_mgr.erl`` [U] (SURVEY.md §2.3, §3.6):

* each configured server is dialled at start; ``OnProviderLoaded``
  negotiates which hook points that server wants;
* *advisory* hooks (client.authenticate / client.authorize /
  message.publish) are synchronous gRPC round trips whose
  ``ValuedResponse`` may stop the chain with a verdict or a mutated
  message;
* *notification* hooks (client.connected, session.*, message.delivered,
  ...) are fire-and-forget events;
* per-server ``failure_action`` (``deny`` | ``ignore``) applies when the
  call errors or times out — ``ignore`` fails open (SURVEY.md §5.3).

Integration: the synchronous broker core never awaits; the async round
trips happen in :meth:`ExHookManager.intercept`, which the connection
loop awaits *before* ``Channel.handle_in`` for CONNECT / PUBLISH /
SUBSCRIBE packets, applying verdicts by rewriting the packet (mutation),
tagging it (``allow_publish`` / ``denied_filters``, consumed by the
channel), or short-circuiting with ``Channel.deny_in`` actions.
Notification events ride the normal sync hook bus into a bounded queue
drained by one background sender task per server.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import grpc
import grpc.aio

from .. import faultinject as _fi
from ..mqtt import packet as P
from .rpc import HookProviderStub, MirrorSyncStub, pb

log = logging.getLogger(__name__)

__all__ = ["ServerSpec", "ExHookManager"]

#: every hook point the manager can stream (reference exhook v2 set)
ALL_HOOKS = [
    "client.connect", "client.connack", "client.connected",
    "client.disconnected", "client.authenticate", "client.authorize",
    "client.subscribe", "client.unsubscribe",
    "session.created", "session.subscribed", "session.unsubscribed",
    "session.resumed", "session.discarded", "session.takenover",
    "session.terminated",
    "message.publish", "message.delivered", "message.dropped",
    "message.acked",
]

_NOTIFY_QUEUE_CAP = 10000


@dataclass
class ServerSpec:
    name: str
    url: str                       # "host:port"
    failure_action: str = "ignore"  # "deny" | "ignore"
    timeout: float = 5.0
    enable: bool = True


@dataclass
class _ServerState:
    spec: ServerSpec
    channel: Optional[grpc.aio.Channel] = None
    stub: Optional[HookProviderStub] = None
    hooks: List[str] = field(default_factory=list)
    queue: "asyncio.Queue" = field(default_factory=lambda: asyncio.Queue(_NOTIFY_QUEUE_CAP))
    sender: Optional[asyncio.Task] = None
    ok: int = 0
    failed: int = 0
    dropped: int = 0

    def wants(self, point: str) -> bool:
        return point in self.hooks


class ExHookManager:
    """Owns the server registry + the packet intercept stage."""

    def __init__(self, node: Any, servers: List[ServerSpec]) -> None:
        self.node = node
        self.broker = node.broker
        self.servers: List[_ServerState] = [
            _ServerState(spec=s) for s in servers if s.enable
        ]
        self._running = False
        self._hook_names: List[str] = []
        self._reconnector: Optional[asyncio.Task] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    RECONNECT_INTERVAL = 5.0

    async def start(self) -> None:
        self._running = True
        # dial concurrently: N unreachable servers cost one timeout, not N
        await asyncio.gather(
            *(self._load_server(st) for st in self.servers)
        )
        self._register_notify_hooks()
        sup = getattr(self.node, "supervisor", None)
        if sup is not None:
            self._reconnector = sup.start_child(
                "exhook.reconnect", self._reconnect_loop)
        else:
            self._reconnector = asyncio.ensure_future(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        """Keep retrying servers that failed to load — a deny-policy
        server fails closed while down (see ``_down_deny``), so recovery
        must not require a broker restart."""
        while self._running:
            await asyncio.sleep(self.RECONNECT_INTERVAL)
            for st in self.servers:
                if st.stub is None:
                    await self._load_server(st)

    async def stop(self) -> None:
        self._running = False
        if getattr(self, "_reconnector", None) is not None:
            self._reconnector.cancel()
            self._reconnector = None
        self._unregister_notify_hooks()
        for st in self.servers:
            if st.sender is not None:
                st.sender.cancel()
            if st.stub is not None:
                try:
                    await asyncio.wait_for(
                        st.stub.OnProviderUnloaded(
                            pb.ProviderUnloadedRequest(meta=self._meta())
                        ),
                        timeout=st.spec.timeout,
                    )
                except Exception:
                    log.debug("exhook %s OnProviderUnloaded failed",
                              st.spec.name, exc_info=True)
            if st.channel is not None:
                await st.channel.close()
                st.channel = None

    async def _load_server(self, st: _ServerState) -> None:
        # st.stub stays None until negotiation succeeds — _down_deny and
        # the advisory loops treat a non-None stub as "server usable"
        channel = stub = None
        try:
            channel = grpc.aio.insecure_channel(st.spec.url)
            stub = HookProviderStub(channel)
            resp = await asyncio.wait_for(
                stub.OnProviderLoaded(
                    pb.ProviderLoadedRequest(
                        broker=pb.BrokerInfo(
                            version="emqx_tpu",
                            sysdescr="tpu-native broker",
                            uptime=str(int(time.time() - self.node.started_at)),
                        ),
                        meta=self._meta(),
                    )
                ),
                timeout=st.spec.timeout,
            )
            st.hooks = [h.name for h in resp.hooks if h.name in ALL_HOOKS]
            st.channel, st.stub = channel, stub
            if st.sender is None:
                sup = getattr(self.node, "supervisor", None)
                if sup is not None:
                    # supervised: a crashed notification drain restarts
                    # instead of silently dropping every hook event for
                    # this server until broker restart
                    st.sender = sup.start_child(
                        f"exhook.sender.{st.spec.name}",
                        lambda st=st: self._sender_loop(st))
                else:
                    st.sender = asyncio.ensure_future(self._sender_loop(st))
            log.info("exhook server %s loaded hooks=%s", st.spec.name, st.hooks)
            await self._push_mirror_snapshot(st)
        except Exception as e:
            log.warning("exhook server %s load failed: %s", st.spec.name, e)
            st.hooks = []
            if channel is not None:
                await channel.close()

    async def _push_mirror_snapshot(self, st: _ServerState) -> None:
        """Reconcile a subscription-mirroring server (our TPU sidecar)
        with the broker's CURRENT filter set at (re)connect: hook events
        only stream changes, so without this a restarted sidecar keeps
        checkpoint ghosts and misses pre-existing subscriptions.  Stock
        HookProvider servers don't implement MirrorSync — UNIMPLEMENTED
        is expected and ignored."""
        if "session.subscribed" not in st.hooks:
            return
        ref: Dict[str, int] = {}
        for sess in self.broker.sessions.values():
            for flt in sess.subscriptions:
                f = self._strip_share(flt)
                ref[f] = ref.get(f, 0) + 1
        try:
            mirror = MirrorSyncStub(st.channel)
            items = sorted(ref.items())
            epoch = self.broker.router.epoch

            async def chunks():
                if not items:
                    yield pb.SnapshotChunk(epoch=epoch, last=True)
                for i in range(0, len(items), 1024):
                    part = items[i:i + 1024]
                    yield pb.SnapshotChunk(
                        epoch=epoch,
                        filters=[f for f, _ in part],
                        refcounts=[c for _, c in part],
                        last=i + 1024 >= len(items),
                    )

            ack = await asyncio.wait_for(
                mirror.InstallSnapshot(chunks()), timeout=st.spec.timeout * 4
            )
            log.info(
                "exhook server %s mirror snapshot: %d filters acked",
                st.spec.name, ack.n_filters,
            )
        except Exception as e:
            log.debug(
                "exhook server %s has no MirrorSync (%s) — hook-only feed",
                st.spec.name, e,
            )

    def _meta(self) -> pb.RequestMeta:
        return pb.RequestMeta(
            node=self.broker.node, version="0.1", sysdescr="emqx_tpu",
            cluster_name="emqx_tpu",
        )

    # ------------------------------------------------------------------
    # notification hooks (fire-and-forget over the sync hook bus)
    # ------------------------------------------------------------------

    def _register_notify_hooks(self) -> None:
        hooks = self.broker.hooks
        reg = [
            ("client.connected",
             lambda cid, info: self._notify("OnClientConnected",
                 pb.ClientConnectedRequest(clientinfo=self._clientinfo(cid),
                                           meta=self._meta()),
                 "client.connected")),
            ("client.disconnected",
             lambda cid, reason: self._notify("OnClientDisconnected",
                 pb.ClientDisconnectedRequest(clientinfo=self._clientinfo(cid),
                                              reason=str(reason),
                                              meta=self._meta()),
                 "client.disconnected")),
            ("session.created",
             lambda cid: self._notify("OnSessionCreated",
                 pb.SessionCreatedRequest(clientinfo=self._clientinfo(cid),
                                          meta=self._meta()),
                 "session.created")),
            # topic carries the routing filter ($share/<g>/ stripped — the
            # group rides subopts.share); the sidecar mirror matches on it
            ("session.subscribed",
             lambda cid, flt, opts, is_new: self._notify("OnSessionSubscribed",
                 pb.SessionSubscribedRequest(
                     clientinfo=self._clientinfo(cid),
                     topic=self._strip_share(flt),
                     subopts=pb.SubOpts(qos=opts.qos,
                                        share=opts.share or "",
                                        rh=opts.rh, rap=int(opts.rap),
                                        nl=int(opts.nl)),
                     meta=self._meta()),
                 "session.subscribed")),
            ("session.unsubscribed",
             lambda cid, flt: self._notify("OnSessionUnsubscribed",
                 pb.SessionUnsubscribedRequest(
                     clientinfo=self._clientinfo(cid),
                     topic=self._strip_share(flt),
                     meta=self._meta()),
                 "session.unsubscribed")),
            ("session.resumed",
             lambda cid: self._notify("OnSessionResumed",
                 pb.SessionResumedRequest(clientinfo=self._clientinfo(cid),
                                          meta=self._meta()),
                 "session.resumed")),
            ("session.discarded",
             lambda cid: self._notify("OnSessionDiscarded",
                 pb.SessionDiscardedRequest(clientinfo=self._clientinfo(cid),
                                            meta=self._meta()),
                 "session.discarded")),
            ("session.terminated",
             lambda cid: self._notify("OnSessionTerminated",
                 pb.SessionTerminatedRequest(clientinfo=self._clientinfo(cid),
                                             reason="terminated",
                                             meta=self._meta()),
                 "session.terminated")),
            ("message.delivered",
             lambda cid, msg: self._notify("OnMessageDelivered",
                 pb.MessageDeliveredRequest(clientinfo=self._clientinfo(cid),
                                            message=self._pb_msg(msg),
                                            meta=self._meta()),
                 "message.delivered")),
            ("message.acked",
             lambda cid, msg: self._notify("OnMessageAcked",
                 pb.MessageAckedRequest(clientinfo=self._clientinfo(cid),
                                        message=self._pb_msg(msg),
                                        meta=self._meta()),
                 "message.acked")),
            ("message.dropped",
             lambda msg, reason: self._notify("OnMessageDropped",
                 pb.MessageDroppedRequest(message=self._pb_msg(msg),
                                          reason=str(reason),
                                          meta=self._meta()),
                 "message.dropped")),
        ]
        self._hook_names = []
        for point, fn in reg:
            name = f"exhook.{point}"
            hooks.add(point, fn, priority=-100, name=name)  # after core hooks
            self._hook_names.append((point, name))

    def _unregister_notify_hooks(self) -> None:
        for point, name in self._hook_names:
            self.broker.hooks.delete(point, name)
        self._hook_names = []

    def _notify(self, method: str, req: Any, point: str) -> None:
        for st in self.servers:
            if st.stub is None or not st.wants(point):
                continue
            try:
                st.queue.put_nowait((method, req))
            except asyncio.QueueFull:
                st.dropped += 1

    async def _sender_loop(self, st: _ServerState) -> None:
        while True:
            method, req = await st.queue.get()
            try:
                await asyncio.wait_for(
                    getattr(st.stub, method)(req), timeout=st.spec.timeout
                )
                st.ok += 1
            except asyncio.CancelledError:
                raise
            except Exception:
                st.failed += 1

    # ------------------------------------------------------------------
    # advisory intercept (awaited by the connection loop pre-handle_in)
    # ------------------------------------------------------------------

    async def intercept(self, channel: Any, pkt: Any) -> Optional[List[Any]]:
        """Run advisory round trips for this packet.  Returns ``None`` to
        proceed with (a possibly mutated) ``pkt``, or a list of channel
        actions that replace normal handling (a deny)."""
        try:
            if pkt.type == P.CONNECT:
                if channel.state != "idle":
                    return None  # duplicate CONNECT: normal handling closes
                return await self._on_connect(channel, pkt)
            if pkt.type == P.PUBLISH and channel.state == "connected":
                return await self._on_publish(channel, pkt)
            if pkt.type == P.SUBSCRIBE and channel.state == "connected":
                return await self._on_subscribe(channel, pkt)
            if pkt.type == P.UNSUBSCRIBE and channel.state == "connected":
                self._notify_unsubscribe(channel, pkt)
        except Exception:
            log.exception("exhook intercept failed")
        return None

    async def _on_connect(self, channel, pkt) -> Optional[List[Any]]:
        conninfo = pb.ConnInfo(
            node=self.broker.node, clientid=pkt.clientid or "",
            username=pkt.username or "", peerhost=self._peerhost(channel),
            proto_name="MQTT", proto_ver=str(pkt.proto_ver),
            keepalive=pkt.keepalive,
        )
        self._notify("OnClientConnect",
                     pb.ClientConnectRequest(conninfo=conninfo,
                                             meta=self._meta()),
                     "client.connect")
        # fail-closed check first: a later deny-policy server that never
        # loaded must veto even if an earlier server would allow
        if any(self._down_deny(st) for st in self.servers):
            return channel.deny_in(pkt, P.RC.SERVER_UNAVAILABLE)
        for st in self.servers:
            if st.stub is None or not st.wants("client.authenticate"):
                continue
            req = pb.ClientAuthenticateRequest(
                clientinfo=pb.ClientInfo(
                    node=self.broker.node, clientid=pkt.clientid or "",
                    username=pkt.username or "",
                    password=(pkt.password or b"").decode("utf-8", "replace")
                    if isinstance(pkt.password, (bytes, bytearray))
                    else (pkt.password or ""),
                    peerhost=self._peerhost(channel),
                ),
                result=True, meta=self._meta(),
            )
            verdict = await self._advise(st, "OnClientAuthenticate", req)
            if verdict == "deny":
                return channel.deny_in(pkt, P.RC.NOT_AUTHORIZED)
            if verdict == "allow":
                break  # STOP_AND_RETURN true: short-circuit remaining servers
        return None

    async def _on_publish(self, channel, pkt) -> Optional[List[Any]]:
        # resolve v5 topic aliases so advisory rules see the real topic;
        # unresolvable (unknown alias / empty) → let the channel reject
        topic = channel.peek_topic(pkt)
        if topic is None:
            return None
        # fail-closed check covers BOTH advisory loops below (authorize and
        # message.publish), before any server's allow can short-circuit
        if any(self._down_deny(st) for st in self.servers):
            return channel.deny_in(pkt, P.RC.NOT_AUTHORIZED)
        for st in self.servers:
            if st.stub is None or not st.wants("client.authorize"):
                continue
            req = pb.ClientAuthorizeRequest(
                clientinfo=self._clientinfo(channel.clientid),
                type=pb.ClientAuthorizeRequest.PUBLISH,
                topic=topic, result=True, meta=self._meta(),
            )
            verdict = await self._advise(st, "OnClientAuthorize", req)
            if verdict == "deny":
                return channel.deny_in(pkt, P.RC.NOT_AUTHORIZED)
            if verdict == "allow":
                break
        for st in self.servers:
            if st.stub is None or not st.wants("message.publish"):
                continue
            req = pb.MessagePublishRequest(
                message=pb.Message(
                    node=self.broker.node, qos=pkt.qos,
                    **{"from": channel.clientid or ""},
                    topic=topic, payload=bytes(pkt.payload),
                    timestamp=int(time.time() * 1000),
                ),
                meta=self._meta(),
            )
            resp, err = await self._call(st, "OnMessagePublish", req)
            if err:
                if st.spec.failure_action == "deny":
                    return channel.deny_in(pkt, P.RC.UNSPECIFIED_ERROR)
                continue
            if resp.type == pb.ValuedResponse.STOP_AND_RETURN:
                if resp.WhichOneof("value") == "message":
                    m = resp.message
                    if m.headers.get("allow_publish") == "false":
                        pkt.allow_publish = False
                    else:
                        # mutate routed content only; the packet's QoS/ack
                        # flow and alias registration stay untouched (a QoS
                        # edit would desync the client's PUBACK/PUBREC
                        # expectations; a wire-topic edit would corrupt the
                        # alias map)
                        from .. import topic as T

                        if (
                            m.topic and m.topic != topic
                            and T.is_valid(m.topic, "name")
                        ):
                            pkt.route_topic = m.topic
                        pkt.payload = m.payload
                break
        return None

    async def _on_subscribe(self, channel, pkt) -> Optional[List[Any]]:
        filters = [
            pb.TopicFilter(name=flt, qos=o.get("qos", 0))
            for flt, o in pkt.topic_filters
        ]
        self._notify("OnClientSubscribe",
                     pb.ClientSubscribeRequest(
                         clientinfo=self._clientinfo(channel.clientid),
                         topic_filters=filters, meta=self._meta()),
                     "client.subscribe")
        if any(self._down_deny(st) for st in self.servers):
            pkt.denied_filters = set(range(len(pkt.topic_filters)))
            return None

        async def check(flt: str) -> bool:
            """True if this filter is denied.  Servers chain sequentially
            (chain semantics); independent filters run concurrently."""
            for st in self.servers:
                if st.stub is None or not st.wants("client.authorize"):
                    continue
                req = pb.ClientAuthorizeRequest(
                    clientinfo=self._clientinfo(channel.clientid),
                    type=pb.ClientAuthorizeRequest.SUBSCRIBE,
                    topic=flt, result=True, meta=self._meta(),
                )
                verdict = await self._advise(st, "OnClientAuthorize", req)
                if verdict == "deny":
                    return True
                if verdict == "allow":
                    return False
            return False

        verdicts = await asyncio.gather(
            *(check(flt) for flt, _ in pkt.topic_filters)
        )
        denied = {i for i, d in enumerate(verdicts) if d}
        if denied:
            pkt.denied_filters = denied
        return None

    def _notify_unsubscribe(self, channel, pkt) -> None:
        filters = [pb.TopicFilter(name=f) for f in pkt.topic_filters]
        self._notify("OnClientUnsubscribe",
                     pb.ClientUnsubscribeRequest(
                         clientinfo=self._clientinfo(channel.clientid),
                         topic_filters=filters, meta=self._meta()),
                     "client.unsubscribe")

    # ------------------------------------------------------------------

    def _down_deny(self, st: _ServerState) -> bool:
        """A deny-policy server that never loaded fails CLOSED: we don't
        know its hook set, so every advisory operation is refused until
        the reconnect loop brings it back."""
        return st.stub is None and st.spec.failure_action == "deny"

    async def _call(self, st: _ServerState, method: str, req) -> Tuple[Any, bool]:
        try:
            if _fi._injector is not None:
                # chaos seam: a raised call fault takes the server's
                # failure_action path (deny fails closed, ignore open);
                # a delay exercises the timeout handling
                act = _fi._injector.act("exhook.call")
                if act == "raise":
                    raise _fi.InjectedFault("exhook.call")
                if act == "delay":
                    await _fi._injector.pause()
            resp = await asyncio.wait_for(
                getattr(st.stub, method)(req), timeout=st.spec.timeout
            )
            st.ok += 1
            return resp, False
        except asyncio.CancelledError:
            raise
        except Exception as e:
            st.failed += 1
            log.debug("exhook %s %s failed: %s", st.spec.name, method, e)
            return None, True

    async def _advise(self, st: _ServerState, method: str, req) -> str:
        """Returns 'deny' | 'allow' (stop-and-return true) | 'continue'."""
        resp, err = await self._call(st, method, req)
        if err:
            return "deny" if st.spec.failure_action == "deny" else "continue"
        if resp.type == pb.ValuedResponse.STOP_AND_RETURN:
            if resp.WhichOneof("value") == "bool_result":
                return "allow" if resp.bool_result else "deny"
        return "continue"

    # ------------------------------------------------------------------

    @staticmethod
    def _strip_share(flt: str) -> str:
        from .. import topic as T

        share = T.parse_share(flt)
        return share[1] if share is not None else flt

    def _clientinfo(self, clientid: Optional[str]) -> pb.ClientInfo:
        cid = clientid or ""
        return pb.ClientInfo(
            node=self.broker.node, clientid=cid,
            username=self.broker.usernames.get(cid) or "",
        )

    def _pb_msg(self, msg: Any) -> pb.Message:
        return pb.Message(
            node=self.broker.node, id=str(getattr(msg, "id", "")),
            qos=getattr(msg, "qos", 0),
            **{"from": getattr(msg, "sender", "") or ""},
            topic=getattr(msg, "topic", ""),
            payload=bytes(getattr(msg, "payload", b"") or b""),
            timestamp=int(getattr(msg, "timestamp", time.time()) * 1000),
        )

    def _peerhost(self, channel) -> str:
        info = getattr(channel, "conninfo", None) or {}
        peer = info.get("peername") if isinstance(info, dict) else None
        return str(peer[0]) if isinstance(peer, (tuple, list)) and peer else ""

    def stats(self) -> List[dict]:
        return [
            {
                "name": st.spec.name, "url": st.spec.url,
                "hooks": list(st.hooks), "ok": st.ok, "failed": st.failed,
                "dropped": st.dropped,
                "connected": st.stub is not None,
            }
            for st in self.servers
        ]
