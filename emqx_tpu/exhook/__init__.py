"""ExHook-compatible gRPC extension boundary (SURVEY.md §2.3, §3.6).

* :mod:`~emqx_tpu.exhook.manager` — broker side: stream hook points to
  external HookProvider servers (advisory + notification semantics,
  per-server timeout and deny/ignore failure policy).
* :mod:`~emqx_tpu.exhook.server` — the TPU match sidecar: a
  HookProvider implementation keeping a device NFA mirror fresh from
  the subscription delta feed and serving micro-batched topic matches.
* :mod:`~emqx_tpu.exhook.rpc` — hand-written service glue over the
  ``protoc``-generated messages (``grpc_tools`` absent here).
"""

from .manager import ExHookManager, ServerSpec
from .rpc import pb

__all__ = ["ExHookManager", "ServerSpec", "pb"]
