#!/bin/sh
# Regenerate exhook_pb2.py from exhook.proto.  Plain protoc only —
# service stubs are hand-written in ../rpc.py (grpc_tools not available).
cd "$(dirname "$0")/../../.." || exit 1
exec protoc --python_out=emqx_tpu/exhook -Iemqx_tpu/exhook/protos \
    emqx_tpu/exhook/protos/exhook.proto
