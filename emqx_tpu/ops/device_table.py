"""Device twin of :class:`~emqx_tpu.ops.incremental.IncrementalNfa`.

The mria-replicant side of the mirror (SURVEY.md §2.2, §5.4): the host
table is authoritative; this class keeps the device copy fresh by
scatter-applying drained :class:`NfaDelta` batches **in place** (buffer
donation ⇒ no reallocation, no host↔device reshipping of the table) and
re-uploads only when shapes changed (table growth — rare, amortized).

Every delta ships as fixed-size scatter chunks so steady-state serving
reuses ONE compiled scatter per table shape (pre-warmed at upload) —
XLA recompiles are the p99 killer (SURVEY.md §7).

Threading model (for the asyncio serving path): host mutations and
``drain()`` happen on the owner (event-loop) thread; ``apply_pending``
and ``match`` may run on worker threads.  A lock serializes device-op
*dispatch* (donation invalidates the old buffers, so an unserialized
late dispatch could touch a deleted array); result readback happens
outside the lock.  ``arrays()`` returns one atomically-read tuple so a
reader never sees a half-applied (node, edge) pair.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .incremental import IncrementalNfa, NfaDelta
from .match_kernel import MatchResult, nfa_match, nfa_match_donated

__all__ = ["DeviceNfa", "PendingSync", "SCATTER_CHUNK"]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(tab, idx, rows):
    """tab[idx] = rows, in place (donated)."""
    return tab.at[idx].set(rows, mode="drop", unique_indices=False)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_vals(arr, idx, vals):
    """arr[idx] = vals for 1-D arrays, in place (donated) — the join
    relation's tombstone/revival path."""
    return arr.at[idx].set(vals, mode="drop", unique_indices=False)


# fixed scatter chunk: every delta ships as ceil(n/CHUNK) scatters of
# exactly CHUNK rows (padding repeats row 0 — same index, same contents,
# an idempotent no-op scatter).
SCATTER_CHUNK = 1024


def _chunks(idx: np.ndarray, rows: np.ndarray):
    n = len(idx)
    for lo in range(0, n, SCATTER_CHUNK):
        ci = idx[lo:lo + SCATTER_CHUNK]
        cr = rows[lo:lo + SCATTER_CHUNK]
        if len(ci) < SCATTER_CHUNK:
            pad = SCATTER_CHUNK - len(ci)
            ci = np.concatenate([ci, np.full(pad, ci[0], ci.dtype)])
            cr = np.concatenate([cr, np.tile(cr[0], (pad, 1))])
        yield ci, cr


def _chunks1(idx: np.ndarray, vals: np.ndarray):
    """1-D twin of :func:`_chunks` (join-relation value scatters):
    fixed-size chunks, padding repeats entry 0 (idempotent)."""
    n = len(idx)
    for lo in range(0, n, SCATTER_CHUNK):
        ci = idx[lo:lo + SCATTER_CHUNK]
        cv = vals[lo:lo + SCATTER_CHUNK]
        if len(ci) < SCATTER_CHUNK:
            pad = SCATTER_CHUNK - len(ci)
            ci = np.concatenate([ci, np.full(pad, ci[0], ci.dtype)])
            cv = np.concatenate([cv, np.full(pad, cv[0], cv.dtype)])
        yield ci, cv


class PendingSync(NamedTuple):
    """Drained host state, safe to apply from any thread: the arrays are
    stable copies, never aliases of the live mutable table."""

    delta: Optional[NfaDelta]          # in-place scatter path
    full: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]  # re-upload
    shape_key: Tuple[int, int, int]
    epoch: int
    # dirty-region grow path (``dirty_regions`` mode): a resized delta
    # whose node prefix is still valid on device ships only the grown
    # region + dirty rows; when the edge table was rehashed its full
    # contents ride here (node still grows in place).  A rehash also
    # drew FRESH seeds — they must ship with the table, or the device
    # keeps mixing with the old pair and every lookup misses (found by
    # the join backend's parity suite: the relation is seed-free, so
    # it kept answering while the hash kernel went dark).
    edge_full: Optional[np.ndarray] = None
    seeds_full: Optional[np.ndarray] = None

    @property
    def empty(self) -> bool:
        return self.full is None and (self.delta is None or self.delta.empty)


class DeviceNfa:
    """Live device mirror: ``sync()`` after host mutations, ``match()``
    to evaluate a batch.  Single-chip twin; the sharded path wraps the
    same arrays via ``parallel.sharded_match``."""

    def __init__(
        self,
        inc: "IncrementalNfa",
        active_slots: int = 16,
        max_matches: int = 32,
        device: Optional[jax.Device] = None,
        lazy: bool = False,
        compact_output: bool = True,
    ) -> None:
        # `inc` is any host table with the IncrementalNfa mutation/drain
        # surface — the Python IncrementalNfa or the native C++ NativeNfa
        # (emqx_tpu.native.nfa; exposes tables() instead of raw arrays)
        self.inc = inc
        self.active_slots = active_slots
        self.max_matches = max_matches
        self.compact_output = compact_output
        self.device = device
        self.epoch = -1
        self.uploads = 0        # full table uploads (growth / first sync)
        self.delta_applies = 0  # in-place scatter batches
        # dirty-region mode (streaming table lifecycle, opt-in): a table
        # resize grows the device buffers in place (pad + scatter the
        # tracked dirty rows) instead of re-shipping everything; above
        # dirty_full_threshold (dirty rows / total rows) the one
        # contiguous device_put wins and drain() falls back to it.
        # Requires a host table with track_regions (the Python
        # IncrementalNfa); the native table keeps the full-upload path.
        self.dirty_regions = False
        self.dirty_full_threshold = 0.5
        self.grow_applies = 0           # in-place grow resizes applied
        self.dirty_rows_uploaded = 0    # rows shipped by scatter/grow
        # optional shape-keyed AOT compile cache (ops/kernel_cache.py):
        # when set, match() dispatches through pre-compiled executables
        # so a table resize never stalls a serve batch on an XLA compile
        self.kernel_cache = None
        # relational-join backend (ops/join_match.py, opt-in): when
        # enabled the device ALSO mirrors the sorted edge relation so
        # match(backend="join") can serve; maintenance rides the same
        # drain/apply cycle (tombstone/overlay scatters per delta, one
        # rebuild on rehash/compact/overlay-overflow)
        self.join_enabled = False
        self._join = None                 # host JoinRelation
        self._jarrs = None                # device relation arrays
        self._join_seed = None            # (epoch, shape_key, arrays)
        self.join_rebuilds = 0            # full relation re-uploads
        self._shape_key = None
        self._arrs: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None
        self._lock = threading.Lock()
        # activate deferred accept-id reuse: freed aids stay tombstoned
        # until we ack the epoch that cleared their device rows
        inc.device_epoch = -1
        if not lazy:
            self.sync(full=True)

    # -- mirror maintenance ------------------------------------------------

    def _put(self, arr: np.ndarray) -> jax.Array:
        return (
            jax.device_put(arr, self.device)
            if self.device is not None
            else jnp.asarray(arr)
        )

    def arrays(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(node_tab, edge_tab, seeds) — one consistent epoch's view."""
        arrs = self._arrs
        if arrs is None:
            raise RuntimeError("DeviceNfa not synced yet (lazy init)")
        return arrs

    # expose the individual arrays for introspection / graft entry
    @property
    def node_tab(self):
        return self.arrays()[0]

    @property
    def edge_tab(self):
        return self.arrays()[1]

    @property
    def seeds(self):
        return self.arrays()[2]

    def drain(self, full: bool = False) -> PendingSync:
        """OWNER-THREAD step: flush host dirty state into a stable,
        thread-safe :class:`PendingSync`.  O(delta) except when a full
        upload is needed (first sync / growth), which copies the table.
        In ``dirty_regions`` mode a growth resize whose dirty sets
        survived (track_regions host table) ships as a grow-in-place
        sync instead — O(dirty) + the rehashed edge table at most."""
        delta = self.inc.flush()
        if not full and delta.resized and self._grow_ok(delta):
            key = self.inc.shape_key()
            rehash = delta.edges_rehashed or key[1] != self._shape_key[1]
            return PendingSync(
                delta=delta, full=None, shape_key=key, epoch=delta.epoch,
                edge_full=self.inc.edge_tab.copy() if rehash else None,
                seeds_full=self.inc.seeds.copy() if rehash else None,
            )
        if full or delta.resized or self._shape_key != self.inc.shape_key():
            if hasattr(self.inc, "tables"):  # native table: one export
                tabs = self.inc.tables()
            else:
                tabs = (
                    self.inc.node_tab.copy(),
                    self.inc.edge_tab.copy(),
                    self.inc.seeds.copy(),
                )
            return PendingSync(
                delta=None,
                full=tabs,
                shape_key=self.inc.shape_key(),
                epoch=self.inc.epoch,
            )
        return PendingSync(
            delta=delta, full=None,
            shape_key=self.inc.shape_key(), epoch=delta.epoch,
        )

    def _grow_ok(self, delta: NfaDelta) -> bool:
        """May this resized delta ride the grow-in-place path?  Needs the
        mode on, a synced device twin whose node prefix matches the
        delta's valid-prefix marker, an unchanged depth, and a dirty
        fraction below the measured full-upload crossover."""
        if not self.dirty_regions or self._shape_key is None \
                or self._arrs is None:
            return False
        if delta.node_grown_from < 0 \
                or delta.node_grown_from != self._shape_key[0]:
            return False
        key = self.inc.shape_key()
        if key[2] != self._shape_key[2]:
            return False
        n_dirty = len(delta.state_idx) + len(delta.bucket_idx)
        return n_dirty <= self.dirty_full_threshold * (key[0] + key[1])

    def apply_pending(self, p: PendingSync) -> bool:
        """ANY-THREAD step: ship a drained sync to the device.

        On ANY failure the mirror is poisoned (``_arrs`` dropped,
        shape key cleared): a partial apply may have donated-away live
        buffers, and the drained delta is already lost from the host
        dirty sets — the next ``drain()`` therefore returns a full
        re-upload, and matches until then fail fast to the host path."""
        with self._lock:
            try:
                return self._apply_locked(p)
            except Exception:
                self._arrs = None
                self._shape_key = None  # force full re-upload next drain
                self._join = None       # relation rebuilt with the table
                self._jarrs = None
                raise

    def _apply_locked(self, p: PendingSync) -> bool:
        if p.full is not None:
            node = self._put(p.full[0])
            edge = self._put(p.full[1])
            seeds = self._put(p.full[2])
            self._shape_key = p.shape_key
            self.uploads += 1
            node, edge = self._warm_scatter(node, edge, p.full)
            self._arrs = (node, edge, seeds)
            if self.join_enabled:
                self._join_full(p)
            self.epoch = p.epoch
            self.inc.device_epoch = p.epoch
            return True
        if p.delta is None or p.delta.empty:
            self.epoch = max(self.epoch, p.epoch)
            self.inc.device_epoch = max(
                self.inc.device_epoch or -1, p.epoch
            )
            return False
        if p.delta.resized:
            return self._apply_grow(p)
        node, edge, seeds = self._arrs
        for idx, rows in _chunks(p.delta.state_idx, p.delta.state_rows):
            node = _scatter_rows(node, self._put(idx), self._put(rows))
        for idx, rows in _chunks(p.delta.bucket_idx, p.delta.bucket_rows):
            edge = _scatter_rows(edge, self._put(idx), self._put(rows))
        self._arrs = (node, edge, seeds)
        if self.join_enabled and self._join is not None:
            self._join_delta(p.delta)
        self.epoch = p.delta.epoch
        self.inc.device_epoch = p.delta.epoch
        self.delta_applies += 1
        self.dirty_rows_uploaded += (
            len(p.delta.state_idx) + len(p.delta.bucket_idx))
        return True

    def _apply_grow(self, p: PendingSync) -> bool:
        """Grow-in-place resize: pad the node table device-side to the
        new S (no h2d traffic for the surviving prefix), swap in the
        rehashed edge table when it moved, then scatter the tracked
        dirty rows — replacing the whole-table ``device_put`` the old
        resize path paid (25–107 s at 10M filters, BENCH_r03/r05)."""
        node, edge, seeds = self._arrs
        target_s, target_hb, _d = p.shape_key
        if int(node.shape[0]) != p.delta.node_grown_from:
            # base mismatch (missed sync): poison via the caller's
            # except path — the next drain ships full tables
            raise RuntimeError(
                f"grow-in-place base mismatch: device S={node.shape[0]} "
                f"!= host prefix {p.delta.node_grown_from}")
        grow = target_s - int(node.shape[0])
        if grow > 0:
            pad = jnp.broadcast_to(
                jnp.asarray([-1, -1, -1, 0], jnp.int32), (grow, 4))
            node = jnp.concatenate([node, pad], axis=0)
        if p.edge_full is not None:
            edge = self._put(p.edge_full)
            if p.seeds_full is not None:
                seeds = self._put(p.seeds_full)
        elif int(edge.shape[0]) != target_hb:
            raise RuntimeError(
                f"grow-in-place edge mismatch: device Hb={edge.shape[0]} "
                f"!= host {target_hb} with no rehashed table shipped")
        for idx, rows in _chunks(p.delta.state_idx, p.delta.state_rows):
            node = _scatter_rows(node, self._put(idx), self._put(rows))
        for idx, rows in _chunks(p.delta.bucket_idx, p.delta.bucket_rows):
            edge = _scatter_rows(edge, self._put(idx), self._put(rows))
        self._shape_key = p.shape_key
        self._arrs = (node, edge, seeds)
        if self.join_enabled and self._join is not None:
            if p.edge_full is not None:
                # cuckoo rehash: the relation's CAPACITY moved with Hb,
                # so rebuild from the shipped table (note the edge SET
                # often barely changed — the rebuild is the capacity
                # resize, same amortized class as the rehash itself)
                self._join.rebuild(target_s, p.edge_full)
                self._put_join()
            else:
                self._join.grow_states(target_s)
                ss, ew, en, ov = self._jarrs
                grow_ss = (target_s + 1) - int(ss.shape[0])
                if grow_ss > 0:
                    # new states have no CSR segment: pad the offsets
                    # device-side with the terminal value (no h2d for
                    # the surviving prefix — the grow-in-place idiom)
                    ss = jnp.concatenate(
                        [ss, jnp.broadcast_to(ss[-1:], (grow_ss,))])
                self._jarrs = (ss, ew, en, ov)
                self._join_delta(p.delta)
        self.epoch = p.delta.epoch
        self.inc.device_epoch = p.delta.epoch
        self.grow_applies += 1
        self.dirty_rows_uploaded += (
            len(p.delta.state_idx) + len(p.delta.bucket_idx))
        return True

    def sync(self, full: bool = False) -> bool:
        """Single-threaded convenience: drain + apply in one call."""
        return self.apply_pending(self.drain(full=full))

    # -- join-relation mirror (ops/join_match.py, opt-in) ------------------

    def enable_join(self, seed=None) -> None:
        """Turn the sorted-relation mirror on.  ``seed`` is an optional
        ``(epoch, shape_key, (state_start, edge_word, edge_next))``
        tuple from a persisted segment — used at the next full upload
        iff the epoch still matches (skips the build sort).  On an
        ALREADY-synced twin the relation builds now, from the device
        copy of the edge table (the truth the kernels see)."""
        self.join_enabled = True
        self._join_seed = seed
        if self._arrs is not None and self._jarrs is None:
            from .join_match import JoinRelation

            node, edge, _seeds = self._arrs
            self._join = JoinRelation(
                int(node.shape[0]), np.asarray(jax.device_get(edge)))
            self._put_join()

    def _join_full(self, p: PendingSync) -> None:
        """Full-upload half of the relation mirror: seed from a
        persisted segment when provably fresh, else one lexsort."""
        from .join_match import JoinRelation

        s = int(p.full[0].shape[0])
        seed = self._join_seed
        self._join_seed = None
        self._join = None
        if seed is not None and seed[0] == p.epoch \
                and tuple(seed[1]) == tuple(p.shape_key):
            try:
                self._join = JoinRelation(s, p.full[1], arrays=seed[2])
            except ValueError:
                self._join = None  # malformed seed: sort fresh below
        if self._join is None:
            self._join = JoinRelation(s, p.full[1])
        self._put_join()

    def _put_join(self) -> None:
        """Ship the whole relation + warm its scatter shapes (the same
        pre-pay idiom as ``_warm_scatter``)."""
        start, word, nxt, overlay = self._join.arrays()
        ss = self._put(start)
        ew = self._put(word)
        en = self._put(nxt)
        ov = self._put(overlay)
        z = self._put(np.zeros(SCATTER_CHUNK, np.int32))
        en = _scatter_vals(
            en, z, self._put(np.full(SCATTER_CHUNK, nxt[0], np.int32)))
        ov = _scatter_rows(
            ov, z, self._put(np.tile(overlay[0], (SCATTER_CHUNK, 1))))
        self._jarrs = (ss, ew, en, ov)
        self.join_rebuilds += 1

    def _join_delta(self, delta: NfaDelta) -> None:
        """Delta half: tombstone/revival scatters on ``edge_next`` +
        overlay row writes — O(changed edges) d2h, zero for the node
        side.  Overlay overflow (or shadow drift) rebuilds from the
        already-updated shadow."""
        from .join_match import OverlayFull

        try:
            mpos, mval, opos, orows = self._join.apply_bucket_delta(
                delta.bucket_idx, delta.bucket_rows)
        except OverlayFull:
            self._join.rebuild(len(self._join.state_start) - 1)
            self._put_join()
            return
        ss, ew, en, ov = self._jarrs
        for idx, vals in _chunks1(mpos, mval):
            en = _scatter_vals(en, self._put(idx), self._put(vals))
        for idx, rows in _chunks(opos, orows):
            ov = _scatter_rows(ov, self._put(idx), self._put(rows))
        self._jarrs = (ss, ew, en, ov)
        self.dirty_rows_uploaded += len(mpos) + len(opos)

    def _warm_scatter(self, node, edge, full):
        """Pre-pay the scatter compiles for the current shapes so the
        first real delta lands at steady-state latency.  The warm writes
        are idempotent (row 0 rewritten with its own contents)."""
        z = np.zeros(SCATTER_CHUNK, np.int32)
        node = _scatter_rows(
            node, self._put(z),
            self._put(np.tile(full[0][0], (SCATTER_CHUNK, 1))),
        )
        edge = _scatter_rows(
            edge, self._put(z),
            self._put(np.tile(full[1][0], (SCATTER_CHUNK, 1))),
        )
        return node, edge

    # -- serving -----------------------------------------------------------

    def match(self, words, lens, is_sys, *,
              flat_cap: int = 0, block_compile: bool = True,
              donate_inputs: bool = False,
              backend: Optional[str] = None) -> MatchResult:
        """Run the kernel on already-encoded operands.  Dispatch happens
        under the device lock; the returned arrays are futures — callers
        block (np.asarray) outside any lock.  ``flat_cap`` > 0 selects
        the flat compacted output (minimal-readback serving mode; see
        match_kernel.decode_flat).  With a kernel cache attached and
        ``block_compile=False``, an uncompiled shape raises
        :class:`~emqx_tpu.ops.kernel_cache.CompileMiss` instead of
        stalling the caller behind XLA (serving fail-open contract).
        ``donate_inputs`` hands the batch operand buffers to the kernel
        (the pipelined serve chain's idiom — the caller must not touch
        words/lens/is_sys afterwards; same donation contract as
        ``_scatter_rows``).  ``backend`` selects the edge-structure
        kernel ("hash" default; "join" rides the sorted-relation mirror
        and silently falls back to hash while the relation is not yet
        mirrored — both kernels answer identically; "join-pallas" walks
        the same relation with the fused Pallas kernel and falls back
        to "join" when the shape doesn't fit its tiling contract —
        flat output only, batch a multiple of its tile)."""
        with self._lock:
            node, edge, seeds = self.arrays()
            be = backend or "hash"
            if be in ("join", "join-pallas") and self._jarrs is None:
                be = "hash"
            if be == "join-pallas":
                from .pallas_match import TILE_B

                b = int(words.shape[0])
                if flat_cap <= 0 or b % min(TILE_B, b):
                    be = "join"
            kc = self.kernel_cache
            if kc is not None and self.device is None:
                fn = kc.executable(
                    tuple(words.shape), int(node.shape[0]),
                    int(edge.shape[0]),
                    active_slots=self.active_slots,
                    max_matches=self.max_matches,
                    compact_output=self.compact_output,
                    flat_cap=flat_cap,
                    donate=donate_inputs,
                    backend=be,
                    block=block_compile,
                )
                if be in ("join", "join-pallas"):
                    return fn(words, lens, is_sys, node, *self._jarrs)
                return fn(words, lens, is_sys, node, edge, seeds)
            if be == "join-pallas":
                import jax

                from .pallas_match import pallas_join_match_flat

                return pallas_join_match_flat(
                    words, lens, is_sys, node, *self._jarrs,
                    depth=int(words.shape[1]),
                    active_slots=self.active_slots,
                    max_matches=self.max_matches,
                    flat_cap=flat_cap,
                    interpret=(jax.default_backend() != "tpu"),
                )
            if be == "join":
                from .join_match import join_match, join_match_donated

                jfn = join_match_donated if donate_inputs else join_match
                return jfn(
                    words, lens, is_sys, node, *self._jarrs,
                    active_slots=self.active_slots,
                    max_matches=self.max_matches,
                    compact_output=self.compact_output,
                    flat_cap=flat_cap,
                )
            fn = nfa_match_donated if donate_inputs else nfa_match
            return fn(
                words, lens, is_sys, node, edge, seeds,
                active_slots=self.active_slots,
                max_matches=self.max_matches,
                compact_output=self.compact_output,
                flat_cap=flat_cap,
            )

    def match_names(self, names: Sequence[str], batch: Optional[int] = None):
        """Encode + match a batch of topic names (encode must run on the
        owner thread — it reads the live vocab)."""
        from .encode import encode_batch

        words, lens, is_sys = encode_batch(self.inc, names, batch=batch)
        return self.match(
            self._put(words), self._put(lens), self._put(is_sys)
        )
