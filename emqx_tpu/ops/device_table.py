"""Device twin of :class:`~emqx_tpu.ops.incremental.IncrementalNfa`.

The mria-replicant side of the mirror (SURVEY.md §2.2, §5.4): the host
table is authoritative; this class keeps the device copy fresh by
scatter-applying drained :class:`NfaDelta` batches **in place** (buffer
donation ⇒ no reallocation, no host↔device reshipping of the table) and
re-uploads only when shapes changed (table growth — rare, amortized).

Every delta ships as fixed-size scatter chunks so steady-state serving
reuses ONE compiled scatter per table shape (pre-warmed at upload) —
XLA recompiles are the p99 killer (SURVEY.md §7).

Threading model (for the asyncio serving path): host mutations and
``drain()`` happen on the owner (event-loop) thread; ``apply_pending``
and ``match`` may run on worker threads.  A lock serializes device-op
*dispatch* (donation invalidates the old buffers, so an unserialized
late dispatch could touch a deleted array); result readback happens
outside the lock.  ``arrays()`` returns one atomically-read tuple so a
reader never sees a half-applied (node, edge) pair.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .incremental import IncrementalNfa, NfaDelta
from .match_kernel import MatchResult, nfa_match, nfa_match_donated

__all__ = ["DeviceNfa", "PendingSync", "SCATTER_CHUNK"]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(tab, idx, rows):
    """tab[idx] = rows, in place (donated)."""
    return tab.at[idx].set(rows, mode="drop", unique_indices=False)


# fixed scatter chunk: every delta ships as ceil(n/CHUNK) scatters of
# exactly CHUNK rows (padding repeats row 0 — same index, same contents,
# an idempotent no-op scatter).
SCATTER_CHUNK = 1024


def _chunks(idx: np.ndarray, rows: np.ndarray):
    n = len(idx)
    for lo in range(0, n, SCATTER_CHUNK):
        ci = idx[lo:lo + SCATTER_CHUNK]
        cr = rows[lo:lo + SCATTER_CHUNK]
        if len(ci) < SCATTER_CHUNK:
            pad = SCATTER_CHUNK - len(ci)
            ci = np.concatenate([ci, np.full(pad, ci[0], ci.dtype)])
            cr = np.concatenate([cr, np.tile(cr[0], (pad, 1))])
        yield ci, cr


class PendingSync(NamedTuple):
    """Drained host state, safe to apply from any thread: the arrays are
    stable copies, never aliases of the live mutable table."""

    delta: Optional[NfaDelta]          # in-place scatter path
    full: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]  # re-upload
    shape_key: Tuple[int, int, int]
    epoch: int
    # dirty-region grow path (``dirty_regions`` mode): a resized delta
    # whose node prefix is still valid on device ships only the grown
    # region + dirty rows; when the edge table was rehashed its full
    # contents ride here (node still grows in place).
    edge_full: Optional[np.ndarray] = None

    @property
    def empty(self) -> bool:
        return self.full is None and (self.delta is None or self.delta.empty)


class DeviceNfa:
    """Live device mirror: ``sync()`` after host mutations, ``match()``
    to evaluate a batch.  Single-chip twin; the sharded path wraps the
    same arrays via ``parallel.sharded_match``."""

    def __init__(
        self,
        inc: "IncrementalNfa",
        active_slots: int = 16,
        max_matches: int = 32,
        device: Optional[jax.Device] = None,
        lazy: bool = False,
        compact_output: bool = True,
    ) -> None:
        # `inc` is any host table with the IncrementalNfa mutation/drain
        # surface — the Python IncrementalNfa or the native C++ NativeNfa
        # (emqx_tpu.native.nfa; exposes tables() instead of raw arrays)
        self.inc = inc
        self.active_slots = active_slots
        self.max_matches = max_matches
        self.compact_output = compact_output
        self.device = device
        self.epoch = -1
        self.uploads = 0        # full table uploads (growth / first sync)
        self.delta_applies = 0  # in-place scatter batches
        # dirty-region mode (streaming table lifecycle, opt-in): a table
        # resize grows the device buffers in place (pad + scatter the
        # tracked dirty rows) instead of re-shipping everything; above
        # dirty_full_threshold (dirty rows / total rows) the one
        # contiguous device_put wins and drain() falls back to it.
        # Requires a host table with track_regions (the Python
        # IncrementalNfa); the native table keeps the full-upload path.
        self.dirty_regions = False
        self.dirty_full_threshold = 0.5
        self.grow_applies = 0           # in-place grow resizes applied
        self.dirty_rows_uploaded = 0    # rows shipped by scatter/grow
        # optional shape-keyed AOT compile cache (ops/kernel_cache.py):
        # when set, match() dispatches through pre-compiled executables
        # so a table resize never stalls a serve batch on an XLA compile
        self.kernel_cache = None
        self._shape_key = None
        self._arrs: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None
        self._lock = threading.Lock()
        # activate deferred accept-id reuse: freed aids stay tombstoned
        # until we ack the epoch that cleared their device rows
        inc.device_epoch = -1
        if not lazy:
            self.sync(full=True)

    # -- mirror maintenance ------------------------------------------------

    def _put(self, arr: np.ndarray) -> jax.Array:
        return (
            jax.device_put(arr, self.device)
            if self.device is not None
            else jnp.asarray(arr)
        )

    def arrays(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(node_tab, edge_tab, seeds) — one consistent epoch's view."""
        arrs = self._arrs
        if arrs is None:
            raise RuntimeError("DeviceNfa not synced yet (lazy init)")
        return arrs

    # expose the individual arrays for introspection / graft entry
    @property
    def node_tab(self):
        return self.arrays()[0]

    @property
    def edge_tab(self):
        return self.arrays()[1]

    @property
    def seeds(self):
        return self.arrays()[2]

    def drain(self, full: bool = False) -> PendingSync:
        """OWNER-THREAD step: flush host dirty state into a stable,
        thread-safe :class:`PendingSync`.  O(delta) except when a full
        upload is needed (first sync / growth), which copies the table.
        In ``dirty_regions`` mode a growth resize whose dirty sets
        survived (track_regions host table) ships as a grow-in-place
        sync instead — O(dirty) + the rehashed edge table at most."""
        delta = self.inc.flush()
        if not full and delta.resized and self._grow_ok(delta):
            key = self.inc.shape_key()
            rehash = delta.edges_rehashed or key[1] != self._shape_key[1]
            return PendingSync(
                delta=delta, full=None, shape_key=key, epoch=delta.epoch,
                edge_full=self.inc.edge_tab.copy() if rehash else None,
            )
        if full or delta.resized or self._shape_key != self.inc.shape_key():
            if hasattr(self.inc, "tables"):  # native table: one export
                tabs = self.inc.tables()
            else:
                tabs = (
                    self.inc.node_tab.copy(),
                    self.inc.edge_tab.copy(),
                    self.inc.seeds.copy(),
                )
            return PendingSync(
                delta=None,
                full=tabs,
                shape_key=self.inc.shape_key(),
                epoch=self.inc.epoch,
            )
        return PendingSync(
            delta=delta, full=None,
            shape_key=self.inc.shape_key(), epoch=delta.epoch,
        )

    def _grow_ok(self, delta: NfaDelta) -> bool:
        """May this resized delta ride the grow-in-place path?  Needs the
        mode on, a synced device twin whose node prefix matches the
        delta's valid-prefix marker, an unchanged depth, and a dirty
        fraction below the measured full-upload crossover."""
        if not self.dirty_regions or self._shape_key is None \
                or self._arrs is None:
            return False
        if delta.node_grown_from < 0 \
                or delta.node_grown_from != self._shape_key[0]:
            return False
        key = self.inc.shape_key()
        if key[2] != self._shape_key[2]:
            return False
        n_dirty = len(delta.state_idx) + len(delta.bucket_idx)
        return n_dirty <= self.dirty_full_threshold * (key[0] + key[1])

    def apply_pending(self, p: PendingSync) -> bool:
        """ANY-THREAD step: ship a drained sync to the device.

        On ANY failure the mirror is poisoned (``_arrs`` dropped,
        shape key cleared): a partial apply may have donated-away live
        buffers, and the drained delta is already lost from the host
        dirty sets — the next ``drain()`` therefore returns a full
        re-upload, and matches until then fail fast to the host path."""
        with self._lock:
            try:
                return self._apply_locked(p)
            except Exception:
                self._arrs = None
                self._shape_key = None  # force full re-upload next drain
                raise

    def _apply_locked(self, p: PendingSync) -> bool:
        if p.full is not None:
            node = self._put(p.full[0])
            edge = self._put(p.full[1])
            seeds = self._put(p.full[2])
            self._shape_key = p.shape_key
            self.uploads += 1
            node, edge = self._warm_scatter(node, edge, p.full)
            self._arrs = (node, edge, seeds)
            self.epoch = p.epoch
            self.inc.device_epoch = p.epoch
            return True
        if p.delta is None or p.delta.empty:
            self.epoch = max(self.epoch, p.epoch)
            self.inc.device_epoch = max(
                self.inc.device_epoch or -1, p.epoch
            )
            return False
        if p.delta.resized:
            return self._apply_grow(p)
        node, edge, seeds = self._arrs
        for idx, rows in _chunks(p.delta.state_idx, p.delta.state_rows):
            node = _scatter_rows(node, self._put(idx), self._put(rows))
        for idx, rows in _chunks(p.delta.bucket_idx, p.delta.bucket_rows):
            edge = _scatter_rows(edge, self._put(idx), self._put(rows))
        self._arrs = (node, edge, seeds)
        self.epoch = p.delta.epoch
        self.inc.device_epoch = p.delta.epoch
        self.delta_applies += 1
        self.dirty_rows_uploaded += (
            len(p.delta.state_idx) + len(p.delta.bucket_idx))
        return True

    def _apply_grow(self, p: PendingSync) -> bool:
        """Grow-in-place resize: pad the node table device-side to the
        new S (no h2d traffic for the surviving prefix), swap in the
        rehashed edge table when it moved, then scatter the tracked
        dirty rows — replacing the whole-table ``device_put`` the old
        resize path paid (25–107 s at 10M filters, BENCH_r03/r05)."""
        node, edge, seeds = self._arrs
        target_s, target_hb, _d = p.shape_key
        if int(node.shape[0]) != p.delta.node_grown_from:
            # base mismatch (missed sync): poison via the caller's
            # except path — the next drain ships full tables
            raise RuntimeError(
                f"grow-in-place base mismatch: device S={node.shape[0]} "
                f"!= host prefix {p.delta.node_grown_from}")
        grow = target_s - int(node.shape[0])
        if grow > 0:
            pad = jnp.broadcast_to(
                jnp.asarray([-1, -1, -1, 0], jnp.int32), (grow, 4))
            node = jnp.concatenate([node, pad], axis=0)
        if p.edge_full is not None:
            edge = self._put(p.edge_full)
        elif int(edge.shape[0]) != target_hb:
            raise RuntimeError(
                f"grow-in-place edge mismatch: device Hb={edge.shape[0]} "
                f"!= host {target_hb} with no rehashed table shipped")
        for idx, rows in _chunks(p.delta.state_idx, p.delta.state_rows):
            node = _scatter_rows(node, self._put(idx), self._put(rows))
        for idx, rows in _chunks(p.delta.bucket_idx, p.delta.bucket_rows):
            edge = _scatter_rows(edge, self._put(idx), self._put(rows))
        self._shape_key = p.shape_key
        self._arrs = (node, edge, seeds)
        self.epoch = p.delta.epoch
        self.inc.device_epoch = p.delta.epoch
        self.grow_applies += 1
        self.dirty_rows_uploaded += (
            len(p.delta.state_idx) + len(p.delta.bucket_idx))
        return True

    def sync(self, full: bool = False) -> bool:
        """Single-threaded convenience: drain + apply in one call."""
        return self.apply_pending(self.drain(full=full))

    def _warm_scatter(self, node, edge, full):
        """Pre-pay the scatter compiles for the current shapes so the
        first real delta lands at steady-state latency.  The warm writes
        are idempotent (row 0 rewritten with its own contents)."""
        z = np.zeros(SCATTER_CHUNK, np.int32)
        node = _scatter_rows(
            node, self._put(z),
            self._put(np.tile(full[0][0], (SCATTER_CHUNK, 1))),
        )
        edge = _scatter_rows(
            edge, self._put(z),
            self._put(np.tile(full[1][0], (SCATTER_CHUNK, 1))),
        )
        return node, edge

    # -- serving -----------------------------------------------------------

    def match(self, words, lens, is_sys, *,
              flat_cap: int = 0, block_compile: bool = True,
              donate_inputs: bool = False) -> MatchResult:
        """Run the kernel on already-encoded operands.  Dispatch happens
        under the device lock; the returned arrays are futures — callers
        block (np.asarray) outside any lock.  ``flat_cap`` > 0 selects
        the flat compacted output (minimal-readback serving mode; see
        match_kernel.decode_flat).  With a kernel cache attached and
        ``block_compile=False``, an uncompiled shape raises
        :class:`~emqx_tpu.ops.kernel_cache.CompileMiss` instead of
        stalling the caller behind XLA (serving fail-open contract).
        ``donate_inputs`` hands the batch operand buffers to the kernel
        (the pipelined serve chain's idiom — the caller must not touch
        words/lens/is_sys afterwards; same donation contract as
        ``_scatter_rows``)."""
        with self._lock:
            node, edge, seeds = self.arrays()
            kc = self.kernel_cache
            if kc is not None and self.device is None:
                fn = kc.executable(
                    tuple(words.shape), int(node.shape[0]),
                    int(edge.shape[0]),
                    active_slots=self.active_slots,
                    max_matches=self.max_matches,
                    compact_output=self.compact_output,
                    flat_cap=flat_cap,
                    donate=donate_inputs,
                    block=block_compile,
                )
                return fn(words, lens, is_sys, node, edge, seeds)
            fn = nfa_match_donated if donate_inputs else nfa_match
            return fn(
                words, lens, is_sys, node, edge, seeds,
                active_slots=self.active_slots,
                max_matches=self.max_matches,
                compact_output=self.compact_output,
                flat_cap=flat_cap,
            )

    def match_names(self, names: Sequence[str], batch: Optional[int] = None):
        """Encode + match a batch of topic names (encode must run on the
        owner thread — it reads the live vocab)."""
        from .encode import encode_batch

        words, lens, is_sys = encode_batch(self.inc, names, batch=batch)
        return self.match(
            self._put(words), self._put(lens), self._put(is_sys)
        )
