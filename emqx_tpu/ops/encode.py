"""Batch topic encoding for the match kernel — the serving-path front.

Round 1 measured the pure-Python per-word dict loop at ~82% of the
per-batch budget (VERDICT.md weak item 3); this module replaces it with
the native C++ tokenizer/interner (``emqx_tpu/native/encoder.cpp``,
loaded via ctypes) and keeps the Python loop as a fallback with
identical output.

An encoder instance is cached per vocab *object* (the vocab is
append-only between compactions, so new words are pushed incrementally;
a compaction swaps the dict instance, which drops the cache entry).
"""

from __future__ import annotations

import ctypes
import logging
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .. import topic as T

log = logging.getLogger(__name__)

__all__ = ["TopicEncoder", "encode_batch"]

_lib = None
_lib_tried = False


def _native():
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        from ..native import load_library

        lib = load_library("encoder")
        if lib is not None:
            lib.enc_new.restype = ctypes.c_void_p
            lib.enc_free.argtypes = [ctypes.c_void_p]
            lib.enc_add_words.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int32,
            ]
            lib.enc_vocab_size.argtypes = [ctypes.c_void_p]
            lib.enc_vocab_size.restype = ctypes.c_int64
            lib.enc_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.enc_encode.restype = ctypes.c_int32
        _lib = lib
    return _lib


class TopicEncoder:
    """Vocab-bound encoder; push-incremental, native when available."""

    def __init__(self, vocab: Dict[str, int]) -> None:
        self.vocab = vocab
        self._pushed = 0
        self._h = None
        lib = _native()
        if lib is not None:
            self._h = ctypes.c_void_p(lib.enc_new())

    def __del__(self):  # pragma: no cover - interpreter teardown order
        lib = _lib
        if lib is not None and self._h:
            try:
                lib.enc_free(self._h)
            except Exception:
                pass

    def _push_new_words(self) -> None:
        """Ship vocab entries added since the last call (dict preserves
        insertion order; interning only appends)."""
        n = len(self.vocab)
        if n == self._pushed:
            return
        items = list(self.vocab.items())[self._pushed:]
        buf = b"\x00".join(w.encode("utf-8") for w, _ in items)
        ids = np.fromiter((i for _, i in items), np.int32, len(items))
        _lib.enc_add_words(
            self._h, buf, len(buf),
            ids.ctypes.data_as(ctypes.c_void_p), len(items),
        )
        self._pushed = n

    def encode(
        self, names: Sequence[str], depth: int, batch: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Mirror of the round-1 ``encode_topics`` contract: returns
        ``(words (B,D) int32, lens (B,) int32, is_sys (B,) bool)`` with
        inert padding rows (len sentinel D+2, is_sys True, UNKNOWN words).
        """
        D = depth
        B = batch if batch is not None else len(names)
        n = len(names)
        if n > B:
            raise ValueError(f"{n} topics > batch {B}")
        words = np.zeros((B, D), np.int32)
        lens = np.full(B, D + 2, np.int32)
        is_sys = np.ones(B, bool)
        if n == 0:
            return words, lens, is_sys
        if self._h is not None:
            self._push_new_words()
            joined = "\x00".join(names).encode("utf-8")
            sys8 = np.zeros(n, np.uint8)
            done = _lib.enc_encode(
                self._h, joined, len(joined), n, D,
                words.ctypes.data_as(ctypes.c_void_p),
                lens.ctypes.data_as(ctypes.c_void_p),
                sys8.ctypes.data_as(ctypes.c_void_p),
            )
            if done == n:
                is_sys[:n] = sys8.astype(bool)
                return words, lens, is_sys
            # a topic smuggled a NUL (forbidden in MQTT): the segment
            # count diverged, which would row-shift other topics'
            # answers — fall back for the whole batch
            log.warning("native encode rejected batch (%d); falling back",
                        done)
            words[:n] = 0
            lens[:n] = D + 2
        vocab = self.vocab
        for r, name in enumerate(names):
            ws = T.words(name)
            lens[r] = min(len(ws), D + 1)
            is_sys[r] = name.startswith("$")
            for i, w in enumerate(ws[:D]):
                words[r, i] = vocab.get(w, 0)
        return words, lens, is_sys


def encode_batch(
    table, names: Sequence[str], batch: Optional[int] = None,
    depth: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode against any table-like with ``.vocab`` and ``.depth``
    (NfaTable, IncrementalNfa).  The encoder rides on the table object
    itself and is rebuilt when the vocab instance is swapped
    (compaction), so its lifetime exactly tracks the table's."""
    enc = getattr(table, "_topic_encoder", None)
    if enc is None or enc.vocab is not table.vocab:
        enc = TopicEncoder(table.vocab)
        try:
            object.__setattr__(table, "_topic_encoder", enc)
        except (AttributeError, TypeError):
            pass  # slotted/frozen table: encoder lives for this call only
    return enc.encode(names, depth if depth is not None
                      else table.depth, batch=batch)
