"""Pallas fast path for SMALL (VMEM-resident) match tables — the
SURVEY.md §7.4 "pallas kernel for the hot op" experiment, with the
honest applicability analysis.

**Where pallas can win here.**  The shipping ``nfa_match`` is
HBM-random-gather bound at scale (BASELINE.md ablation: edge+node
gathers are ~65% of kernel time at 200k filters; the table has ~1.0
literal edges per state, so the 2-choice×4-slot cuckoo probe is already
byte-minimal).  XLA's native gather is the right tool for those
HBM-scale lookups: a pallas kernel would have to issue one DMA per
probed bucket (B·A·2 small DMAs per step — DMA issue overhead alone
exceeds the gather cost), so pallas is NOT attempted for the 1M–10M
filter regime; the measured reasoning lives in BASELINE.md.

For tables that FIT IN VMEM (≲100k edges ≈ 6.4 MB edge table + node
table), the calculus inverts: the whole 8-step walk can run in ONE
kernel with every probe hitting VMEM — no per-step HBM round trips, no
intermediate materialization.  That is this module: a fused
walk-and-match kernel for the small/medium broker (≤~50k wildcard
filters), grid over batch tiles, tables broadcast to every tile.

**Status.**  Parity-tested against ``nfa_match`` in interpret mode (the
CPU-mesh suite).  Mosaic lowering exercised via ``bench_pallas_small``
on real TPU hardware — run it when a chip is attached; if Mosaic
rejects the vectorized VMEM gathers on some TPU generation, the caller
falls back to ``nfa_match`` (both paths share the table layout, so the
fallback is a function swap).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .compiler import BUCKET_SLOTS

__all__ = ["pallas_small_match", "pallas_small_match_flat",
           "pallas_join_match", "pallas_join_match_flat",
           "pallas_join_match_flat_donated", "supports_table",
           "supports_join_table", "bench_pallas_small"]

VMEM_BUDGET_BYTES = 8 << 20   # tables beyond this stay on nfa_match
TILE_B = 256                  # batch rows per grid step


def supports_table(node_tab: np.ndarray, edge_tab: np.ndarray) -> bool:
    return (node_tab.nbytes + edge_tab.nbytes) <= VMEM_BUDGET_BYTES


def supports_join_table(node_tab, state_start, edge_word,
                        edge_next, overlay) -> bool:
    """VMEM fit check for the join-relation walk: node table + CSR
    offsets + both relation columns + the overlay must co-reside."""
    total = sum(int(np.asarray(a).nbytes)
                for a in (node_tab, state_start, edge_word, edge_next,
                          overlay))
    return total <= VMEM_BUDGET_BYTES


def _hash(state, word, seed, mask):
    h = (state.astype(jnp.uint32) * jnp.uint32(2654435761)
         + word.astype(jnp.uint32) * jnp.uint32(2246822519)
         + seed.astype(jnp.uint32))
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(3266489917)
    h = h ^ (h >> jnp.uint32(13))
    return (h & jnp.uint32(mask)).astype(jnp.int32)


def _walk_tile(words, lens, is_sys, node_tab, lit_lookup,
               acc_ref, aover_ref, *, depth: int, active_slots: int):
    """One batch tile: the full D-step walk with VMEM-resident tables,
    the literal-edge lookup pluggable (the ``nfa_walk`` factoring).

    Mirrors ``nfa_match`` exactly (same per-step widths, same accept
    slot layout) so parity is bit-for-bit and callers can decode with
    the same host code."""
    B = words.shape[0]
    A = active_slots

    active = jnp.zeros((B, 1), jnp.int32)
    aover = jnp.zeros((B,), jnp.int32)
    col = 0
    for t in range(depth + 1):
        valid = active >= 0
        sa = jnp.maximum(active, 0)
        node = node_tab[sa]                  # (B, w, 4) VMEM gather
        hacc = jnp.where(valid, node[..., 1], -1)
        if t == 0:
            hacc = jnp.where(is_sys[:, None], -1, hacc)
        eacc = jnp.where(valid & (t == lens)[:, None], node[..., 2], -1)
        w_cols = hacc.shape[1]
        acc_ref[:, col:col + w_cols] = hacc
        acc_ref[:, col + w_cols:col + 2 * w_cols] = eacc
        col += 2 * w_cols
        if t == depth:
            break
        w = jnp.broadcast_to(words[:, t][:, None], active.shape)
        lit = jnp.where(valid, lit_lookup(active, w), -1)
        plus = jnp.where(valid, node[..., 0], -1)
        if t == 0:
            plus = jnp.where(is_sys[:, None], -1, plus)
        cand = jnp.concatenate([lit, plus], axis=1)
        cand = jnp.where((t < lens)[:, None], cand, -1)
        if cand.shape[1] <= A:
            active = cand
        else:
            active, _ = jax.lax.top_k(cand, A)
            n_cand = jnp.sum((cand >= 0).astype(jnp.int32), axis=1)
            n_kept = jnp.sum((active >= 0).astype(jnp.int32), axis=1)
            aover = aover + (n_cand - n_kept)
    aover_ref[...] = aover


def _kernel(words_ref, lens_ref, issys_ref, node_ref, edge_ref, seeds_ref,
            acc_ref, aover_ref, *, depth: int, active_slots: int):
    """Hash-backend tile: the cuckoo 2-choice probe as the literal
    lookup, every probe hitting VMEM."""
    edge_tab = edge_ref[...]
    seeds = seeds_ref[...]
    Hb = edge_tab.shape[0]
    mask = Hb - 1
    B = words_ref.shape[0]

    def lookup(active, w):
        hits = []
        for k in range(2):
            b = _hash(active, w, seeds[k], mask)
            rows = edge_tab[b].reshape(B, active.shape[1],
                                       BUCKET_SLOTS, 4)
            hit = (rows[..., 0] == active[..., None]) & (
                rows[..., 1] == w[..., None])
            hits.append(jnp.max(jnp.where(hit, rows[..., 2], -1),
                                axis=-1))
        return jnp.maximum(hits[0], hits[1])

    _walk_tile(words_ref[...], lens_ref[...], issys_ref[...],
               node_ref[...], lookup, acc_ref, aover_ref,
               depth=depth, active_slots=active_slots)


def _join_kernel(words_ref, lens_ref, issys_ref, node_ref, start_ref,
                 eword_ref, enext_ref, overlay_ref, acc_ref, aover_ref,
                 *, depth: int, active_slots: int):
    """Join-backend tile: the whole sorted-relation lower-bound walk
    (``ops/join_match._join_edge_lookup`` ported verbatim — CSR
    segment bounds + unrolled binary search, then the sorted-overlay
    lower bound) runs on-chip, so the seed-free join backend composes
    with the VMEM walk end-to-end — no per-step HBM round trips, no
    host bounce for the search steps."""
    state_start = start_ref[...]
    edge_word = eword_ref[...]
    edge_next = enext_ref[...]
    overlay = overlay_ref[...]
    E = int(edge_word.shape[0])
    steps = max(1, E.bit_length())          # ceil(log2(E)) + 1 margin
    o_state = overlay[:, 0]
    o_word = overlay[:, 1]
    o_next = overlay[:, 2]
    cap = int(o_state.shape[0])
    osteps = max(1, cap.bit_length())

    def lookup(active, word):
        sa = jnp.maximum(active, 0)          # safe gather index
        lo = state_start[sa]
        hi0 = state_start[sa + 1]
        hi = hi0
        for _ in range(steps):
            act = lo < hi
            mid = (lo + hi) >> 1
            wm = edge_word[jnp.clip(mid, 0, E - 1)]
            right = act & (wm < word)
            lo = jnp.where(right, mid + 1, lo)
            hi = jnp.where(act & ~right, mid, hi)
        pos = jnp.clip(lo, 0, E - 1)
        hit = (lo < hi0) & (edge_word[pos] == word)
        nxt = jnp.where(hit, edge_next[pos], -1)
        # sorted overlay: lexicographic (state, word) lower bound
        olo = jnp.zeros_like(active)
        ohi = jnp.full_like(active, cap)
        for _ in range(osteps):
            act = olo < ohi
            mid = (olo + ohi) >> 1
            midc = jnp.clip(mid, 0, cap - 1)
            ms = o_state[midc]
            mw = o_word[midc]
            right = act & ((ms < active) | ((ms == active) & (mw < word)))
            olo = jnp.where(right, mid + 1, olo)
            ohi = jnp.where(act & ~right, mid, ohi)
        opos = jnp.clip(olo, 0, cap - 1)
        ohit = ((olo < cap) & (o_state[opos] == active)
                & (o_word[opos] == word))
        nxt_o = jnp.where(ohit, o_next[opos], -1)
        return jnp.maximum(nxt, nxt_o)

    _walk_tile(words_ref[...], lens_ref[...], issys_ref[...],
               node_ref[...], lookup, acc_ref, aover_ref,
               depth=depth, active_slots=active_slots)


def _accept_cols(depth: int, active_slots: int) -> int:
    cols = 0
    w = 1
    for t in range(depth + 1):
        cols += 2 * w
        w = min(2 * w, active_slots)
    return cols


@partial(jax.jit, static_argnames=("depth", "active_slots", "interpret"))
def pallas_small_match(words, lens, is_sys, node_tab, edge_tab, seeds,
                       *, depth: int, active_slots: int = 8,
                       interpret: bool = False) -> Tuple[jax.Array,
                                                         jax.Array]:
    """-> (raw accept slots (B, C), active_overflow (B,)) — the same
    raw-mode layout as ``nfa_match(compact_output=False)``; reuse its
    host decode / XLA compaction."""
    from jax.experimental import pallas as pl

    B, D = words.shape
    assert D == depth, (D, depth)
    if B % TILE_B:
        raise ValueError(f"batch {B} must be a multiple of {TILE_B}")
    C = _accept_cols(depth, active_slots)
    kernel = partial(_kernel, depth=depth, active_slots=active_slots)
    grid = (B // TILE_B,)
    acc, aover = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, C), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_B, D), lambda i: (i, 0)),
            pl.BlockSpec((TILE_B,), lambda i: (i,)),
            pl.BlockSpec((TILE_B,), lambda i: (i,)),
            pl.BlockSpec(node_tab.shape, lambda i: (0, 0)),
            pl.BlockSpec(edge_tab.shape, lambda i: (0, 0)),
            pl.BlockSpec(seeds.shape, lambda i: (0,)),
        ],
        out_specs=(
            pl.BlockSpec((TILE_B, C), lambda i: (i, 0)),
            pl.BlockSpec((TILE_B,), lambda i: (i,)),
        ),
        interpret=interpret,
    )(words, lens, is_sys, node_tab, edge_tab, seeds)
    return acc, aover


@partial(jax.jit, static_argnames=("depth", "active_slots",
                                   "max_matches", "flat_cap",
                                   "interpret"))
def pallas_small_match_flat(words, lens, is_sys, node_tab, edge_tab,
                            seeds, *, depth: int, active_slots: int = 8,
                            max_matches: int = 32, flat_cap: int,
                            interpret: bool = False):
    """Pallas walk + the SHARED flat compaction epilogue
    (:func:`~emqx_tpu.ops.match_kernel.flat_epilogue`): the dense
    (row, accept-id) list and the packed ``row_meta`` vector are
    produced on device, so the match-proportional two-phase readback
    contract holds identically for both kernel backends — the VMEM
    walk fuses straight into the cumsum-offset scatter under one jit.
    Returns the same :class:`~emqx_tpu.ops.match_kernel.MatchResult`
    layout as ``nfa_match(flat_cap=...)``."""
    from .match_kernel import MatchResult, flat_epilogue

    acc, aover = pallas_small_match(
        words, lens, is_sys, node_tab, edge_tab, seeds, depth=depth,
        active_slots=active_slots, interpret=interpret)
    n = jnp.sum((acc >= 0).astype(jnp.int32), axis=1)
    matches, mover, row_meta = flat_epilogue(
        acc, n, aover, max_matches, flat_cap)
    return MatchResult(matches=matches, n_matches=n,
                       active_overflow=aover, match_overflow=mover,
                       row_meta=row_meta)


@partial(jax.jit, static_argnames=("depth", "active_slots", "interpret"))
def pallas_join_match(words, lens, is_sys, node_tab, state_start,
                      edge_word, edge_next, overlay, *, depth: int,
                      active_slots: int = 8,
                      interpret: bool = False) -> Tuple[jax.Array,
                                                        jax.Array]:
    """Join-relation twin of :func:`pallas_small_match`: the unrolled
    lower-bound walk (``join-pallas`` backend) over VMEM-resident CSR
    relation arrays.  -> (raw accept slots (B, C), active_overflow
    (B,)) — the same raw-mode layout as ``nfa_match
    (compact_output=False)``.  Tiles adapt down to the batch (pow2
    serve buckets below ``TILE_B`` run as one tile), so the warm
    shapes (B=64) compile without padding."""
    from jax.experimental import pallas as pl

    B, D = words.shape
    assert D == depth, (D, depth)
    tile = min(TILE_B, B)
    if B % tile:
        raise ValueError(f"batch {B} must be a multiple of {tile}")
    C = _accept_cols(depth, active_slots)
    kernel = partial(_join_kernel, depth=depth,
                     active_slots=active_slots)
    grid = (B // tile,)
    acc, aover = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, C), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, D), lambda i: (i, 0)),
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec((tile,), lambda i: (i,)),
            pl.BlockSpec(node_tab.shape, lambda i: (0, 0)),
            pl.BlockSpec(state_start.shape, lambda i: (0,)),
            pl.BlockSpec(edge_word.shape, lambda i: (0,)),
            pl.BlockSpec(edge_next.shape, lambda i: (0,)),
            pl.BlockSpec(overlay.shape, lambda i: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((tile, C), lambda i: (i, 0)),
            pl.BlockSpec((tile,), lambda i: (i,)),
        ),
        interpret=interpret,
    )(words, lens, is_sys, node_tab, state_start, edge_word,
      edge_next, overlay)
    return acc, aover


_JOIN_FLAT_STATIC = ("depth", "active_slots", "max_matches", "flat_cap",
                     "interpret")


def _pallas_join_match_flat(words, lens, is_sys, node_tab, state_start,
                            edge_word, edge_next, overlay, *,
                            depth: int, active_slots: int = 8,
                            max_matches: int = 32, flat_cap: int,
                            interpret: bool = False):
    from .match_kernel import MatchResult, flat_epilogue

    acc, aover = pallas_join_match(
        words, lens, is_sys, node_tab, state_start, edge_word,
        edge_next, overlay, depth=depth, active_slots=active_slots,
        interpret=interpret)
    n = jnp.sum((acc >= 0).astype(jnp.int32), axis=1)
    matches, mover, row_meta = flat_epilogue(
        acc, n, aover, max_matches, flat_cap)
    return MatchResult(matches=matches, n_matches=n,
                       active_overflow=aover, match_overflow=mover,
                       row_meta=row_meta)


#: Pallas join walk + the SHARED flat compaction epilogue — the same
#: readback contract as ``nfa_match(flat_cap=...)`` / ``join_match``,
#: so the two-phase (and ragged) d2h decode is backend-agnostic.
pallas_join_match_flat = jax.jit(
    _pallas_join_match_flat, static_argnames=_JOIN_FLAT_STATIC)

#: pipelined twin: batch operands donated, table/relation arrays NOT
#: (they serve every in-flight batch) — the nfa_match_donated contract
pallas_join_match_flat_donated = jax.jit(
    _pallas_join_match_flat, static_argnames=_JOIN_FLAT_STATIC,
    donate_argnums=(0, 1, 2))


def bench_pallas_small(n_filters: int = 50_000, batch: int = 8192,
                       iters: int = 20, depth: int = 8) -> dict:
    """Real-chip A/B: fused pallas walk vs nfa_match on a VMEM-sized
    table.  Run manually when a TPU is attached (the tunnel was down
    when this landed); falls back with the Mosaic error recorded if
    lowering is rejected."""
    import time

    from .compiler import compile_filters, encode_topics
    from .match_kernel import nfa_match

    rng = np.random.default_rng(3)
    filters = [f"s/{rng.integers(1000)}/+/d{i % 97}/#"[: 64]
               for i in range(n_filters)]
    table = compile_filters(sorted(set(filters)), depth=depth)
    topics = [f"s/{rng.integers(1000)}/x/d{i % 97}/leaf"
              for i in range(batch)]
    words, lens, is_sys = encode_topics(table, topics, batch=batch)
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in table.device_arrays()])
    out = {"n_states": table.n_states,
           "table_bytes": int(sum(a.nbytes for a in
                                  table.device_arrays()[:2]))}
    r = nfa_match(*args, active_slots=8, compact_output=False)
    np.asarray(r.matches)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = nfa_match(*args, active_slots=8, compact_output=False)
    np.asarray(r.matches)
    out["xla_ms_per_batch"] = round(
        (time.perf_counter() - t0) / iters * 1e3, 2)
    try:
        acc, aover = pallas_small_match(
            *args, depth=depth, active_slots=8)
        np.asarray(acc)
        t0 = time.perf_counter()
        for _ in range(iters):
            acc, aover = pallas_small_match(
                *args, depth=depth, active_slots=8)
        np.asarray(acc)
        out["pallas_ms_per_batch"] = round(
            (time.perf_counter() - t0) / iters * 1e3, 2)
    except Exception as e:  # noqa: BLE001 — record the lowering verdict
        out["pallas_error"] = f"{type(e).__name__}: {e}"[:500]
    return out


if __name__ == "__main__":
    print(bench_pallas_small())
