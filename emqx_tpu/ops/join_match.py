"""Relational-join match backend — the TrieJax recast of the NFA walk.

The hash backend (:mod:`~emqx_tpu.ops.match_kernel`) resolves each
literal transition with two wide cuckoo-bucket gathers: 2 × 16 int32
per (row, active-slot) regardless of how many land, and the bucket
table itself carries ≥25% padding by the growth rule.  TrieJax
(PAPERS.md, arxiv 1905.08021) shows the same trie-walk workload recast
as a worst-case-optimal relational join vectorizes without either
cost: wildcard match IS a level-by-level join of the (level, token)
topic relation against the (state, token, next) edge relation.

This module stores the edge relation **sorted** and answers each level
step with a vectorized ``searchsorted`` intersection instead of hash
probes:

* ``state_start (S+1,) int32`` — CSR offsets: state ``s``'s edges live
  at rows ``[state_start[s], state_start[s+1])`` of the relation;
* ``edge_word (E,) int32`` — the edge tokens, sorted within each state
  segment (the relation is lexicographically sorted by (state, word));
* ``edge_next (E,) int32`` — the target state per row, ``-1`` for a
  TOMBSTONE (a deleted edge whose row is kept so sortedness — and the
  device copy — survive without a rebuild);
* ``overlay (OVERLAY_CAP, 3) int32`` — rows ``[state, word, next]`` of
  edges added since the last rebuild: insertions cannot keep a packed
  CSR sorted in place, so they land here until the next compaction
  folds them in.  The overlay itself is SORTED by (state, word) with
  ``INT32_MAX`` sentinel rows packed at the end, so the kernel
  resolves it with a second unrolled lower-bound search —
  ``log2(OVERLAY_CAP)`` two-int32 gathers per (row, slot) instead of
  the former dense 256-wide compare (ROADMAP maintenance (c): that
  compare was O(B·A·256) on EVERY dispatch, paid even with an empty
  overlay).  The host re-sorts on mutation and ships the overlay
  whole (3 KB) — mutations are rare, dispatches are not.

The lookup per (row, slot) is one CSR-offset gather plus an unrolled
lower-bound binary search over the state's own segment — ``log2(E)``
single-int32 gathers worst case, and the relation rows are exactly the
live edges (no bucket padding, no probe loops, no seeds).  The walk,
accepts, ``$``-topic masking and the flat/`row_meta`` epilogue are the
SHARED :func:`~emqx_tpu.ops.match_kernel.nfa_walk`, so hint/match
parity with the hash backend is structural.

**Maintenance** (:class:`JoinRelation`): the host keeps a shadow copy
of the cuckoo table and diffs each drained delta's dirty buckets
against it — deletions tombstone in place (one scatter), re-additions
revive their tombstone, fresh edges append to the overlay; a cuckoo
kick chain (the same edge relocating between buckets) cancels out of
the diff entirely, and a cuckoo RESEED doesn't touch the relation at
all (it is keyed by (state, word), not by bucket).  When the overlay
fills, the relation rebuilds from the shadow (one ``lexsort``, the
same cost class as the edge-table growth that usually triggered it).
Table compaction always rebuilds clean (overlay empty), which is when
segments persist the arrays (storage/segments.py format v2).

**Routing** (:class:`BackendAutotuner`): neither backend wins every
shape — the hash probe is two bulk gathers (good when the frontier is
wide and the table small), the join search is ``log2(segment)`` steps
(good when buckets are padded and fanout is skewed).  The autotuner
times both per (B, D, S, Hb) shape on representative topics, persists
its pick table as checksummed JSON next to the XLA disk cache, and
:class:`~emqx_tpu.ops.kernel_cache.MatchKernelCache` serves whichever
kernel won that shape.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .compiler import BUCKET_SLOTS

log = logging.getLogger(__name__)

__all__ = ["OVERLAY_CAP", "OVERLAY_EMPTY", "JoinRelation", "OverlayFull",
           "join_match", "join_match_donated", "relation_capacity",
           "BackendAutotuner"]

#: overlay rows available between rebuilds.  Small on purpose: the
#: kernel binary-searches the overlay per (row, slot), so its size
#: rides every dispatch (log2(CAP) steps); a full overlay just means
#: one rebuild (a lexsort over live edges — cheaper than the cuckoo
#: growth path that lands in the same sync).
OVERLAY_CAP = 256

#: sentinel state/word for unused overlay rows: sorts AFTER every live
#: (state, word) pair, so the lower-bound search never lands on one
#: (and no live state or word id can ever equal it)
OVERLAY_EMPTY = np.int32(2**31 - 1)


def relation_capacity(hb: int) -> int:
    """Relation row capacity for a cuckoo table of ``hb`` buckets.

    Slaved to the hash table's slot capacity so the two backends'
    shape keys stay one (S, Hb) pair: the cuckoo holds at most
    ``hb * BUCKET_SLOTS`` edges, so a relation this size can always
    absorb a rebuild, and it doubles exactly when Hb doubles."""
    return hb * BUCKET_SLOTS


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _join_edge_lookup(state, word, state_start, edge_word, edge_next,
                      overlay, linear_overlay: bool = False):
    """Literal-edge lookup for (B, w) (state, word) pairs against the
    sorted relation: CSR segment bounds (2 gathers) + an unrolled
    lower-bound binary search (1 int32 gather/step), then the overlay
    intersection — a second unrolled lower bound over the sorted
    (state, word) overlay rows (2 int32 gathers/step, log2(CAP)
    steps).  Misses and tombstones both resolve to -1.

    ``linear_overlay`` keeps the pre-ISSUE-16 dense O(CAP) overlay
    compare compilable as the parity oracle for the sorted search."""
    import jax.numpy as jnp

    E = int(edge_word.shape[0])
    steps = max(1, E.bit_length())          # ceil(log2(E)) + 1 margin
    sa = jnp.maximum(state, 0)              # safe gather index
    lo = state_start[sa]
    hi0 = state_start[sa + 1]
    hi = hi0
    for _ in range(steps):
        act = lo < hi
        mid = (lo + hi) >> 1
        wm = edge_word[jnp.clip(mid, 0, E - 1)]
        right = act & (wm < word)
        lo = jnp.where(right, mid + 1, lo)
        hi = jnp.where(act & ~right, mid, hi)
    pos = jnp.clip(lo, 0, E - 1)
    hit = (lo < hi0) & (edge_word[pos] == word)
    nxt = jnp.where(hit, edge_next[pos], -1)
    # overlay intersection: edges added since the last rebuild
    o_state = overlay[:, 0]
    o_word = overlay[:, 1]
    o_next = overlay[:, 2]
    if linear_overlay:
        # dense compare, (B, w, OVERLAY_CAP) int32: the historical
        # path, kept as the bit-parity oracle (sentinel rows never
        # equal a live query, and their next = -1 never wins the max)
        eq = (state[..., None] == o_state[None, None, :]) & (
            word[..., None] == o_word[None, None, :])
        nxt_o = jnp.max(
            jnp.where(eq, o_next[None, None, :], -1), axis=-1)
        return jnp.maximum(nxt, nxt_o)
    # sorted overlay: lower-bound search on the lexicographic
    # (state, word) order; OVERLAY_EMPTY sentinel rows pack at the
    # end and compare greater than every live pair, so the search
    # never resolves to one.  Inactive slots query state = -1, which
    # compares less than every live row — lo lands at 0 and the
    # equality check misses.
    cap = int(o_state.shape[0])
    osteps = max(1, cap.bit_length())
    olo = jnp.zeros_like(state)
    ohi = jnp.full_like(state, cap)
    for _ in range(osteps):
        act = olo < ohi
        mid = (olo + ohi) >> 1
        midc = jnp.clip(mid, 0, cap - 1)
        ms = o_state[midc]
        mw = o_word[midc]
        right = act & ((ms < state) | ((ms == state) & (mw < word)))
        olo = jnp.where(right, mid + 1, olo)
        ohi = jnp.where(act & ~right, mid, ohi)
    opos = jnp.clip(olo, 0, cap - 1)
    ohit = ((olo < cap) & (o_state[opos] == state)
            & (o_word[opos] == word))
    nxt_o = jnp.where(ohit, o_next[opos], -1)
    return jnp.maximum(nxt, nxt_o)


def _join_match(
    words,        # (B, D) int32
    lens,         # (B,) int32
    is_sys,       # (B,) bool
    node_tab,     # (S, 4) int32 — same node table as the hash backend
    state_start,  # (S+1,) int32 CSR offsets
    edge_word,    # (E,) int32 sorted within each state segment
    edge_next,    # (E,) int32, -1 = tombstone
    overlay,      # (OVERLAY_CAP, 3) int32 [state, word, next]
    *,
    active_slots: int = 16,
    max_matches: int = 32,
    compact_output: bool = True,
    flat_cap: int = 0,
    linear_overlay: bool = False,
):
    from .match_kernel import nfa_walk

    return nfa_walk(
        words, lens, is_sys, node_tab,
        lambda st, w: _join_edge_lookup(
            st, w, state_start, edge_word, edge_next, overlay,
            linear_overlay=linear_overlay),
        active_slots=active_slots, max_matches=max_matches,
        compact_output=compact_output, flat_cap=flat_cap,
    )


def _jit_pair():
    import jax

    from .match_kernel import _MATCH_STATIC

    statics = tuple(_MATCH_STATIC) + ("linear_overlay",)
    fn = jax.jit(_join_match, static_argnames=statics)
    # pipelined twin: batch operands donated, table/relation arrays NOT
    # (they serve every in-flight batch) — same contract as nfa_match
    fn_d = jax.jit(_join_match, static_argnames=statics,
                   donate_argnums=(0, 1, 2))
    return fn, fn_d


join_match, join_match_donated = _jit_pair()


# ---------------------------------------------------------------------------
# host-side relation maintenance
# ---------------------------------------------------------------------------


class OverlayFull(RuntimeError):
    """The overlay ran out of rows: the caller rebuilds the relation
    from the shadow table (one lexsort) and re-uploads it whole."""


class JoinRelation:
    """Host twin of the device relation arrays.

    Owns the numpy state plus a SHADOW copy of the cuckoo edge table;
    :meth:`apply_bucket_delta` diffs drained dirty buckets against the
    shadow and returns exactly the scatter updates the device copy
    needs (tombstones/revivals on ``edge_next``, overlay row writes) —
    O(dirty buckets), never a rebuild, until the overlay fills."""

    def __init__(self, s: int, edge_tab: np.ndarray,
                 arrays: Optional[Tuple[np.ndarray, np.ndarray,
                                        np.ndarray]] = None) -> None:
        self.shadow = np.array(edge_tab, np.int32, copy=True)
        hb = int(edge_tab.shape[0])
        self.cap = relation_capacity(hb)
        # overlay edges keyed (state, word); the materialized array is
        # kept SORTED (sentinel rows at the end) so the kernel's
        # lower-bound search stays valid — any mutation re-sorts and
        # ships the whole 3 KB array
        self.overlay = np.empty((OVERLAY_CAP, 3), np.int32)
        self._o_map: Dict[Tuple[int, int], int] = {}
        self._materialize_overlay()
        if arrays is not None:
            start, word, nxt = arrays
            self.state_start = np.array(start, np.int32, copy=True)
            self.edge_word = np.array(word, np.int32, copy=True)
            self.edge_next = np.array(nxt, np.int32, copy=True)
            if (len(self.state_start) != s + 1
                    or len(self.edge_word) != self.cap
                    or len(self.edge_next) != self.cap):
                raise ValueError("seed relation shape mismatch")
        else:
            self._build(s)

    def _build(self, s: int) -> None:
        flat = self.shadow.reshape(-1, 4)
        live = flat[flat[:, 0] >= 0]
        order = np.lexsort((live[:, 1], live[:, 0]))
        sw = live[order]
        n = len(sw)
        if n > self.cap:  # structurally impossible (cap = slot count)
            raise ValueError(f"{n} edges > relation capacity {self.cap}")
        word = np.zeros(self.cap, np.int32)
        nxt = np.full(self.cap, -1, np.int32)
        word[:n] = sw[:, 1]
        nxt[:n] = sw[:, 2]
        counts = np.bincount(sw[:, 0], minlength=s) if n else \
            np.zeros(s, np.int64)
        start = np.zeros(s + 1, np.int32)
        start[1:] = np.cumsum(counts[:s])
        self.state_start = start
        self.edge_word = word
        self.edge_next = nxt
        self._o_map = {}
        self._materialize_overlay()

    def _materialize_overlay(self) -> None:
        """Re-sort the overlay rows by (state, word); unused rows pack
        at the end as OVERLAY_EMPTY sentinels (they must compare
        GREATER than every live pair for the device lower bound)."""
        self.overlay[:, 0] = OVERLAY_EMPTY
        self.overlay[:, 1] = OVERLAY_EMPTY
        self.overlay[:, 2] = -1
        if self._o_map:
            rows = [(s, w, n) for (s, w), n in sorted(self._o_map.items())]
            self.overlay[:len(rows)] = np.asarray(rows, np.int32)

    # -- queries -----------------------------------------------------------

    def arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                              np.ndarray]:
        return (self.state_start, self.edge_word, self.edge_next,
                self.overlay)

    def lookup(self, s: int, w: int) -> int:
        """Host-side oracle of the kernel lookup (tests)."""
        pos = self._csr_find(s, w)
        if pos is not None and self.edge_next[pos] >= 0:
            return int(self.edge_next[pos])
        return self._o_map.get((s, w), -1)

    def _csr_find(self, s: int, w: int) -> Optional[int]:
        start = self.state_start
        if s + 1 >= len(start):
            return None
        lo, hi = int(start[s]), int(start[s + 1])
        i = lo + int(np.searchsorted(self.edge_word[lo:hi], w))
        if i < hi and self.edge_word[i] == w:
            return i
        return None

    # -- maintenance -------------------------------------------------------

    @staticmethod
    def _bucket_edges(row: np.ndarray) -> Dict[Tuple[int, int], int]:
        out: Dict[Tuple[int, int], int] = {}
        r = row.tolist()
        for i in range(0, len(r), 4):
            if r[i] >= 0:
                out[(r[i], r[i + 1])] = r[i + 2]
        return out

    def apply_bucket_delta(self, bucket_idx: np.ndarray,
                           bucket_rows: np.ndarray):
        """Diff dirty buckets against the shadow → device scatter ops.

        Returns ``(main_pos, main_val, olay_pos, olay_rows)`` numpy
        arrays (possibly empty): ``edge_next[main_pos] = main_val`` and
        ``overlay[olay_pos] = olay_rows``.  Any overlay mutation
        re-sorts and returns the WHOLE overlay (sortedness is the
        device search's invariant; 3 KB per rare mutation beats 256
        compares per dispatch).  Raises :class:`OverlayFull` when an
        insertion finds no overlay slot — the caller rebuilds (the
        shadow is ALREADY updated, so ``rebuild()`` is enough)."""
        if len(bucket_idx) and int(bucket_idx.max()) >= len(self.shadow):
            # shadow shape drift (a resize the caller didn't route
            # through rebuild()): force the rebuild path rather than
            # corrupting the relation
            raise OverlayFull("dirty bucket beyond shadow shape")
        removed: Dict[Tuple[int, int], int] = {}
        added: Dict[Tuple[int, int], int] = {}
        for b, new in zip(bucket_idx.tolist(), bucket_rows):
            old_e = self._bucket_edges(self.shadow[b])
            new_e = self._bucket_edges(new)
            for k, v in old_e.items():
                if k not in new_e:
                    removed[k] = v
            for k, v in new_e.items():
                if k not in old_e or old_e[k] != v:
                    added[k] = v
            self.shadow[b] = new
        # a cuckoo kick relocates an edge between buckets: it shows as
        # removed in one bucket and added in another — net no-op (same
        # next), or an in-place next update (child re-created)
        for k in [k for k in removed if k in added]:
            if removed[k] == added[k]:
                del added[k]
            del removed[k]
        main_pos: List[int] = []
        main_val: List[int] = []
        o_dirty = False
        for (s, w) in removed:
            if self._o_map.pop((s, w), None) is not None:
                o_dirty = True
                continue
            pos = self._csr_find(s, w)
            if pos is None:  # shadow/relation drift: force a rebuild
                raise OverlayFull(f"edge ({s},{w}) missing from relation")
            self.edge_next[pos] = -1
            main_pos.append(pos)
            main_val.append(-1)
        for (s, w), nv in added.items():
            pos = self._csr_find(s, w)
            if pos is not None:   # revive the tombstone in place
                self.edge_next[pos] = nv
                main_pos.append(pos)
                main_val.append(nv)
                continue
            if (s, w) not in self._o_map and \
                    len(self._o_map) >= OVERLAY_CAP:
                raise OverlayFull(f"overlay full ({OVERLAY_CAP} rows)")
            if self._o_map.get((s, w)) != nv:
                self._o_map[(s, w)] = nv
                o_dirty = True
        if o_dirty:
            self._materialize_overlay()
            olay_pos = np.arange(OVERLAY_CAP, dtype=np.int32)
            olay_rows = self.overlay.copy()
        else:
            olay_pos = np.empty(0, np.int32)
            olay_rows = np.empty((0, 3), np.int32)
        return (
            np.asarray(main_pos, np.int32),
            np.asarray(main_val, np.int32),
            olay_pos,
            olay_rows,
        )

    def grow_states(self, new_s: int) -> None:
        """Node-table growth: new states have no CSR segment (their
        edges arrive through the overlay), so the offsets just extend
        with the terminal value."""
        cur = len(self.state_start) - 1
        if new_s <= cur:
            return
        self.state_start = np.concatenate([
            self.state_start,
            np.full(new_s - cur, self.state_start[-1], np.int32),
        ])

    def rebuild(self, s: int,
                edge_tab: Optional[np.ndarray] = None) -> None:
        """Re-sort from ``edge_tab`` (or the current shadow): the
        overlay-full / rehash / compaction path.  O(E log E)."""
        if edge_tab is not None:
            self.shadow = np.array(edge_tab, np.int32, copy=True)
            self.cap = relation_capacity(int(edge_tab.shape[0]))
        self._build(s)


# ---------------------------------------------------------------------------
# per-shape backend autotuner
# ---------------------------------------------------------------------------


class BackendAutotuner:
    """Measured hash-vs-join pick per kernel shape, persisted as
    checksummed JSON (the segment-checksum idiom: a corrupt or
    tampered pick table is REJECTED and the default serves — a wrong
    pick is only slow, but a torn file must never poison routing).

    Thread model: ``pick()`` is a dict read (serve path, GIL-atomic);
    ``record()``/``save()`` run from measurement threads under one
    lock."""

    VERSION = 1

    def __init__(self, path: Optional[str] = None, reps: int = 3) -> None:
        self.path = path
        self.reps = max(1, int(reps))
        self.picks: Dict[str, str] = {}
        self.measured: Dict[str, Dict[str, float]] = {}
        self.rejected = False
        self.family_hits = 0
        self._lock = threading.Lock()
        if path:
            self._load()

    @staticmethod
    def sig(b: int, d: int, s: int, hb: int) -> str:
        return f"b{b}:d{d}:s{s}:h{hb}"

    @staticmethod
    def family(sig: str) -> str:
        """The pow2 (S, Hb) family a sig belongs to: the (batch,
        depth) prefix — table shapes are padded pow2s, so every
        growth step lands in the same family."""
        return sig.split(":s", 1)[0]

    def pick(self, sig: str) -> Optional[str]:
        return self.picks.get(sig)

    def pick_for(self, b: int, d: int, s: int, hb: int) -> Optional[str]:
        """The serving pick for a shape: the exact measured sig, else
        the (B, D)-family CONSENSUS across pow2 (S, Hb) shapes — the
        pick rarely flips within a family (ROADMAP join residual (d)),
        so a growth step inherits the family's answer instead of
        re-measuring cold.  A split family (measured shapes disagree)
        returns None and the exact shape measures as before."""
        sig = self.sig(b, d, s, hb)
        p = self.picks.get(sig)
        if p is not None:
            return p
        fam = self.family(sig)
        seen = {v for k, v in self.picks.items()
                if self.family(k) == fam}
        if len(seen) == 1:
            self.family_hits += 1
            return next(iter(seen))
        return None

    # -- measurement -------------------------------------------------------

    def measure(self, sig: str,
                runners: Dict[str, Callable[[], None]]) -> str:
        """Time each runner (one warmup call outside the clock — the
        first call may compile), record the per-rep minimum, pick the
        fastest, persist.  Returns the winning backend name."""
        import time

        times: Dict[str, float] = {}
        for name, run in runners.items():
            run()                       # warmup / compile, untimed
            best = float("inf")
            for _ in range(self.reps):
                t0 = time.perf_counter()
                run()
                best = min(best, time.perf_counter() - t0)
            times[name] = best
        winner = min(times, key=lambda n: times[n])
        self.record(sig, winner, times)
        return winner

    def record(self, sig: str, backend: str,
               times: Optional[Dict[str, float]] = None) -> None:
        with self._lock:
            self.picks[sig] = backend
            if times:
                self.measured[sig] = {
                    k: round(v * 1e6, 2) for k, v in times.items()}
            self._save_locked()

    # -- persistence -------------------------------------------------------

    @staticmethod
    def _checksum(picks: Dict[str, str]) -> str:
        return hashlib.sha1(
            json.dumps(picks, sort_keys=True).encode()).hexdigest()

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                doc = json.load(f)
            if doc.get("version") != self.VERSION:
                raise ValueError(f"version {doc.get('version')!r}")
            picks = doc.get("picks")
            if not isinstance(picks, dict) or any(
                    v not in ("hash", "join", "join-pallas")
                    for v in picks.values()):
                raise ValueError("malformed picks")
            if doc.get("checksum") != self._checksum(picks):
                raise ValueError("checksum mismatch")
            self.picks = dict(picks)
            self.measured = dict(doc.get("measured") or {})
        except FileNotFoundError:
            pass
        except Exception as e:  # corrupt table: defaults serve
            self.rejected = True
            log.warning("autotune pick table %s rejected (%s); "
                        "measuring fresh", self.path, e)

    def _save_locked(self) -> None:
        if not self.path:
            return
        doc = {
            "version": self.VERSION,
            "checksum": self._checksum(self.picks),
            "picks": self.picks,
            "measured": self.measured,
        }
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            log.warning("autotune pick table %s not persisted",
                        self.path, exc_info=True)

    def info(self) -> dict:
        return {
            "picks": dict(self.picks),
            "measured_shapes": len(self.measured),
            "family_hits": self.family_hits,
            "rejected_file": self.rejected,
            "path": self.path,
        }
