"""Trie → flattened-NFA compiler: the device mirror of the route table.

Behavioral reference: the subscription index semantics of
``apps/emqx/src/emqx_trie.erl`` / ``emqx_topic.erl`` [U] (SURVEY.md §2.1);
the mirror/refresh pattern follows mria's bootstrap-then-replay design
(SURVEY.md §2.2, §5.4).

The wildcard filter set is compiled to static int32 arrays that a
``lax.scan`` NFA walk consumes (``emqx_tpu.ops.match_kernel``):

* **states** — trie nodes of the wildcard filter trie, BFS-numbered with
  root = 0.  ``#``-children are *not* states (``#`` is always terminal):
  they collapse into a per-state ``hash_accept`` id.
* ``plus_child[s]`` — state id of the ``+`` edge from ``s``, or -1.
* ``accept[s]``    — accept id if ≥1 filter terminates at ``s``, else -1.
* ``hash_accept[s]`` — accept id of the ``#``-child of ``s``, else -1.
* literal edges — open-addressing hash table keyed by (state, word_id)
  with linear probing; build guarantees probe chains ≤ ``MAX_PROBES`` by
  growing the table, so the device probe loop is statically bounded.
* **vocab** — host dict interning literal edge words to int32 ids.
  Id 0 is reserved UNKNOWN: publish-topic words never seen in any filter
  map to 0, which has no literal edges by construction (they can still
  match ``+``/``#``).

Shapes are padded to buckets (powers of two) so that table growth rarely
changes compiled shapes (XLA recompiles are the p99 killer — SURVEY.md §7
hard parts).

Accept ids are dense in ``[0, n_accepts)``; ``accept_filters[aid]`` maps
back to the filter string, and the broker layer maps filters to subscriber
sets / bitmap rows.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .. import topic as T

__all__ = ["NfaTable", "compile_filters", "encode_topics", "MAX_PROBES"]

MAX_PROBES = 8  # static device-side probe bound; build grows H to enforce

# multiplicative hash constants (Knuth / murmur-style odd constants)
_HC1 = np.uint32(2654435761)
_HC2 = np.uint32(2246822519)


def _bucket(n: int, minimum: int = 8) -> int:
    """Next power of two ≥ max(n, minimum)."""
    b = minimum
    while b < n:
        b <<= 1
    return b


def _slot(state: np.ndarray, word: np.ndarray, mask: int):
    """Initial probe slot for (state, word) — uint32 math, identical on
    host (numpy) and device (jnp).  uint32 wraparound is the point."""
    with np.errstate(over="ignore"):
        h = state.astype(np.uint32) * _HC1 + word.astype(np.uint32) * _HC2
        h ^= h >> np.uint32(15)
        h *= np.uint32(2246822519)
        h ^= h >> np.uint32(13)
        return (h & np.uint32(mask)).astype(np.int32)


@dataclass
class NfaTable:
    """Flattened NFA snapshot (host numpy; ship with ``.device_arrays()``)."""

    plus_child: np.ndarray   # (S,) int32
    hash_accept: np.ndarray  # (S,) int32
    accept: np.ndarray       # (S,) int32
    tab_state: np.ndarray    # (H,) int32, -1 = empty slot
    tab_word: np.ndarray     # (H,) int32
    tab_next: np.ndarray     # (H,) int32
    n_states: int            # live states (≤ S)
    depth: int               # max filter levels the table supports (D)
    vocab: Dict[str, int]
    accept_filters: List[str]
    epoch: int = 0

    @property
    def S(self) -> int:
        return int(self.plus_child.shape[0])

    @property
    def H(self) -> int:
        return int(self.tab_state.shape[0])

    @property
    def n_accepts(self) -> int:
        return len(self.accept_filters)

    def device_arrays(self):
        """The arrays the kernel consumes, in kernel argument order."""
        return (
            self.plus_child,
            self.hash_accept,
            self.accept,
            self.tab_state,
            self.tab_word,
            self.tab_next,
        )

    def shape_key(self) -> Tuple[int, int, int]:
        """Compile-relevant shape signature; same key ⇒ no XLA recompile."""
        return (self.S, self.H, self.depth)

    # -- host-side reference probe (used by tests / debugging) -----------
    def lookup_literal(self, state: int, word_id: int) -> int:
        mask = self.H - 1
        s = _slot(np.int32(state), np.int32(word_id), mask)
        for i in range(MAX_PROBES):
            j = (int(s) + i) & mask
            if self.tab_state[j] == -1:
                return -1
            if self.tab_state[j] == state and self.tab_word[j] == word_id:
                return int(self.tab_next[j])
        return -1


class _Node:
    __slots__ = ("sid", "lit", "plus", "hash_aid", "aid")

    def __init__(self) -> None:
        self.sid = -1
        self.lit: Dict[str, "_Node"] = {}
        self.plus: Optional["_Node"] = None
        self.hash_aid = -1
        self.aid = -1


def compile_filters(
    filters: Iterable[str],
    depth: int = 16,
    state_bucket: int = 1024,
    epoch: int = 0,
) -> NfaTable:
    """Compile a wildcard filter set into an :class:`NfaTable`.

    ``filters`` are real filters (``$share`` already stripped), deduplicated
    here.  Filters deeper than ``depth`` levels are rejected — the broker
    keeps them on the host slow path (see config ``tpu.max_levels``).
    """
    uniq = sorted(set(filters))
    root = _Node()
    accept_filters: List[str] = []

    # -- build the trie with '#' collapsed into hash_accept ---------------
    for flt in uniq:
        ws = T.words(flt)
        if len(ws) > depth:
            raise ValueError(
                f"filter {flt!r} has {len(ws)} levels > table depth {depth}"
            )
        node = root
        for i, w in enumerate(ws):
            if w == "#":
                assert i == len(ws) - 1, "validated upstream"
                if node.hash_aid < 0:
                    node.hash_aid = len(accept_filters)
                    accept_filters.append(flt)
                break
            if w == "+":
                if node.plus is None:
                    node.plus = _Node()
                node = node.plus
            else:
                nxt = node.lit.get(w)
                if nxt is None:
                    nxt = node.lit[w] = _Node()
                node = nxt
        else:
            if node.aid < 0:
                node.aid = len(accept_filters)
                accept_filters.append(flt)

    # -- BFS state numbering ----------------------------------------------
    order: List[_Node] = []
    root.sid = 0
    order.append(root)
    q = deque([root])
    while q:
        node = q.popleft()
        for child in list(node.lit.values()) + ([node.plus] if node.plus else []):
            child.sid = len(order)
            order.append(child)
            q.append(child)

    n_states = len(order)
    S = _bucket(n_states, state_bucket)

    plus_child = np.full(S, -1, np.int32)
    hash_accept = np.full(S, -1, np.int32)
    accept = np.full(S, -1, np.int32)

    # -- vocab over literal edge words (0 = UNKNOWN) -----------------------
    vocab: Dict[str, int] = {}
    edges: List[Tuple[int, int, int]] = []  # (state, word_id, next_state)
    for node in order:
        plus_child[node.sid] = node.plus.sid if node.plus is not None else -1
        hash_accept[node.sid] = node.hash_aid
        accept[node.sid] = node.aid
        for w, child in node.lit.items():
            wid = vocab.get(w)
            if wid is None:
                wid = vocab[w] = len(vocab) + 1  # 0 reserved
            edges.append((node.sid, wid, child.sid))

    # -- open-addressing literal table; grow until probe bound holds -------
    H = _bucket(max(2 * len(edges), 16))
    while True:
        tab_state = np.full(H, -1, np.int32)
        tab_word = np.full(H, -1, np.int32)
        tab_next = np.full(H, -1, np.int32)
        ok = True
        mask = H - 1
        for s, w, nxt in edges:
            j = int(_slot(np.int32(s), np.int32(w), mask))
            for i in range(MAX_PROBES):
                k = (j + i) & mask
                if tab_state[k] == -1:
                    tab_state[k] = s
                    tab_word[k] = w
                    tab_next[k] = nxt
                    break
            else:
                ok = False
                break
        if ok:
            break
        H <<= 1  # chain too long: double and rebuild

    return NfaTable(
        plus_child=plus_child,
        hash_accept=hash_accept,
        accept=accept,
        tab_state=tab_state,
        tab_word=tab_word,
        tab_next=tab_next,
        n_states=n_states,
        depth=depth,
        vocab=vocab,
        accept_filters=accept_filters,
        epoch=epoch,
    )


def encode_topics(
    table: NfaTable, names: Sequence[str], batch: Optional[int] = None
):
    """Tokenize concrete topics for the kernel.

    Returns ``(words (B, D) int32, lens (B,) int32, is_sys (B,) bool)``
    padded to ``batch`` rows (default: len(names)).  Words beyond depth D
    are irrelevant to matching (only ``#`` accepts can fire past trie
    depth, and those depend on the first D words only); lengths are capped
    at D+1 so "deeper than D" uniformly means "no end-accept fires".
    Padding rows are inert: len sentinel D+2 (no end-accept can fire),
    ``is_sys`` True (suppresses root ``+``/``#`` at step 0) and all-UNKNOWN
    words (no literal edge exists for word id 0), so they match nothing.
    """
    D = table.depth
    B = batch if batch is not None else len(names)
    if len(names) > B:
        raise ValueError(f"{len(names)} topics > batch {B}")
    words = np.zeros((B, D), np.int32)
    lens = np.full(B, D + 2, np.int32)
    is_sys = np.ones(B, bool)
    vocab = table.vocab
    for r, name in enumerate(names):
        ws = T.words(name)
        lens[r] = min(len(ws), D + 1)
        is_sys[r] = name.startswith("$")
        for i, w in enumerate(ws[:D]):
            words[r, i] = vocab.get(w, 0)
    return words, lens, is_sys
