"""Trie → flattened-NFA compiler: the device mirror of the route table.

Behavioral reference: the subscription index semantics of
``apps/emqx/src/emqx_trie.erl`` / ``emqx_topic.erl`` [U] (SURVEY.md §2.1);
the mirror/refresh pattern follows mria's bootstrap-then-replay design
(SURVEY.md §2.2, §5.4).

The wildcard filter set is compiled to static int32 arrays that an
unrolled NFA walk consumes (``emqx_tpu.ops.match_kernel``):

* **states** — trie nodes of the wildcard filter trie, BFS-numbered with
  root = 0.  ``#``-children are *not* states (``#`` is always terminal):
  they collapse into a per-state ``hash_accept`` id.
* ``node_tab`` (S, 4) int32 — per-state ``[plus_child, hash_accept,
  accept, 0]``, fetched with ONE wide gather per step (-1 = absent).
* ``edge_tab`` (Hb, BUCKET_SLOTS·4) int32 — literal edges in a
  **bucketed cuckoo table**: each bucket row holds BUCKET_SLOTS slots
  of ``[state, word, next, 0]`` (2 slots = 32 B rows; see the
  BUCKET_SLOTS note below for the measured reason).  A lookup is exactly TWO wide row-gathers (one per hash seed)
  plus vector compares — wide sequential slices are the access pattern
  TPU HBM likes; scattered narrow probes are ~10× slower (measured).
  2-choice bucketed cuckoo keeps the table small and gather-friendly
  (growth at 3/4 load, under the (2,2)-cuckoo ~0.89 threshold).
* **vocab** — host dict interning literal edge words to int32 ids.
  Id 0 is reserved UNKNOWN: publish-topic words never seen in any filter
  map to 0, which has no edges by construction (they still match
  ``+``/``#``).

Shapes are padded to buckets (powers of two) so that table growth rarely
changes compiled shapes (XLA recompiles are the p99 killer — SURVEY.md §7
hard parts).

Accept ids are dense in ``[0, n_accepts)``; ``accept_filters[aid]`` maps
back to the filter string, and the broker layer maps filters to subscriber
sets / bitmap rows.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .. import topic as T

__all__ = ["NfaTable", "compile_filters", "encode_topics", "BUCKET_SLOTS"]

# Slots per cuckoo bucket.  Round-5 on-chip measurement: gathering 32 B
# rows is 2.2x faster than 64 B rows on v5e (4.19 → 1.90 ms for the
# same probe count at 10M-scale Hb), and edge gathers are ~65% of
# kernel time — so 2 slots × 16 B beats 4 × 16 B despite the lower
# per-bucket load threshold ((2,2)-cuckoo sustains ~0.89; growth
# triggers at 3/4 either way).  Total table bytes are unchanged: half
# the slots per bucket, twice the buckets after growth.
BUCKET_SLOTS = 2     # slots per cuckoo bucket (row = 2 slots × 4 int32)
_MAX_KICKS = 500     # cuckoo random-walk bound before growing the table


def _bucket(n: int, minimum: int = 8) -> int:
    """Next power of two ≥ max(n, minimum)."""
    b = minimum
    while b < n:
        b <<= 1
    return b


def _bucket_hash(state, word, seed, mask):
    """Bucket index for (state, word) — uint32 math identical on host
    (numpy) and device (jnp).  Wraparound is intentional."""
    with np.errstate(over="ignore"):
        h = (
            state.astype(np.uint32) * np.uint32(2654435761)
            + word.astype(np.uint32) * np.uint32(2246822519)
            + np.uint32(seed)
        )
        h ^= h >> np.uint32(16)
        h *= np.uint32(3266489917)
        h ^= h >> np.uint32(13)
        return (h & np.uint32(mask)).astype(np.int32)


@dataclass
class NfaTable:
    """Flattened NFA snapshot (host numpy; ship with ``.device_arrays()``)."""

    node_tab: np.ndarray   # (S, 4) int32: [plus_child, hash_accept, accept, 0]
    edge_tab: np.ndarray   # (Hb, BUCKET_SLOTS*4) int32 [state, word, next, 0] slots
    seeds: np.ndarray      # (2,) int32 — cuckoo bucket-hash seeds
    n_states: int          # live states (≤ S)
    depth: int             # max filter levels the table supports (D)
    vocab: Dict[str, int]
    accept_filters: List[str]
    epoch: int = 0

    @property
    def S(self) -> int:
        return int(self.node_tab.shape[0])

    @property
    def Hb(self) -> int:
        return int(self.edge_tab.shape[0])

    @property
    def n_accepts(self) -> int:
        return len(self.accept_filters)

    def device_arrays(self):
        """The arrays the kernel consumes, in kernel argument order."""
        return (self.node_tab, self.edge_tab, self.seeds)

    def shape_key(self) -> Tuple[int, int, int]:
        """Compile-relevant shape signature; same key ⇒ no XLA recompile."""
        return (self.S, self.Hb, self.depth)

    # -- host-side reference lookup (tests / debugging) -------------------
    def lookup_literal(self, state: int, word_id: int) -> int:
        mask = self.Hb - 1
        for seed in self.seeds:
            b = int(_bucket_hash(np.int32(state), np.int32(word_id), seed, mask))
            row = self.edge_tab[b].reshape(BUCKET_SLOTS, 4)
            for s, w, nxt, _ in row:
                if s == state and w == word_id:
                    return int(nxt)
        return -1


class _Node:
    __slots__ = ("sid", "lit", "plus", "hash_aid", "aid")

    def __init__(self) -> None:
        self.sid = -1
        self.lit: Dict[str, "_Node"] = {}
        self.plus: Optional["_Node"] = None
        self.hash_aid = -1
        self.aid = -1


def _build_cuckoo(
    edges: List[Tuple[int, int, int]], rng: np.random.Generator
) -> Tuple[np.ndarray, np.ndarray]:
    """Place (state, word, next) edges into a 2-choice bucketed cuckoo table.
    Returns (edge_tab (Hb, BUCKET_SLOTS*4) int32, seeds (2,) int32)."""
    Hb = _bucket(max(1, int(len(edges) / (BUCKET_SLOTS * 0.85))), 8)
    while True:
        seeds = rng.integers(1, 2**31 - 1, size=2, dtype=np.int32)
        mask = Hb - 1
        slots: List[List[Optional[Tuple[int, int, int]]]] = [
            [None] * BUCKET_SLOTS for _ in range(Hb)
        ]
        ok = True
        for edge in edges:
            cur = edge
            placed = False
            for _ in range(_MAX_KICKS):
                s, w, nxt = cur
                b_opts = [
                    int(_bucket_hash(np.int32(s), np.int32(w), sd, mask))
                    for sd in seeds
                ]
                for b in b_opts:
                    row = slots[b]
                    for i in range(BUCKET_SLOTS):
                        if row[i] is None:
                            row[i] = cur
                            placed = True
                            break
                    if placed:
                        break
                if placed:
                    break
                # evict a random victim from a random candidate bucket
                b = b_opts[int(rng.integers(2))]
                i = int(rng.integers(BUCKET_SLOTS))
                cur, slots[b][i] = slots[b][i], cur
            if not placed:
                ok = False
                break
        if ok:
            tab = np.full((Hb, BUCKET_SLOTS, 4), -1, np.int32)
            for b in range(Hb):
                for i in range(BUCKET_SLOTS):
                    if slots[b][i] is not None:
                        s, w, nxt = slots[b][i]
                        tab[b, i] = (s, w, nxt, 0)
            return tab.reshape(Hb, BUCKET_SLOTS * 4), seeds
        Hb <<= 1  # insertion failed: grow and retry with fresh seeds


def compile_filters(
    filters: Iterable[str],
    depth: int = 16,
    state_bucket: int = 1024,
    epoch: int = 0,
    seed: int = 0xE709,
) -> NfaTable:
    """Compile a wildcard filter set into an :class:`NfaTable`.

    ``filters`` are real filters (``$share`` already stripped), deduplicated
    here.  Filters deeper than ``depth`` levels are rejected — the broker
    keeps them on the host slow path (see config ``tpu.max_levels``).
    """
    uniq = sorted(set(filters))
    root = _Node()
    accept_filters: List[str] = []

    # -- build the trie with '#' collapsed into hash_accept ---------------
    for flt in uniq:
        ws = T.words(flt)
        if len(ws) > depth:
            raise ValueError(
                f"filter {flt!r} has {len(ws)} levels > table depth {depth}"
            )
        node = root
        for i, w in enumerate(ws):
            if w == "#":
                assert i == len(ws) - 1, "validated upstream"
                if node.hash_aid < 0:
                    node.hash_aid = len(accept_filters)
                    accept_filters.append(flt)
                break
            if w == "+":
                if node.plus is None:
                    node.plus = _Node()
                node = node.plus
            else:
                nxt = node.lit.get(w)
                if nxt is None:
                    nxt = node.lit[w] = _Node()
                node = nxt
        else:
            if node.aid < 0:
                node.aid = len(accept_filters)
                accept_filters.append(flt)

    # -- BFS state numbering ----------------------------------------------
    order: List[_Node] = []
    root.sid = 0
    order.append(root)
    q = deque([root])
    while q:
        node = q.popleft()
        for child in list(node.lit.values()) + ([node.plus] if node.plus else []):
            child.sid = len(order)
            order.append(child)
            q.append(child)

    n_states = len(order)
    S = _bucket(n_states, state_bucket)
    node_tab = np.full((S, 4), -1, np.int32)
    node_tab[:, 3] = 0

    # -- vocab over literal edge words (0 = UNKNOWN) -----------------------
    vocab: Dict[str, int] = {}
    edges: List[Tuple[int, int, int]] = []  # (state, word_id, next_state)
    for node in order:
        node_tab[node.sid, 0] = node.plus.sid if node.plus is not None else -1
        node_tab[node.sid, 1] = node.hash_aid
        node_tab[node.sid, 2] = node.aid
        for w, child in node.lit.items():
            wid = vocab.get(w)
            if wid is None:
                wid = vocab[w] = len(vocab) + 1  # 0 reserved
            edges.append((node.sid, wid, child.sid))

    rng = np.random.default_rng(seed)
    edge_tab, seeds = _build_cuckoo(edges, rng)

    return NfaTable(
        node_tab=node_tab,
        edge_tab=edge_tab,
        seeds=seeds,
        n_states=n_states,
        depth=depth,
        vocab=vocab,
        accept_filters=accept_filters,
        epoch=epoch,
    )


def encode_topics(
    table: NfaTable, names: Sequence[str], batch: Optional[int] = None
):
    """Tokenize concrete topics for the kernel.

    Returns ``(words (B, D) int32, lens (B,) int32, is_sys (B,) bool)``
    padded to ``batch`` rows (default: len(names)).  Words beyond depth D
    are irrelevant to matching (only ``#`` accepts can fire past trie
    depth, and those depend on the first D words only); lengths are capped
    at D+1 so "deeper than D" uniformly means "no end-accept fires".
    Padding rows are inert: len sentinel D+2 (no end-accept can fire),
    ``is_sys`` True (suppresses root ``+``/``#`` at step 0) and all-UNKNOWN
    words (no literal edge exists for word id 0), so they match nothing.
    """
    from .encode import encode_batch

    return encode_batch(table, names, batch=batch)
