"""Dense matmul NFA walk — the MXU-native small-table match engine.

**Why this exists.**  Round-5 silicon run of the pallas VMEM kernel
(``pallas_match.py``) hit Mosaic's gather lowering limits: TPU Mosaic
supports only ``take_along_axis``-shaped 2D gathers (input/indices/
output the same shape), so arbitrary table lookups — the heart of the
walk — cannot lower (``ValueError: Shape mismatch in input, indices and
output``, recorded in BASELINE.md).  Rather than fight the gather unit,
this module removes gathers entirely: for a small table the trie walk
IS dense linear algebra, and the MXU is the fastest unit on the chip.

**The reformulation.**  Active-state sets become multi-hot rows
``active (B, S)`` instead of id lists, and one step of the walk is:

* literal edges: every state has exactly ONE incoming literal edge
  (its trie parent), so ``L[parent, child] = 1`` is a 0/1 matrix with
  at most one nonzero per column and ``active @ L`` lands each parent's
  activation on its children — exact in bf16, no accumulation happens.
  A child survives only if the topic word at this level equals its edge
  label: a broadcast compare against ``label (S,)``, no hash probes.
* ``+`` edges: same construction with ``P[state, plus_child] = 1``.
* accepts are bitmaps: ``ever-active ∧ has-hash-accept`` and
  ``active-at-len ∧ has-end-accept``, compacted to id lists on device.

No cuckoo probes, no ``top_k``, **no active-set cap and therefore no
spill**: the multi-hot row holds every reachable state, so this engine
is exact where the gather kernel fails open (``aover ≡ 0``).  Cost is
``2·D·B·S²`` bf16 MACs — pure MXU work that beats the HBM
random-gather kernel while ``S`` stays small (the hot tier of
``ops.tiered``); the gather kernel keeps the 1M–10M regime where S²
explodes.  Matrices ship once per epoch like every other table.

Semantics mirror ``nfa_match`` exactly (same accept rules, $-topic
root suppression, UNKNOWN word id 0 having no literal edges by
construction) and parity is tested against the host oracle AND the
gather kernel.  Reference behavior: ``emqx_trie:match/1`` [U]
(SURVEY.md §3.4).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .compiler import NfaTable
from .match_kernel import MatchResult, _compact

__all__ = ["DenseTable", "build_dense", "dense_match", "supports_dense",
           "bench_dense", "DENSE_STATE_CAP"]

# above this many states the S^2 matmuls lose to the gather kernel ON
# THE SAME SMALL TABLE.  Measured on v5e (bench_dense sweep, B=4096,
# 2026-07-30): S=256 → 1.75x, S=512 → 1.36x, S=2048 → 0.70x, S=4096 →
# 0.31x (FLOP-bound: 16 (B,S)x(S,S) bf16 matmuls at ~50% MXU
# efficiency).  Either engine on a small hot table beats the monolithic
# 150k-filter table's gather walk ~4x/topic (8.2 → 1.9-2.4 µs) — the
# tier win is mostly table smallness; dense adds exactness (no spill)
# and the extra 1.4-1.8x under this cap.  See BASELINE.md.
DENSE_STATE_CAP = 512
_LABEL_NONE = -7            # never equals a word id (those are >= 0)


class DenseTable(NamedTuple):
    """Device operands of the dense walk (host numpy until shipped)."""

    lmat: np.ndarray        # (S, S) f32 0/1 — literal edge parent→child
    pmat: np.ndarray        # (S, S) f32 0/1 — plus edge parent→child
    label: np.ndarray       # (S,) i32 — incoming literal word id, -7 none
    hacc: np.ndarray        # (S,) i32 — hash-accept id, -1 none
    eacc: np.ndarray        # (S,) i32 — end-accept id, -1 none

    @property
    def S(self) -> int:
        return int(self.label.shape[0])

    def device_arrays(self):
        return (self.lmat, self.pmat, self.label, self.hacc, self.eacc)


def supports_dense(table: NfaTable,
                   state_cap: int = DENSE_STATE_CAP) -> bool:
    return table.n_states <= state_cap


def build_dense(table: NfaTable, min_s: int = 128) -> DenseTable:
    """Dense operands from the compiled table; S is padded to a power
    of two ≥ live states (NOT ``table.S`` — the cuckoo layout pads far
    wider than the matmul wants to pay for)."""
    n = max(table.n_states, 1)
    S = min_s
    while S < n:
        S <<= 1
    lmat = np.zeros((S, S), np.float32)
    pmat = np.zeros((S, S), np.float32)
    label = np.full((S,), _LABEL_NONE, np.int32)
    hacc = np.full((S,), -1, np.int32)
    eacc = np.full((S,), -1, np.int32)
    node = table.node_tab
    hacc[:min(S, node.shape[0])] = node[:min(S, node.shape[0]), 1]
    eacc[:min(S, node.shape[0])] = node[:min(S, node.shape[0]), 2]
    plus = node[:n, 0]
    src = np.nonzero(plus >= 0)[0]
    pmat[src, plus[src]] = 1.0
    slots = table.edge_tab.reshape(-1, 4)
    live = slots[slots[:, 2] >= 0]          # [state, word, next, 0]
    lmat[live[:, 0], live[:, 2]] = 1.0
    label[live[:, 2]] = live[:, 1]
    return DenseTable(lmat, pmat, label, hacc, eacc)


@partial(jax.jit, static_argnames=("max_matches",))
def dense_match(
    words,      # (B, D) int32
    lens,       # (B,) int32
    is_sys,     # (B,) bool
    lmat,       # (S, S) f32/bf16
    pmat,       # (S, S) f32/bf16
    label,      # (S,) i32
    hacc,       # (S,) i32
    eacc,       # (S,) i32
    *,
    max_matches: int = 32,
) -> MatchResult:
    B, D = words.shape
    S = label.shape[0]
    dt = jnp.bfloat16
    lmat = lmat.astype(dt)
    pmat = pmat.astype(dt)

    root = jnp.zeros((B, S), dt).at[:, 0].set(1.0)
    active = root
    acc_h = jnp.zeros((B, S), bool)
    acc_e = jnp.zeros((B, S), bool)
    for t in range(D + 1):
        a = active > 0.5
        fire = a if t else a & ~is_sys[:, None]   # $-topics: no root fire
        acc_h = acc_h | fire
        acc_e = acc_e | (a & (t == lens)[:, None])
        if t == D:
            break
        lit_in = active @ lmat                     # (B, S) — exact: one
        plus_src = active if t else active * (~is_sys[:, None]).astype(dt)
        plus_in = plus_src @ pmat                  # nonzero per column
        wmatch = words[:, t][:, None] == label[None, :]
        nxt = jnp.where(wmatch, lit_in, 0) + plus_in
        alive = (t < lens)[:, None]
        active = (alive & (nxt > 0.5)).astype(dt)

    cand = jnp.concatenate(
        [jnp.where(acc_h & (hacc >= 0)[None, :], hacc[None, :], -1),
         jnp.where(acc_e & (eacc >= 0)[None, :], eacc[None, :], -1)],
        axis=1)                                    # (B, 2S)
    n = jnp.sum((cand >= 0).astype(jnp.int32), axis=1)
    matches = _compact(cand, max_matches)
    return MatchResult(
        matches=matches,
        n_matches=n,
        active_overflow=jnp.zeros((B,), jnp.int32),  # exact by design
        match_overflow=(n > max_matches).astype(jnp.int32),
    )


def bench_dense(n_filters: int = 420, batch: int = 4096,
                iters: int = 20, depth: int = 8) -> dict:
    """On-chip A/B: dense matmul walk vs the HBM gather kernel on the
    SAME small table — the hot-tier engine decision measurement.
    Default sized to land near DENSE_STATE_CAP states (the regime the
    tier actually runs in; S=4096 measured 0.31x and set the cap)."""
    import time

    from .compiler import compile_filters, encode_topics
    from .match_kernel import nfa_match

    rng = np.random.default_rng(11)
    filters = sorted({
        f"r{rng.integers(40)}/"
        + "/".join(("+" if rng.random() < 0.3 else f"w{rng.integers(30)}")
                   for _ in range(rng.integers(1, depth - 2)))
        + ("/#" if rng.random() < 0.2 else "")
        for _ in range(n_filters)})
    table = compile_filters(filters, depth=depth)
    dense = build_dense(table)
    topics = [f"r{rng.integers(40)}/" +
              "/".join(f"w{rng.integers(30)}"
                       for _ in range(rng.integers(1, depth - 1)))
              for _ in range(batch)]
    words, lens, is_sys = encode_topics(table, topics, batch=batch)
    jargs = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys))
    gargs = tuple(jnp.asarray(a) for a in table.device_arrays())
    dargs = tuple(jnp.asarray(a) for a in dense.device_arrays())
    out = {"n_filters": len(filters), "n_states": table.n_states,
           "dense_S": dense.S, "batch": batch}

    r = nfa_match(*jargs, *gargs, active_slots=8, compact_output=True,
                  max_matches=64)
    np.asarray(r.matches)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = nfa_match(*jargs, *gargs, active_slots=8, compact_output=True,
                      max_matches=64)
    np.asarray(r.matches)
    out["gather_ms"] = round((time.perf_counter() - t0) / iters * 1e3, 2)

    d = dense_match(*jargs, *dargs, max_matches=64)
    np.asarray(d.matches)
    t0 = time.perf_counter()
    for _ in range(iters):
        d = dense_match(*jargs, *dargs, max_matches=64)
    np.asarray(d.matches)
    out["dense_ms"] = round((time.perf_counter() - t0) / iters * 1e3, 2)
    out["dense_topics_per_s"] = int(batch / (out["dense_ms"] / 1e3))
    out["speedup_vs_gather"] = round(out["gather_ms"] / out["dense_ms"], 2)

    # parity on the measured batch (sets; gather rows that spilled are
    # excluded — dense cannot spill)
    ga = np.asarray(r.matches)
    da = np.asarray(d.matches)
    skip = np.asarray(r.spilled_rows()) | (np.asarray(d.match_overflow) > 0)
    mism = sum(
        1 for i in range(len(topics))
        if not skip[i]
        and set(ga[i][ga[i] >= 0]) != set(da[i][da[i] >= 0]))
    out["parity_mismatches"] = mism
    return out


if __name__ == "__main__":
    print(bench_dense())
