"""Two-tier hot/cold match table: VMEM pallas tier + HBM gather tier.

VERDICT r4 item 2 / SURVEY.md §5.7, §7 stage 4: the single-chip kernel
plateau is HBM-random-gather bound (ablation: edge+node gathers = 63–65%
of kernel time), and publish traffic is Zipfian over root prefixes
(BASELINE config 3).  So: partition the FILTER set by root word —

* **hot tier** — filters under the most-published root prefixes,
  compiled into a table small enough for VMEM
  (:func:`~emqx_tpu.ops.pallas_match.supports_table`), matched by the
  fused :func:`~emqx_tpu.ops.pallas_match.pallas_small_match` kernel
  where every probe hits VMEM;
* **cold tier** — every other filter, matched by the shipping HBM
  ``nfa_match`` gather kernel.

Root-level wildcard filters (``+``/``#`` first word) replicate into
BOTH tiers (same rule as :mod:`~emqx_tpu.parallel.prefix_ep`: a filter
can only match a topic whose root equals its own root, ``+`` or ``#``),
so each topic needs exactly ONE tier: per-batch routing splits topics
by root-prefix hotness, the Zipf-hot majority rides VMEM and only the
cold tail pays HBM gathers.  Correctness is therefore a partition
argument, and the parity suite checks the merged answer against the
host oracle per topic.

Tier selection (:func:`pick_hot_roots`) is observed-traffic-driven:
rank roots by published-topic counts (the serving engine's natural
byproduct), greedily admit while the projected hot table still fits
the VMEM budget, then verify by compiling.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .. import topic as T
from .compiler import NfaTable, compile_filters, encode_topics

__all__ = ["TieredTable", "TieredMatcher", "bench_tiered",
           "build_tiered", "pick_hot_roots", "split_filters"]


def _root(flt: str) -> str:
    return flt.split("/", 1)[0]


def split_filters(filters: Sequence[str],
                  hot_roots: Iterable[str]) -> Tuple[List[str], List[str]]:
    """(hot, cold) filter lists; root wildcards replicate into both."""
    hot_roots = set(hot_roots)
    hot: List[str] = []
    cold: List[str] = []
    for f in sorted(set(filters)):
        r = _root(f)
        if r in ("+", "#"):
            hot.append(f)
            cold.append(f)
        elif r in hot_roots:
            hot.append(f)
        else:
            cold.append(f)
    return hot, cold


def pick_hot_roots(
    filters: Sequence[str],
    topic_counts: Dict[str, int],
    vmem_budget_bytes: Optional[int] = None,
    depth: int = 8,
) -> List[str]:
    """Choose the hot root set: greediest published-traffic roots whose
    combined filter table is projected to fit VMEM.

    Projection: the compiled table costs ~(16 B/state node row) +
    (~16 B/edge amortized across cuckoo buckets); states+edges are
    bounded by total words over the tier's filters.  The builder
    verifies with a real compile and demotes if the estimate was low.
    """
    if vmem_budget_bytes is None:
        from .pallas_match import VMEM_BUDGET_BYTES

        vmem_budget_bytes = VMEM_BUDGET_BYTES
    by_root: Dict[str, List[str]] = {}
    for f in set(filters):
        by_root.setdefault(_root(f), []).append(f)
    by_root.pop("+", None)
    by_root.pop("#", None)

    def score(root: str) -> Tuple[int, int]:
        # primary: observed publishes; tie-break: filter density
        return (topic_counts.get(root, 0), len(by_root[root]))

    ranked = sorted(by_root, key=score, reverse=True)
    # ~2.2 table rows per filter word with padding/cuckoo headroom —
    # matches the native builder's bucket sizing heuristics
    budget_rows = vmem_budget_bytes // 16
    picked: List[str] = []
    rows = 0
    for root in ranked:
        if topic_counts and topic_counts.get(root, 0) == 0:
            break   # no observed traffic: not hot, stop admitting
        cost = int(sum(min(f.count("/") + 1, depth) for f in by_root[root])
                   * 2.2)
        if rows + cost > budget_rows:
            continue
        picked.append(root)
        rows += cost
    return picked


class TieredTable(NamedTuple):
    hot: Optional[NfaTable]     # None when no root qualified
    cold: NfaTable
    hot_roots: frozenset

    def stats(self) -> dict:
        hb = (int(self.hot.node_tab.nbytes + self.hot.edge_tab.nbytes)
              if self.hot is not None else 0)
        return {
            "hot_roots": len(self.hot_roots),
            "hot_filters": (len([f for f in self.hot.accept_filters
                                 if f is not None])
                            if self.hot is not None else 0),
            "cold_filters": len([f for f in self.cold.accept_filters
                                 if f is not None]),
            "hot_table_bytes": hb,
        }


def build_tiered(filters: Sequence[str], hot_roots: Iterable[str],
                 depth: int = 8) -> TieredTable:
    """Compile both tiers; demote lowest roots until the hot tier
    actually fits VMEM (the estimate in pick_hot_roots is a guess, the
    compile is the truth)."""
    from .pallas_match import supports_table

    roots = list(hot_roots)
    while roots:
        hot_f, cold_f = split_filters(filters, roots)
        hot_tab = compile_filters(hot_f, depth=depth) if hot_f else None
        if hot_tab is None or supports_table(hot_tab.node_tab,
                                             hot_tab.edge_tab):
            return TieredTable(hot_tab, compile_filters(cold_f, depth=depth),
                               frozenset(roots))
        roots.pop()   # demote the least-hot admitted root and retry
    _, cold_f = split_filters(filters, ())
    return TieredTable(None, compile_filters(cold_f, depth=depth),
                       frozenset())


def route(topics: Sequence[str], hot_roots: frozenset) \
        -> Tuple[List[int], List[int]]:
    """Per-batch routing: topic indices → (hot, cold) by root prefix."""
    hot_idx: List[int] = []
    cold_idx: List[int] = []
    for i, t in enumerate(topics):
        if t.split("/", 1)[0] in hot_roots:
            hot_idx.append(i)
        else:
            cold_idx.append(i)
    return hot_idx, cold_idx


class TieredMatcher:
    """End-to-end two-tier matcher (the serving-engine building block
    and the parity-test subject).

    ``match(topics) -> List[List[str]]`` per-topic matched filters;
    rows that spill either tier's active set fall open to the host
    oracle, same discipline as every other engine.
    """

    def __init__(self, table: TieredTable, depth: int = 8,
                 active_slots: int = 8, interpret: bool = False) -> None:
        self.table = table
        self.depth = depth
        self.active_slots = active_slots
        self.interpret = interpret   # pallas interpret mode (CPU tests)
        self.hot_batches = 0
        self.cold_batches = 0
        self.hot_topics = 0
        self.cold_topics = 0

    # pallas tile alignment
    @property
    def _tile(self) -> int:
        from .pallas_match import TILE_B

        return TILE_B

    def _match_hot(self, topics: List[str]) -> List[List[str]]:
        import jax.numpy as jnp

        from .pallas_match import pallas_small_match

        tab = self.table.hot
        B = max(self._tile,
                -(-len(topics) // self._tile) * self._tile)
        words, lens, is_sys = encode_topics(tab, topics, batch=B)
        acc, aover = pallas_small_match(
            jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in tab.device_arrays()],
            depth=self.depth, active_slots=self.active_slots,
            interpret=self.interpret)
        acc = np.asarray(acc)[: len(topics)]
        aover = np.asarray(aover)[: len(topics)]
        self.hot_batches += 1
        self.hot_topics += len(topics)
        return self._decode(acc, aover, tab, topics)

    def _match_cold(self, topics: List[str]) -> List[List[str]]:
        import jax.numpy as jnp

        from .match_kernel import nfa_match

        tab = self.table.cold
        words, lens, is_sys = encode_topics(tab, topics)
        res = nfa_match(
            jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in tab.device_arrays()],
            active_slots=self.active_slots, compact_output=False)
        acc = np.asarray(res.matches)[: len(topics)]
        aover = np.asarray(res.active_overflow)[: len(topics)]
        self.cold_batches += 1
        self.cold_topics += len(topics)
        return self._decode(acc, aover, tab, topics)

    def _decode(self, acc, aover, tab: NfaTable,
                topics: List[str]) -> List[List[str]]:
        out: List[List[str]] = []
        live = [f for f in tab.accept_filters]
        for r, t in enumerate(topics):
            if aover[r]:
                # fail-open: this row's walk spilled; host oracle serves
                out.append(sorted(
                    f for f in live
                    if f is not None and T.match(t, f)))
                continue
            row = acc[r]
            out.append([live[a] for a in row[row >= 0]])
        return out

    def match(self, topics: Sequence[str]) -> List[List[str]]:
        topics = list(topics)
        if self.table.hot is None:
            return self._match_cold(topics)
        hot_idx, cold_idx = route(topics, self.table.hot_roots)
        out: List[Optional[List[str]]] = [None] * len(topics)
        if hot_idx:
            for i, row in zip(hot_idx,
                              self._match_hot([topics[i]
                                               for i in hot_idx])):
                out[i] = row
        if cold_idx:
            for i, row in zip(cold_idx,
                              self._match_cold([topics[i]
                                                for i in cold_idx])):
                out[i] = row
        return out  # type: ignore[return-value]

    def info(self) -> dict:
        return {
            **self.table.stats(),
            "hot_topics": self.hot_topics,
            "cold_topics": self.cold_topics,
            "hot_batches": self.hot_batches,
            "cold_batches": self.cold_batches,
        }


def bench_tiered(n_filters: int = 200_000, batch: int = 8192,
                 iters: int = 10, depth: int = 8,
                 hot_mass: float = 0.8) -> dict:
    """On-chip A/B (run when a TPU is attached; CPU runs are interpret-
    mode and only prove parity): Zipf-routed traffic through the
    two-tier table vs everything through the HBM kernel.

    ``hot_mass`` = fraction of published topics landing on hot roots.
    """
    import time

    import jax.numpy as jnp

    from .match_kernel import nfa_match

    rng = np.random.default_rng(5)
    n_roots = 200
    # Zipf filter mass over roots
    weights = 1.0 / np.arange(1, n_roots + 1)
    weights /= weights.sum()
    filters = sorted({
        f"r{rng.choice(n_roots, p=weights)}/"
        + "/".join(("+" if rng.random() < 0.3 else f"w{rng.integers(50)}")
                   for _ in range(rng.integers(1, depth - 2)))
        + ("/#" if rng.random() < 0.2 else "")
        for _ in range(n_filters)
    })
    # traffic: hot_mass of topics under the top roots
    counts = {f"r{i}": int(1e6 * weights[i]) for i in range(n_roots)}
    hot_roots = pick_hot_roots(filters, counts, depth=depth)
    tiered = build_tiered(filters, hot_roots, depth=depth)
    import jax

    # pallas needs interpret mode off-TPU; the honest A/B number is the
    # on-chip one (CPU runs only prove plumbing)
    tm = TieredMatcher(tiered, depth=depth,
                       interpret=jax.devices()[0].platform == "cpu")
    hot_list = sorted(tiered.hot_roots)   # entries are full roots ("r7")
    assert hot_list, "A/B needs a non-empty hot tier; check the workload"
    topics = []
    for _ in range(batch):
        if rng.random() < hot_mass:
            root = hot_list[rng.integers(len(hot_list))]
        else:
            root = f"r{rng.integers(n_roots)}"
        topics.append(root + "/"
                      + "/".join(f"w{rng.integers(50)}"
                                 for _ in range(rng.integers(1, depth - 2))))

    out = {"n_filters": len(filters), **tiered.stats()}
    full = compile_filters(filters, depth=depth)
    words, lens, is_sys = encode_topics(full, topics, batch=batch)
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in full.device_arrays()])
    r = nfa_match(*args, active_slots=8, compact_output=False)
    np.asarray(r.matches)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = nfa_match(*args, active_slots=8, compact_output=False)
    np.asarray(r.matches)
    out["hbm_only_ms"] = round((time.perf_counter() - t0) / iters * 1e3, 2)

    tm.match(topics[:256])   # warm both tiers' compiles
    t0 = time.perf_counter()
    for _ in range(iters):
        tm.match(topics)
    out["tiered_ms"] = round((time.perf_counter() - t0) / iters * 1e3, 2)
    out["routing"] = {"hot_topics": tm.hot_topics,
                      "cold_topics": tm.cold_topics}
    return out
