"""Two-tier hot/cold match table: VMEM pallas tier + HBM gather tier.

VERDICT r4 item 2 / SURVEY.md §5.7, §7 stage 4: the single-chip kernel
plateau is HBM-random-gather bound (ablation: edge+node gathers = 63–65%
of kernel time), and publish traffic is Zipfian over root prefixes
(BASELINE config 3).  So: partition the FILTER set by root word —

* **hot tier** — filters under the most-published root prefixes,
  compiled into a table small enough for a gather-free engine;
* **cold tier** — every other filter, matched by the shipping HBM
  ``nfa_match`` gather kernel.

**Hot-tier engine (round 5).**  The pallas VMEM kernel
(:func:`~emqx_tpu.ops.pallas_match.pallas_small_match`) was rejected by
Mosaic on real silicon (gather lowering limits — see
``ops/dense_match.py`` docstring and BASELINE.md), so the shipping hot
engine is the **dense matmul walk** (:mod:`~emqx_tpu.ops.dense_match`):
MXU-native, exact (no active-set spill), viable while the hot tier
stays under ``DENSE_STATE_CAP`` states.  Resolution is ``auto``:
interpret mode keeps pallas parity coverage on the CPU mesh; on device
the chain is dense → plain ``nfa_match`` on the (smaller) hot table,
and any engine failure at runtime demotes down the chain rather than
dropping traffic.

Root-level wildcard filters (``+``/``#`` first word) replicate into
BOTH tiers (same rule as :mod:`~emqx_tpu.parallel.prefix_ep`: a filter
can only match a topic whose root equals its own root, ``+`` or ``#``),
so each topic needs exactly ONE tier: per-batch routing splits topics
by root-prefix hotness, the Zipf-hot majority rides VMEM and only the
cold tail pays HBM gathers.  Correctness is therefore a partition
argument, and the parity suite checks the merged answer against the
host oracle per topic.

Tier selection (:func:`pick_hot_roots`) is observed-traffic-driven:
rank roots by published-topic counts (the serving engine's natural
byproduct), greedily admit while the projected hot table still fits
the VMEM budget, then verify by compiling.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .. import topic as T
from .compiler import NfaTable, compile_filters, encode_topics

__all__ = ["TieredTable", "TieredMatcher", "bench_tiered",
           "build_tiered", "fused_tiered_match", "pick_hot_roots",
           "split_filters"]


def _root(flt: str) -> str:
    return flt.split("/", 1)[0]


def fused_tiered_match(hot_args, cold_args, active_slots: int = 8,
                       max_matches: int = 64):
    """BOTH tiers in ONE jit → one XLA program → one dispatch.

    Measured on v5e over the dev tunnel (2026-07-30): the two tiers
    dispatched separately cost 7.8 + 6.9 ms but 22.8 ms when issued as
    two executables per serving iteration (~8 ms launch overhead per
    extra dispatch on a remote-attached device); fusing restores the
    sum.  Returns ``(dense MatchResult, gather MatchResult)``.
    ``hot_args``/``cold_args`` are the positional tuples of
    :func:`~emqx_tpu.ops.dense_match.dense_match` /
    :func:`~emqx_tpu.ops.match_kernel.nfa_match`.
    """
    import jax

    from .dense_match import dense_match
    from .match_kernel import nfa_match

    key = (active_slots, max_matches)
    fn = _fused_cache.get(key)
    if fn is None:
        def _run(hargs, cargs):
            return (dense_match(*hargs, max_matches=max_matches),
                    nfa_match(*cargs, active_slots=active_slots,
                              compact_output=False))

        fn = _fused_cache[key] = jax.jit(_run)
    return fn(hot_args, cold_args)


_fused_cache: Dict[Tuple[int, int], object] = {}


def split_filters(filters: Sequence[str],
                  hot_roots: Iterable[str]) -> Tuple[List[str], List[str]]:
    """(hot, cold) filter lists; root wildcards replicate into both."""
    hot_roots = set(hot_roots)
    hot: List[str] = []
    cold: List[str] = []
    for f in sorted(set(filters)):
        r = _root(f)
        if r in ("+", "#"):
            hot.append(f)
            cold.append(f)
        elif r in hot_roots:
            hot.append(f)
        else:
            cold.append(f)
    return hot, cold


def pick_hot_roots(
    filters: Sequence[str],
    topic_counts: Dict[str, int],
    vmem_budget_bytes: Optional[int] = None,
    depth: int = 8,
    state_budget: Optional[int] = None,
) -> List[str]:
    """Choose the hot root set: greediest published-traffic roots whose
    combined filter table is projected to fit VMEM.

    Projection: the compiled table costs ~(16 B/state node row) +
    (~16 B/edge amortized across cuckoo buckets); states+edges are
    bounded by total words over the tier's filters.  The builder
    verifies with a real compile and demotes if the estimate was low.
    """
    if vmem_budget_bytes is None:
        from .pallas_match import VMEM_BUDGET_BYTES

        vmem_budget_bytes = VMEM_BUDGET_BYTES
    by_root: Dict[str, List[str]] = {}
    for f in set(filters):
        by_root.setdefault(_root(f), []).append(f)
    by_root.pop("+", None)
    by_root.pop("#", None)

    def score(root: str) -> Tuple[int, int]:
        # primary: observed publishes; tie-break: filter density
        return (topic_counts.get(root, 0), len(by_root[root]))

    ranked = sorted(by_root, key=score, reverse=True)
    # ~2.2 table rows per filter word with padding/cuckoo headroom —
    # matches the native builder's bucket sizing heuristics
    budget_rows = vmem_budget_bytes // 16
    if state_budget is not None:
        # dense-tier mode: the budget is STATES (the matmul cost is
        # S^2); the same words-per-filter estimate upper-bounds states
        budget_rows = state_budget
    picked: List[str] = []
    rows = 0
    for root in ranked:
        if topic_counts and topic_counts.get(root, 0) == 0:
            break   # no observed traffic: not hot, stop admitting
        cost = int(sum(min(f.count("/") + 1, depth) for f in by_root[root])
                   * 2.2)
        if rows + cost > budget_rows:
            continue
        picked.append(root)
        rows += cost
    return picked


class TieredTable(NamedTuple):
    hot: Optional[NfaTable]     # None when no root qualified
    cold: NfaTable
    hot_roots: frozenset

    def stats(self) -> dict:
        hb = (int(self.hot.node_tab.nbytes + self.hot.edge_tab.nbytes)
              if self.hot is not None else 0)
        return {
            "hot_roots": len(self.hot_roots),
            "hot_filters": (len([f for f in self.hot.accept_filters
                                 if f is not None])
                            if self.hot is not None else 0),
            "cold_filters": len([f for f in self.cold.accept_filters
                                 if f is not None]),
            "hot_table_bytes": hb,
        }


def build_tiered(filters: Sequence[str], hot_roots: Iterable[str],
                 depth: int = 8, fit=None) -> TieredTable:
    """Compile both tiers; demote lowest roots until the hot tier
    actually fits its engine's budget (the estimate in pick_hot_roots
    is a guess, the compile is the truth).  ``fit(NfaTable) -> bool``
    defaults to the pallas VMEM check; pass
    ``dense_match.supports_dense`` when building for the dense tier."""
    if fit is None:
        from .pallas_match import supports_table

        def fit(tab):
            return supports_table(tab.node_tab, tab.edge_tab)

    roots = list(hot_roots)
    while roots:
        hot_f, cold_f = split_filters(filters, roots)
        hot_tab = compile_filters(hot_f, depth=depth) if hot_f else None
        if hot_tab is None or fit(hot_tab):
            return TieredTable(hot_tab, compile_filters(cold_f, depth=depth),
                               frozenset(roots))
        roots.pop()   # demote the least-hot admitted root and retry
    _, cold_f = split_filters(filters, ())
    return TieredTable(None, compile_filters(cold_f, depth=depth),
                       frozenset())


def route(topics: Sequence[str], hot_roots: frozenset) \
        -> Tuple[List[int], List[int]]:
    """Per-batch routing: topic indices → (hot, cold) by root prefix."""
    hot_idx: List[int] = []
    cold_idx: List[int] = []
    for i, t in enumerate(topics):
        if t.split("/", 1)[0] in hot_roots:
            hot_idx.append(i)
        else:
            cold_idx.append(i)
    return hot_idx, cold_idx


class TieredMatcher:
    """End-to-end two-tier matcher (the serving-engine building block
    and the parity-test subject).

    ``match(topics) -> List[List[str]]`` per-topic matched filters;
    rows that spill either tier's active set fall open to the host
    oracle, same discipline as every other engine.
    """

    def __init__(self, table: TieredTable, depth: int = 8,
                 active_slots: int = 8, interpret: bool = False,
                 hot_engine: str = "auto") -> None:
        self.table = table
        self.depth = depth
        self.active_slots = active_slots
        self.interpret = interpret   # pallas interpret mode (CPU tests)
        if hot_engine not in ("auto", "pallas", "dense", "xla"):
            raise ValueError(f"unknown hot_engine {hot_engine!r}")
        self.hot_engine = hot_engine
        self._dense = None           # built on first dense-tier batch
        self.hot_batches = 0
        self.cold_batches = 0
        self.hot_topics = 0
        self.cold_topics = 0

    def _resolved_hot_engine(self) -> str:
        if self.hot_engine != "auto":
            return self.hot_engine
        if self.interpret:
            self.hot_engine = "pallas"   # CPU-mesh parity coverage
            return "pallas"
        from .dense_match import supports_dense

        self.hot_engine = ("dense" if supports_dense(self.table.hot)
                           else "xla")
        return self.hot_engine

    def _demote_hot(self, exc: Exception) -> None:
        """An engine failed at runtime (e.g. Mosaic rejecting pallas on
        this TPU generation): demote down the chain, never drop."""
        import logging

        from .dense_match import supports_dense

        chain = ("dense" if self.hot_engine == "pallas"
                 and supports_dense(self.table.hot) else "xla")
        logging.getLogger(__name__).warning(
            "tiered hot engine %r failed (%s: %s); demoting to %r",
            self.hot_engine, type(exc).__name__, str(exc)[:200], chain)
        self.hot_engine = chain

    # pallas tile alignment
    @property
    def _tile(self) -> int:
        from .pallas_match import TILE_B

        return TILE_B

    def _match_hot(self, topics: List[str]) -> List[List[str]]:
        engine = self._resolved_hot_engine()
        try:
            if engine == "pallas":
                rows = self._match_hot_pallas(topics)
            elif engine == "dense":
                rows = self._match_hot_dense(topics)
            else:
                rows = self._match_gather(topics, self.table.hot)
            self.hot_batches += 1
            self.hot_topics += len(topics)
            return rows
        except Exception as e:  # noqa: BLE001 — demote, don't drop
            if self.interpret or engine == "xla":
                raise               # CPU tests / last rung: surface it
            self._demote_hot(e)
            return self._match_hot(topics)

    def _match_hot_pallas(self, topics: List[str]) -> List[List[str]]:
        import jax.numpy as jnp

        from .pallas_match import pallas_small_match

        tab = self.table.hot
        B = max(self._tile,
                -(-len(topics) // self._tile) * self._tile)
        words, lens, is_sys = encode_topics(tab, topics, batch=B)
        acc, aover = pallas_small_match(
            jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in tab.device_arrays()],
            depth=self.depth, active_slots=self.active_slots,
            interpret=self.interpret)
        acc = np.asarray(acc)[: len(topics)]
        aover = np.asarray(aover)[: len(topics)]
        return self._decode(acc, aover, tab, topics)

    def _match_hot_dense(self, topics: List[str]) -> List[List[str]]:
        import jax.numpy as jnp

        from .dense_match import build_dense, dense_match

        tab = self.table.hot
        if self._dense is None:
            self._dense = build_dense(tab)
        # pad to a stable power-of-two batch (recompiles are the p99
        # killer); 256 floors the MXU sublane dimension usefully
        B = 256
        while B < len(topics):
            B <<= 1
        words, lens, is_sys = encode_topics(tab, topics, batch=B)
        res = dense_match(
            jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in self._dense.device_arrays()],
            max_matches=64)
        acc = np.asarray(res.matches)[: len(topics)]
        # dense never spills the active set; only count>K rows need the
        # host oracle, and _decode's fail-open handles exactly those
        mover = np.asarray(res.match_overflow)[: len(topics)]
        return self._decode(acc, mover, tab, topics)

    def _match_gather(self, topics: List[str],
                      tab: NfaTable) -> List[List[str]]:
        import jax.numpy as jnp

        from .match_kernel import nfa_match

        words, lens, is_sys = encode_topics(tab, topics)
        res = nfa_match(
            jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in tab.device_arrays()],
            active_slots=self.active_slots, compact_output=False)
        acc = np.asarray(res.matches)[: len(topics)]
        aover = np.asarray(res.active_overflow)[: len(topics)]
        return self._decode(acc, aover, tab, topics)

    def _match_cold(self, topics: List[str]) -> List[List[str]]:
        rows = self._match_gather(topics, self.table.cold)
        self.cold_batches += 1
        self.cold_topics += len(topics)
        return rows

    def _decode(self, acc, aover, tab: NfaTable,
                topics: List[str]) -> List[List[str]]:
        out: List[List[str]] = []
        live = [f for f in tab.accept_filters]
        for r, t in enumerate(topics):
            if aover[r]:
                # fail-open: this row's walk spilled; host oracle serves
                out.append(sorted(
                    f for f in live
                    if f is not None and T.match(t, f)))
                continue
            row = acc[r]
            out.append([live[a] for a in row[row >= 0]])
        return out

    def match(self, topics: Sequence[str]) -> List[List[str]]:
        topics = list(topics)
        if self.table.hot is None:
            return self._match_cold(topics)
        hot_idx, cold_idx = route(topics, self.table.hot_roots)
        out: List[Optional[List[str]]] = [None] * len(topics)
        if hot_idx:
            for i, row in zip(hot_idx,
                              self._match_hot([topics[i]
                                               for i in hot_idx])):
                out[i] = row
        if cold_idx:
            for i, row in zip(cold_idx,
                              self._match_cold([topics[i]
                                                for i in cold_idx])):
                out[i] = row
        return out  # type: ignore[return-value]

    def info(self) -> dict:
        return {
            **self.table.stats(),
            "hot_engine": self.hot_engine,
            "hot_topics": self.hot_topics,
            "cold_topics": self.cold_topics,
            "hot_batches": self.hot_batches,
            "cold_batches": self.cold_batches,
        }


def bench_tiered(n_filters: int = 200_000, batch: int = 8192,
                 iters: int = 10, depth: int = 8,
                 hot_mass: float = 0.8) -> dict:
    """On-chip A/B (run when a TPU is attached; CPU runs are interpret-
    mode and only prove parity): Zipf-routed traffic through the
    two-tier table vs everything through the HBM kernel.

    ``hot_mass`` = fraction of published topics landing on hot roots.
    """
    import time

    import jax.numpy as jnp

    from .match_kernel import nfa_match

    rng = np.random.default_rng(5)
    # The regime the tier targets (and real MQTT fleets show): traffic
    # mass and filter mass ANTI-correlated — hot telemetry roots carry
    # a handful of wildcard subscriptions (dashboards, auditors), the
    # long command/config tail carries the bulk of the filter set.
    # When hot-traffic roots are also filter-heavy, pick_hot_roots
    # admits nothing and the tier degenerates to cold-only — measured
    # round 5: a 200-root Zipf-shared workload seats no root under
    # DENSE_STATE_CAP and the A/B is vacuous.
    n_hot_roots = 40
    hot_root_names = [f"h{i}" for i in range(n_hot_roots)]
    n_roots = 5000
    filters = sorted(
        {f"{r}/" + "/".join(
            ("+" if rng.random() < 0.3 else f"w{rng.integers(50)}")
            for _ in range(rng.integers(1, depth - 2)))
         + ("/#" if rng.random() < 0.2 else "")
         for r in hot_root_names for _ in range(8)}
        | {f"r{rng.integers(n_roots)}/" + "/".join(
            ("+" if rng.random() < 0.3 else f"w{rng.integers(50)}")
            for _ in range(rng.integers(1, depth - 2)))
           + ("/#" if rng.random() < 0.2 else "")
           for _ in range(n_filters)})
    # traffic: hot_mass of topics under the top roots.  The hot tier is
    # sized for the DENSE engine (S <= DENSE_STATE_CAP): the tiered win
    # exists when hot-traffic roots carry few filters — this workload
    # constructs that regime; heavier hot roots simply stay cold.
    from .dense_match import DENSE_STATE_CAP, supports_dense

    counts = {r: 1_000_000 for r in hot_root_names}
    counts.update({f"r{i}": 10 for i in range(50)})
    hot_roots = pick_hot_roots(filters, counts, depth=depth,
                               state_budget=DENSE_STATE_CAP)
    tiered = build_tiered(filters, hot_roots, depth=depth,
                          fit=supports_dense)
    import jax

    # pallas needs interpret mode off-TPU; the honest A/B number is the
    # on-chip one (CPU runs only prove plumbing)
    tm = TieredMatcher(tiered, depth=depth,
                       interpret=jax.devices()[0].platform == "cpu")
    hot_list = sorted(tiered.hot_roots)   # entries are full roots ("r7")
    assert hot_list, "A/B needs a non-empty hot tier; check the workload"
    topics = []
    for _ in range(batch):
        if rng.random() < hot_mass:
            root = hot_list[rng.integers(len(hot_list))]
        else:
            root = f"r{rng.integers(n_roots)}"
        topics.append(root + "/"
                      + "/".join(f"w{rng.integers(50)}"
                                 for _ in range(rng.integers(1, depth - 2))))

    out = {"n_filters": len(filters), **tiered.stats()}
    full = compile_filters(filters, depth=depth)
    words, lens, is_sys = encode_topics(full, topics, batch=batch)
    args = (jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
            *[jnp.asarray(a) for a in full.device_arrays()])
    r = nfa_match(*args, active_slots=8, compact_output=False)
    np.asarray(r.matches)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = nfa_match(*args, active_slots=8, compact_output=False)
    np.asarray(r.matches)
    out["hbm_only_ms"] = round((time.perf_counter() - t0) / iters * 1e3, 2)

    # arm B — routed device cost: hot subset through the dense engine,
    # cold subset through the gather kernel on the (smaller) cold
    # table.  Device path only (encode once, readback to numpy), same
    # as arm A: the serving engine decodes flat output on both arms,
    # so python per-topic decode belongs to neither measurement.
    from .dense_match import build_dense, dense_match

    hot_idx, cold_idx = route(topics, tiered.hot_roots)
    out["routing"] = {"hot_topics": len(hot_idx),
                      "cold_topics": len(cold_idx)}
    hot_names = [topics[i] for i in hot_idx]
    cold_names = [topics[i] for i in cold_idx]

    def _pow2(n: int, floor: int = 256) -> int:
        b = floor
        while b < n:
            b <<= 1
        return b

    dense = build_dense(tiered.hot)
    hw, hl, hs = encode_topics(tiered.hot, hot_names,
                               batch=_pow2(len(hot_names)))
    hargs = (jnp.asarray(hw), jnp.asarray(hl), jnp.asarray(hs),
             *[jnp.asarray(a) for a in dense.device_arrays()])
    cw, cl, cs = encode_topics(tiered.cold, cold_names,
                               batch=_pow2(len(cold_names)))
    cargs = (jnp.asarray(cw), jnp.asarray(cl), jnp.asarray(cs),
             *[jnp.asarray(a) for a in tiered.cold.device_arrays()])

    def routed_pass():
        d = dense_match(*hargs, max_matches=64)
        c = nfa_match(*cargs, active_slots=8, compact_output=False)
        return d, c

    d, c = routed_pass()                # warm both compiles
    np.asarray(d.matches), np.asarray(c.matches)
    # async loop, one sync at the end — IDENTICAL methodology to the
    # hbm-only arm above (amortized pipelined device time per batch;
    # a per-iter sync would bill the tunnel's round-trip floor, ~70 ms
    # on 2026-07-30, to every iteration of this arm only)
    t0 = time.perf_counter()
    for _ in range(iters):
        d, c = routed_pass()
    np.asarray(d.matches), np.asarray(c.matches)
    out["tiered_ms"] = round((time.perf_counter() - t0) / iters * 1e3, 2)
    out["speedup"] = round(out["hbm_only_ms"] / out["tiered_ms"], 2)
    out["dense_S"] = dense.S

    # arm C — both tiers fused into one XLA program (one dispatch):
    # the serving-path configuration
    d, c = fused_tiered_match(hargs, cargs)
    np.asarray(d.matches), np.asarray(c.matches)
    t0 = time.perf_counter()
    for _ in range(iters):
        d, c = fused_tiered_match(hargs, cargs)
    np.asarray(d.matches), np.asarray(c.matches)
    out["tiered_fused_ms"] = round(
        (time.perf_counter() - t0) / iters * 1e3, 2)
    out["speedup_fused"] = round(
        out["hbm_only_ms"] / out["tiered_fused_ms"], 2)

    # correctness plumbing: the TieredMatcher end-to-end path agrees
    # with the host oracle on a slice (the full parity suite lives in
    # tests/test_tiered.py / test_dense_match.py)
    sample = topics[:128]
    got = tm.match(sample)
    mism = sum(1 for t, rows in zip(sample, got)
               if sorted(rows) != sorted(f for f in filters
                                         if T.match(t, f)))
    out["hot_engine"] = tm.hot_engine
    out["parity_mismatches_128"] = mism
    return out
