"""Batched NFA wildcard-match kernel — the device hot path.

Replaces the per-publish ``emqx_trie:match/1`` walk (reference hot loop #1,
SURVEY.md §3.4) with ONE ``lax.scan`` NFA evaluation over a whole topic
batch:

* carry: ``active`` (B, A) int32 — the NFA active-state set per topic,
  -1 padded.  Active sets are **duplicate-free by construction**: a trie
  node is reachable from the root by exactly one label path, so at step t
  each matching depth-t node appears at most once.  Compaction is therefore
  a plain descending sort (valids first), no dedup pass.
* per step t ∈ [0, D]:

  - ``#``-accepts fire for every active state (a ``#`` child matches the
    zero remaining levels too, which is why the scan runs D+1 steps);
  - end-accepts fire when t == topic length;
  - transitions gather the literal edge via a statically-bounded
    linear-probe hash lookup plus the ``+`` edge, masked for t ≥ length
    and for the root-level-wildcard-vs-$-topic rule at t == 0.

Outputs per topic: up to K matched accept ids (sorted descending, -1
padded), the exact match count, plus overflow counters (active-set spill
beyond A, match spill beyond K) for SLO monitoring — spills mean the host
must re-run those topics on the authoritative trie (fail-open, SURVEY.md
§5.3).

Everything is int32, static shapes, no data-dependent control flow — one
XLA compilation per (D, A, K, B, S, H) bucket.
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .compiler import MAX_PROBES, NfaTable, encode_topics

__all__ = ["MatchResult", "build_matcher", "match_topics"]


class MatchResult(NamedTuple):
    matches: jax.Array     # (B, K) int32 accept ids, descending, -1 pad
    n_matches: jax.Array   # (B,) int32 exact count (may exceed K)
    active_overflow: jax.Array  # () int32 — active-set spills (correctness!)
    match_overflow: jax.Array   # () int32 — rows with count > K


def _slot(state: jax.Array, word: jax.Array, mask: int) -> jax.Array:
    """Device twin of compiler._slot — identical uint32 mixing."""
    h = state.astype(jnp.uint32) * jnp.uint32(2654435761) + word.astype(
        jnp.uint32
    ) * jnp.uint32(2246822519)
    h = h ^ (h >> jnp.uint32(15))
    h = h * jnp.uint32(2246822519)
    h = h ^ (h >> jnp.uint32(13))
    return (h & jnp.uint32(mask)).astype(jnp.int32)


def _probe(state, word, tab_state, tab_word, tab_next):
    """Literal-edge lookup for a (B, A) block of (state, word) pairs.

    The build bounds every probe chain to MAX_PROBES slots, and keys are
    compared exactly, so scanning all MAX_PROBES candidate slots needs no
    empty-slot early exit."""
    H = tab_state.shape[0]
    mask = H - 1
    h = _slot(state, word, mask)
    res = jnp.full_like(state, -1)
    for i in range(MAX_PROBES):
        idx = (h + i) & mask
        hit = (tab_state[idx] == state) & (tab_word[idx] == word)
        res = jnp.where((res < 0) & hit, tab_next[idx], res)
    return res


@partial(jax.jit, static_argnames=("active_slots", "max_matches"))
def nfa_match(
    words,        # (B, D) int32
    lens,         # (B,) int32
    is_sys,       # (B,) bool
    plus_child,   # (S,) int32
    hash_accept,  # (S,) int32
    accept,       # (S,) int32
    tab_state,    # (H,) int32
    tab_word,     # (H,) int32
    tab_next,     # (H,) int32
    *,
    active_slots: int = 32,
    max_matches: int = 64,
) -> MatchResult:
    B, D = words.shape
    A = active_slots
    K = max_matches

    # transposed word columns so scan consumes one column per step;
    # step D has no transition (masked), column is a dummy repeat.
    wcols = jnp.concatenate([words.T, words.T[-1:]], axis=0)  # (D+1, B)
    ts = jnp.arange(D + 1, dtype=jnp.int32)

    active0 = jnp.full((B, A), -1, jnp.int32).at[:, 0].set(0)  # {root}

    def step(active, xs):
        t, w = xs                      # t: (), w: (B,)
        valid = active >= 0
        sa = jnp.maximum(active, 0)    # safe gather index
        sys0 = is_sys & (t == 0)       # (B,) root-wildcard suppression

        # --- fire accepts ---------------------------------------------
        hacc = jnp.where(valid, hash_accept[sa], -1)
        hacc = jnp.where(sys0[:, None], -1, hacc)
        at_end = (t == lens)[:, None]
        eacc = jnp.where(valid & at_end, accept[sa], -1)
        accepts_t = jnp.concatenate([hacc, eacc], axis=1)  # (B, 2A)

        # --- transition ------------------------------------------------
        lit = _probe(
            jnp.where(valid, active, -1), jnp.broadcast_to(w[:, None], (B, A)),
            tab_state, tab_word, tab_next,
        )
        lit = jnp.where(valid, lit, -1)
        plus = jnp.where(valid, plus_child[sa], -1)
        plus = jnp.where(sys0[:, None], -1, plus)
        cand = jnp.concatenate([lit, plus], axis=1)        # (B, 2A)
        cand = jnp.where((t < lens)[:, None], cand, -1)
        cand = -jnp.sort(-cand, axis=1)                    # valids first
        new_active = cand[:, :A]
        spill = jnp.sum((cand[:, A:] >= 0).astype(jnp.int32))
        return new_active, (accepts_t, spill)

    _, (accepts, spills) = jax.lax.scan(step, active0, (ts, wcols))
    # accepts: (D+1, B, 2A) → (B, (D+1)·2A)
    flat = jnp.transpose(accepts, (1, 0, 2)).reshape(B, -1)
    flat = -jnp.sort(-flat, axis=1)
    n = jnp.sum((flat >= 0).astype(jnp.int32), axis=1)
    return MatchResult(
        matches=flat[:, :K],
        n_matches=n,
        active_overflow=jnp.sum(spills),
        match_overflow=jnp.sum((n > K).astype(jnp.int32)),
    )


def build_matcher(active_slots: int = 32, max_matches: int = 64):
    """Bind the static kernel knobs; returned fn takes (words, lens,
    is_sys, *table.device_arrays())."""

    def match(words, lens, is_sys, plus_child, hash_accept, accept,
              tab_state, tab_word, tab_next):
        return nfa_match(
            words, lens, is_sys, plus_child, hash_accept, accept,
            tab_state, tab_word, tab_next,
            active_slots=active_slots, max_matches=max_matches,
        )

    return match


def match_topics(
    table: NfaTable,
    names: Sequence[str],
    active_slots: int = 32,
    max_matches: int = 64,
) -> List[List[str]]:
    """Convenience end-to-end: encode → kernel → decode to filter strings.

    Raises if the active set overflowed (callers wanting fail-open handle
    MatchResult directly)."""
    words, lens, is_sys = encode_topics(table, names)
    res = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in table.device_arrays()],
        active_slots=active_slots, max_matches=max_matches,
    )
    if int(res.active_overflow) or int(res.match_overflow):
        raise OverflowError(
            f"match overflow: active={int(res.active_overflow)} "
            f"rows>{max_matches}={int(res.match_overflow)}"
        )
    matches = np.asarray(res.matches)
    counts = np.asarray(res.n_matches)
    out: List[List[str]] = []
    for r in range(len(names)):
        out.append([table.accept_filters[a] for a in matches[r, : counts[r]]])
    return out
