"""Batched NFA wildcard-match kernel — the device hot path.

Replaces the per-publish ``emqx_trie:match/1`` walk (reference hot loop #1,
SURVEY.md §3.4) with ONE unrolled NFA evaluation over a whole topic batch:

* carry: ``active`` (B, A) int32 — the NFA active-state set per topic,
  -1 padded.  Active sets are **duplicate-free by construction**: a trie
  node is reachable from the root by exactly one label path, so at step t
  each matching depth-t node appears at most once.  Compaction is a
  ``top_k`` (valids first), no dedup pass.
* per step t ∈ [0, D]:

  - ``#``-accepts fire for every active state (a ``#`` child matches the
    zero remaining levels too, which is why the walk runs D+1 steps);
  - end-accepts fire when t == topic length;
  - transitions fetch the literal edge from the bucketed cuckoo
    table (TWO wide row-gathers — the TPU-friendly access pattern; see
    compiler docstring) plus the ``+`` edge from the packed per-state
    node table (ONE wide gather), masked for t ≥ length and for the
    root-level-wildcard-vs-$-topic rule at t == 0.

The walk is fully unrolled: D is small and static, XLA fuses across
steps, and no dynamic loop means no per-iteration host round trips on
remote-attached backends.

Outputs per topic: up to K matched accept ids (valids first, -1
padded), the exact match count, plus PER-ROW overflow counters
(active-set spill beyond A, match spill beyond K): a spilled row's
answer is possibly truncated and the host re-runs exactly those rows on
the authoritative trie (fail-open, SURVEY.md §5.3 — implemented in the
serving engines, VERDICT.md weak item 1).

Everything is int32, static shapes, no data-dependent control flow — one
XLA compilation per (D, A, K, B, S, Hb) bucket.
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .compiler import BUCKET_SLOTS, NfaTable, encode_topics

__all__ = ["MatchResult", "SERVE_FLAT_MULT", "build_matcher",
           "decode_flat", "decode_row_meta", "fetch_flat_prefix",
           "fetch_flat_ragged", "match_topics", "nfa_match",
           "nfa_match_donated", "nfa_walk", "ragged_capacity"]

# serving flat-output capacity per padded batch row (ids/topic): shared
# by every serving engine so the fan-out tuning cannot drift between
# the in-process MatchService, the exhook sidecar, and bench.py.
# Round-5 10M measurement: at mult 6 / K=32 the fan-out tail spilled
# 11-12% of topics to ~60 us host re-runs; mult 8 / K=128 keeps the
# tail on device (spills 186k -> 84 per window, serving p99 353 ->
# 133 ms) for ~33% more readback bytes.
SERVE_FLAT_MULT = 8


#: ``row_meta`` packing: low 16 bits = per-row flat-buffer entry count
#: (min(n, K)); bit 16 = the row's fail-open flag (active-set OR match
#: overflow).  One (B,) vector carries everything a two-phase readback
#: needs, so phase 1 of a match-proportional d2h costs 4·B bytes, not
#: the 12·B of fetching counts + both overflow vectors separately.
ROW_META_COUNT_MASK = 0xFFFF
ROW_META_SPILL_SHIFT = 16


def decode_row_meta(meta: np.ndarray):
    """(B,) packed row_meta → (per-row flat entry counts, spilled rows
    bool) — the host half of the two-phase readback contract."""
    return (meta & ROW_META_COUNT_MASK), (meta >> ROW_META_SPILL_SHIFT) > 0


def fetch_flat_prefix(matches, total: int) -> np.ndarray:
    """Phase 2 of the two-phase readback: ship EXACTLY the first
    ``total`` ids of the flat buffer with a BOUNDED executable set.

    A naive ``matches[:total]`` compiles one XLA slice per distinct
    total — unbounded compile churn on the serve path (measured: the
    pipelined p99 collapsed under it).  Instead the prefix is fetched
    by binary decomposition into pow2-sized ``dynamic_slice`` chunks:
    the slice SIZE is static (one executable per (buffer, pow2) pair,
    ≤ log2(flat_cap) of them ever) and the offset rides as a traced
    scalar, so arbitrary totals reuse the same executables.  Bytes
    shipped = 4·total exactly; chunk count ≤ log2(total)+1 (the d2h
    path is bandwidth-bound, BASELINE.md tunnel table)."""
    import jax

    if total <= 0:
        return np.empty(0, np.int32)
    parts = []
    off = 0
    bit = 1 << (int(total).bit_length() - 1)
    rem = int(total)
    while rem:
        if rem >= bit:
            chunk = jax.lax.dynamic_slice(
                matches, (jnp.int32(off),), (bit,))
            parts.append(np.asarray(jax.device_get(chunk)))
            off += bit
            rem -= bit
        bit >>= 1
    return np.concatenate(parts) if len(parts) > 1 else parts[0]


def ragged_capacity(total: int, flat_cap: int) -> int:
    """Capacity class for a ragged single-transfer readback: the
    smallest pow2 ≥ ``total``, clipped to the flat buffer size.  The
    class set is what bounds the executable count (≤ log2(flat_cap)
    distinct slice sizes per buffer shape — the same discipline as the
    binary decomposition, reused by :func:`fetch_flat_ragged`)."""
    if total <= 0:
        return 0
    return min(1 << max(0, int(total) - 1).bit_length(), int(flat_cap))


def fetch_flat_ragged(matches, total: int) -> np.ndarray:
    """Single-transfer twin of :func:`fetch_flat_prefix`: ship the
    first ``total`` ids of the flat buffer in ONE d2h.

    The chunked decomposition keeps bytes exact (4·total) but pays one
    d2h round trip per set bit of ``total`` — on a high-latency link
    p99 tracks RTT·popcount instead of kernel time.  Here the prefix
    is fetched as ONE ``dynamic_slice`` padded up to its pow2
    **capacity class** (:func:`ragged_capacity`) and trimmed on host:
    the slice SIZE stays static (the executables are the SAME
    (buffer, pow2) pairs the chunked path compiles, so mode flips
    never grow the executable set) and the transfer count is exactly
    one.  Bytes shipped = 4·capacity ≤ 8·total — the padding is the
    price of the round trip, which is the right trade whenever RTT
    beats bandwidth (BASELINE.md tunnel table)."""
    import jax

    if total <= 0:
        return np.empty(0, np.int32)
    cap = ragged_capacity(total, int(matches.shape[0]))
    chunk = jax.lax.dynamic_slice(matches, (jnp.int32(0),), (cap,))
    return np.asarray(jax.device_get(chunk))[:int(total)]


class MatchResult(NamedTuple):
    matches: jax.Array     # (B, K) int32 accept ids, valids first, -1 pad
                           # flat mode: (flat_cap,) globally compacted ids
    n_matches: jax.Array   # (B,) int32 exact count (may exceed K)
    active_overflow: jax.Array  # (B,) int32 — per-row active-set spills
    match_overflow: jax.Array   # (B,) int32 — 1 where count > K (flat
                           # mode: also rows truncated by the global cap)
    # flat mode only: packed per-row metadata for match-proportional
    # two-phase readback (see decode_row_meta); None otherwise
    row_meta: Optional[jax.Array] = None

    def spilled_rows(self):
        """Bool (B,) — rows whose answer may be truncated (fail-open set)."""
        return (self.active_overflow > 0) | (self.match_overflow > 0)


def decode_flat(matches: np.ndarray, n_matches: np.ndarray,
                max_matches: int) -> List[np.ndarray]:
    """Split a flat-mode ``matches`` buffer into per-row id arrays.

    Rows flagged by ``spilled_rows()`` carry truncated segments — callers
    re-run those on the host (fail-open), same as compact mode.
    """
    nk = np.minimum(n_matches, max_matches)
    offs = np.cumsum(nk) - nk
    return [matches[o:o + c] for o, c in zip(offs, nk)]


def _bucket_hash(state: jax.Array, word: jax.Array, seed: jax.Array, mask: int):
    """Device twin of compiler._bucket_hash — identical uint32 mixing."""
    h = (
        state.astype(jnp.uint32) * jnp.uint32(2654435761)
        + word.astype(jnp.uint32) * jnp.uint32(2246822519)
        + seed.astype(jnp.uint32)
    )
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(3266489917)
    h = h ^ (h >> jnp.uint32(13))
    return (h & jnp.uint32(mask)).astype(jnp.int32)


def _edge_lookup(state, word, edge_tab, seeds):
    """Literal-edge lookup for (B, A) (state, word): 2 wide row-gathers.

    Each gathered row holds BUCKET_SLOTS slots of [state, word, next, 0];
    at most one slot matches (keys are unique), so a max-reduce extracts
    the hit (-1 elsewhere)."""
    Hb = edge_tab.shape[0]
    mask = Hb - 1
    B, A = state.shape
    hits = []
    for k in range(2):
        b = _bucket_hash(state, word, seeds[k], mask)      # (B, A)
        rows = edge_tab[b].reshape(B, A, BUCKET_SLOTS, 4)  # wide gather
        hit = (rows[..., 0] == state[..., None]) & (
            rows[..., 1] == word[..., None]
        )
        hits.append(jnp.max(jnp.where(hit, rows[..., 2], -1), axis=-1))
    return jnp.maximum(hits[0], hits[1])                   # (B, A)


def _compact(cand: jax.Array, width: int) -> jax.Array:
    """Valids-first compaction of (B, C) → (B, width) via cumsum +
    compare-scatter — no sort.  Any valids beyond ``width`` are dropped
    (the caller counts them as spill)."""
    valid = cand >= 0
    pos = jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    pos = jnp.where(valid, pos, width)
    onehot = pos[..., None] == jnp.arange(width)[None, None, :]
    return jnp.max(jnp.where(onehot, cand[..., None], -1), axis=1)


def flat_epilogue(flat, n, aover, max_matches: int, flat_cap: int):
    """The fused on-device compaction epilogue for flat serving mode:
    per-row top-K compaction, a GLOBAL cumsum-offset scatter into one
    ``(flat_cap,)`` buffer, and the packed ``row_meta`` vector — the
    dense (row, accept-id) list is produced entirely on device, so a
    two-phase readback ships 4·B meta bytes + 4·Σcounts id bytes
    instead of the 4·flat_cap slab.  Shared by :func:`nfa_match` and
    the pallas walk (:func:`~emqx_tpu.ops.pallas_match
    .pallas_small_match_flat`) so both backends honor one readback
    contract.  Returns ``(matches, mover, row_meta)``."""
    K = max_matches
    per_row = _compact(flat, K)                        # (B, K)
    nk = jnp.minimum(n, K)
    offs = jnp.cumsum(nk) - nk                         # (B,)
    col = jnp.arange(K, dtype=jnp.int32)[None, :]
    valid = col < nk[:, None]
    idx = jnp.where(valid, offs[:, None] + col, flat_cap)
    out = jnp.full((flat_cap,), -1, jnp.int32)
    matches = out.at[idx.reshape(-1)].set(
        per_row.reshape(-1), mode="drop")              # OOB dropped
    # truncated rows: count exceeded K, or the segment ran past the
    # global cap — both land in the fail-open set
    mover = ((n > K) | (offs + nk > flat_cap)).astype(jnp.int32)
    spilled = ((aover > 0) | (mover > 0)).astype(jnp.int32)
    row_meta = nk | (spilled << ROW_META_SPILL_SHIFT)
    return matches, mover, row_meta


def nfa_walk(
    words,        # (B, D) int32
    lens,         # (B,) int32
    is_sys,       # (B,) bool
    node_tab,     # (S, 4) int32: [plus_child, hash_accept, accept, 0]
    edge_lookup,  # (state (B,w), word (B,w)) -> next (B,w), -1 on miss
    *,
    active_slots: int = 16,
    max_matches: int = 32,
    compact_output: bool = True,
    flat_cap: int = 0,
) -> MatchResult:
    """The backend-agnostic level walk: accepts, ``+`` transitions and
    the epilogue are identical for every edge-structure backend — only
    the literal-edge lookup is pluggable (the cuckoo hash probe here,
    the sorted-relation ``searchsorted`` join step in
    :mod:`~emqx_tpu.ops.join_match`), so hint/match parity between
    backends is structural, not re-implemented."""
    B, D = words.shape
    A = active_slots
    K = max_matches

    # Per-step active width: a trie has at most 2^t nodes at depth t
    # reachable from the root under one topic (each state forks into at
    # most literal+plus children), so early steps run narrow — step 0 is
    # a single column.  This cuts gather traffic by ~40% at D=8, A=8 and
    # removes the compaction entirely until 2·width exceeds the cap
    # (measured 1.6× end-to-end vs the fixed-width round-2 kernel).
    active = jnp.zeros((B, 1), jnp.int32)                  # {root}
    accept_cols = []
    spills = []
    for t in range(D + 1):
        valid = active >= 0
        sa = jnp.maximum(active, 0)        # safe gather index
        node = node_tab[sa]                # (B, w_t, 4) wide gather
        plus_child = node[..., 0]
        hash_accept = node[..., 1]
        end_accept = node[..., 2]

        # --- fire accepts -------------------------------------------------
        hacc = jnp.where(valid, hash_accept, -1)
        if t == 0:
            # root-level wildcard suppression for $-topics (active == {root})
            hacc = jnp.where(is_sys[:, None], -1, hacc)
        at_end = (t == lens)[:, None]
        eacc = jnp.where(valid & at_end, end_accept, -1)
        accept_cols.append(jnp.concatenate([hacc, eacc], axis=1))

        if t == D:
            break

        # --- transition ---------------------------------------------------
        w = jnp.broadcast_to(words[:, t][:, None], active.shape)
        lit = edge_lookup(active, w)
        lit = jnp.where(valid, lit, -1)
        plus = jnp.where(valid, plus_child, -1)
        if t == 0:
            plus = jnp.where(is_sys[:, None], -1, plus)
        cand = jnp.concatenate([lit, plus], axis=1)        # (B, 2·w_t)
        cand = jnp.where((t < lens)[:, None], cand, -1)
        w_next = min(cand.shape[1], A)
        if cand.shape[1] <= A:
            active = cand                  # lossless: no compaction needed
        else:
            active, _ = jax.lax.top_k(cand, w_next)        # valids first
            n_cand = jnp.sum((cand >= 0).astype(jnp.int32), axis=1)
            n_kept = jnp.sum((active >= 0).astype(jnp.int32), axis=1)
            spills.append(n_cand - n_kept)                 # (B,) per row

    flat = jnp.concatenate(accept_cols, axis=1)            # (B, Σ 2·w_t)
    n = jnp.sum((flat >= 0).astype(jnp.int32), axis=1)
    aover = (
        jnp.sum(jnp.stack(spills), axis=0) if spills
        else jnp.zeros((B,), jnp.int32)
    )
    row_meta = None
    if flat_cap:
        # flat mode: the fused compaction epilogue — readback shrinks
        # from B·K·4 bytes to ~avg_fanout·4 bytes per topic, which is
        # what the serving path is bound by on remote-attached devices
        # (d2h latency/bandwidth, measured 2026-07-30: ~12.5 MB/s
        # through the tunnel vs 1.4 GB/s h2d).
        matches, mover, row_meta = flat_epilogue(
            flat, n, aover, K, flat_cap)
    elif compact_output:
        matches = _compact(flat, K)                        # valids first
        mover = (n > K).astype(jnp.int32)
    else:
        # raw mode: all Σ2·w_t accept slots, valids scattered (-1 holes).
        # Structurally nothing truncates (the walk cannot fire more
        # accepts than it has slots), so only active-set spill remains a
        # fail-open cause — the right mode for high-fan-out tables where
        # a fixed K would overflow (hosts mask row >= 0 to decode).
        matches = flat
        mover = jnp.zeros((B,), jnp.int32)
    return MatchResult(
        matches=matches,
        n_matches=n,
        active_overflow=aover,
        match_overflow=mover,
        row_meta=row_meta,
    )


def _nfa_match(
    words,        # (B, D) int32
    lens,         # (B,) int32
    is_sys,       # (B,) bool
    node_tab,     # (S, 4) int32: [plus_child, hash_accept, accept, 0]
    edge_tab,     # (Hb, BUCKET_SLOTS*4) int32 cuckoo buckets
    seeds,        # (2,) int32
    *,
    active_slots: int = 16,
    max_matches: int = 32,
    compact_output: bool = True,
    flat_cap: int = 0,
) -> MatchResult:
    return nfa_walk(
        words, lens, is_sys, node_tab,
        lambda st, w: _edge_lookup(st, w, edge_tab, seeds),
        active_slots=active_slots, max_matches=max_matches,
        compact_output=compact_output, flat_cap=flat_cap,
    )


_MATCH_STATIC = ("active_slots", "max_matches", "compact_output",
                 "flat_cap")

#: the shipping entry point — one compilation per shape bucket
nfa_match = jax.jit(_nfa_match, static_argnames=_MATCH_STATIC)

#: pipelined-serving twin: the batch operands (words, lens, is_sys) are
#: DONATED to the kernel (the ``_scatter_rows`` idiom — the dispatch
#: consumes the uploaded buffers, so a double-buffered serve chain
#: never holds two generations of encode buffers on device).  Table
#: arrays are NOT donated: they serve every in-flight batch.
nfa_match_donated = jax.jit(_nfa_match, static_argnames=_MATCH_STATIC,
                            donate_argnums=(0, 1, 2))

# a donated operand whose shape no kernel output can alias degrades to
# a plain argument; XLA warns once per compile, which is noise on the
# serve path (the donation is best-effort by design)
import warnings as _warnings  # noqa: E402 — scoped to the filter below

_warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable",
    category=UserWarning)


def build_matcher(active_slots: int = 16, max_matches: int = 32):
    """Bind the static kernel knobs; returned fn takes (words, lens,
    is_sys, *table.device_arrays())."""

    def match(words, lens, is_sys, node_tab, edge_tab, seeds):
        return nfa_match(
            words, lens, is_sys, node_tab, edge_tab, seeds,
            active_slots=active_slots, max_matches=max_matches,
        )

    return match


def match_topics(
    table: NfaTable,
    names: Sequence[str],
    active_slots: int = 16,
    max_matches: int = 32,
) -> List[List[str]]:
    """Convenience end-to-end: encode → kernel → decode to filter strings.

    Raises if the active set overflowed (callers wanting fail-open handle
    MatchResult directly)."""
    words, lens, is_sys = encode_topics(table, names)
    res = nfa_match(
        jnp.asarray(words), jnp.asarray(lens), jnp.asarray(is_sys),
        *[jnp.asarray(a) for a in table.device_arrays()],
        active_slots=active_slots, max_matches=max_matches,
    )
    if int(jnp.sum(res.active_overflow)) or int(jnp.sum(res.match_overflow)):
        raise OverflowError(
            f"match overflow: active={int(jnp.sum(res.active_overflow))} "
            f"rows>{max_matches}={int(jnp.sum(res.match_overflow))}"
        )
    matches = np.asarray(res.matches)
    counts = np.asarray(res.n_matches)
    out: List[List[str]] = []
    for r in range(len(names)):
        out.append([table.accept_filters[a] for a in matches[r, : counts[r]]])
    return out
