"""Padded-shape kernel compile cache — resizes never stall on XLA.

The match kernel (:func:`~emqx_tpu.ops.match_kernel.nfa_match`) compiles
one executable per ``(B, D, S, Hb, A, K, flat_cap, compact)`` bucket;
table shapes are padded to powers of two exactly so growth RARELY
changes them — but when growth does cross a pow2 boundary, the next
dispatch stalls 9–19 s on an XLA compile at 10M filters (BENCH_r03/r05)
and the serve plane browns out to the host path for the whole window.

This cache closes that window two ways:

* **AOT executables** — keys compile via ``jit(nfa_match).lower(...).
  compile()`` against :class:`jax.ShapeDtypeStruct` operands (no dummy
  arrays materialized, no device upload paid just to warm a shape) and
  the resulting ``Compiled`` is what serving dispatches through, so the
  compile-or-hit decision is explicit and countable (the compile-counter
  spy in tests/test_match_segments.py);
* **next-pow2 prewarm** — the serving layer watches table occupancy and
  calls :meth:`prewarm_shape` for the next ``shape_key`` *before* growth
  reaches it, for every (batch, depth, output-mode) combo observed so
  far; the resize is then served entirely from the cache.

Thread model: ``executable()`` may be called from serve worker threads
and ``prewarm_shape`` from a background thread.  A per-key in-flight set
under one lock makes concurrent compiles of the same key collapse into
one; the dict lookup on the hit path is one lock acquisition.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional, Set, Tuple

log = logging.getLogger(__name__)

__all__ = ["MatchKernelCache", "CompileMiss"]

#: (B, D, S, Hb, active_slots, max_matches, compact, flat_cap, donate,
#: backend, mesh).  ``backend`` selects the kernel family: "hash" is
#: the cuckoo-probe nfa_match, "join" the sorted-relation kernel
#: (ops/join_match.py) whose edge-structure shapes DERIVE from the same
#: (S, Hb) pair (relation capacity = Hb * BUCKET_SLOTS), so one shape
#: key covers both families; "join-pallas" is the same join relation
#: walked by the fused Pallas kernel (ops/pallas_match.py) — identical
#: operand shapes, flat-output only.  ``mesh`` is None for single-device keys;
#: the multichip serve backend (parallel/multichip_serve.py) keys its
#: shard_map executables with ``(dp, tp, acap, kind, cap, ...)`` —
#: note the routed bucket CAPACITY is part of the key, so the EP
#: capacity auto-resize pre-compiles its target grid through this
#: cache (block=True off the serve path) and the post-flip dispatch
#: hits without ever parking behind XLA — and installs a
#: ``mesh_lower`` hook the cache delegates those keys to; the same
#: prewarm/CompileMiss contract then covers the mesh step.
Key = Tuple[int, int, int, int, int, int, bool, int, bool, str,
            Optional[Tuple[int, ...]]]


class CompileMiss(RuntimeError):
    """Raised by a non-blocking executable() miss: the caller serves the
    batch from the CPU tables NOW (never a breaker strike — the device
    is healthy) while the key compiles in the background."""


class MatchKernelCache:
    """Shape-keyed AOT compile cache for the match kernel."""

    def __init__(self) -> None:
        self._compiled: Dict[Key, Any] = {}
        self._inflight: Set[Key] = set()
        self._lock = threading.Lock()
        self._done = threading.Condition(self._lock)
        # every (B, D, A, K, compact, flat_cap, donate, backend, mesh)
        # combo ever requested: what prewarm_shape replays against the
        # NEXT table shape
        self._combos: Set[Tuple[int, int, int, int, bool, int,
                                bool, str,
                                Optional[Tuple[int, ...]]]] = set()
        # mesh-key lowering hook, installed by the multichip matcher
        # that owns the mesh (the cache itself stays mesh-agnostic)
        self.mesh_lower: Any = None
        # backends prewarm_shape covers for EVERY combo regardless of
        # which backend the combo was first requested under: with
        # match.backend=auto the first requests route hash (the cold
        # default), so a combo-only replay would leave the join variant
        # uncompiled and the first auto-routed join dispatch on a fresh
        # shape would eat a CompileMiss → CPU hop (ISSUE 13 bugfix)
        self.auto_backends: Tuple[str, ...] = ()
        self.compiles = 0
        self.hits = 0
        self.misses = 0

    # ------------------------------------------------------------------

    @staticmethod
    def key(batch_shape: Tuple[int, int], s: int, hb: int, *,
            active_slots: int, max_matches: int,
            compact_output: bool, flat_cap: int,
            donate: bool = False, backend: str = "hash",
            mesh: Optional[Tuple[int, ...]] = None) -> Key:
        b, d = batch_shape
        return (b, d, s, hb, active_slots, max_matches,
                bool(compact_output), flat_cap, bool(donate), backend,
                mesh)

    def executable(self, batch_shape: Tuple[int, int], s: int, hb: int, *,
                   active_slots: int, max_matches: int,
                   compact_output: bool, flat_cap: int,
                   donate: bool = False, backend: str = "hash",
                   mesh: Optional[Tuple[int, ...]] = None,
                   block: bool = True):
        """The compiled executable for these operand shapes — cached, or
        compiled NOW (blocking; counted, so a resize that was prewarmed
        shows zero compiles on the serve path).  With ``block=False`` a
        miss kicks a background compile and raises :class:`CompileMiss`
        instead — the serving contract: a prefetch is NEVER parked
        behind XLA, the CPU trie answers while the shape warms."""
        k = self.key(batch_shape, s, hb, active_slots=active_slots,
                     max_matches=max_matches,
                     compact_output=compact_output, flat_cap=flat_cap,
                     donate=donate, backend=backend, mesh=mesh)
        with self._lock:
            self._combos.add((k[0], k[1], k[4], k[5], k[6], k[7], k[8],
                              k[9], k[10]))
            fn = self._compiled.get(k)
            if fn is not None:
                self.hits += 1
                return fn
            self.misses += 1
            if not block:
                if k not in self._inflight:
                    self._inflight.add(k)
                    # non-daemon: a daemon compile thread racing XLA
                    # teardown at interpreter exit segfaults; exit
                    # instead waits out the in-flight compile
                    threading.Thread(
                        target=self._compile_bg, args=(k,),
                        name="match-kernel-compile",
                    ).start()
                raise CompileMiss(str(k))
        return self._compile(k)

    def _compile_bg(self, k: Key) -> None:
        """Background half of a non-blocking miss: the key was already
        marked in-flight by the caller under the lock."""
        try:
            fn = self._lower(k)
            with self._lock:
                self._compiled[k] = fn
                self.compiles += 1
        except Exception:  # pragma: no cover - XLA failure surfaces on
            log.exception("background kernel compile failed for %s", k)
        finally:
            with self._lock:
                self._inflight.discard(k)
                self._done.notify_all()

    def warmed(self, batch_shape: Tuple[int, int], s: int, hb: int, *,
               active_slots: int, max_matches: int,
               compact_output: bool, flat_cap: int,
               donate: bool = False, backend: str = "hash",
               mesh: Optional[Tuple[int, ...]] = None) -> bool:
        k = self.key(batch_shape, s, hb, active_slots=active_slots,
                     max_matches=max_matches,
                     compact_output=compact_output, flat_cap=flat_cap,
                     donate=donate, backend=backend, mesh=mesh)
        with self._lock:
            return k in self._compiled

    def _expanded_combos(self) -> list:
        """Observed combos crossed with ``auto_backends``: under
        per-shape routing every covered shape must hold BOTH kernel
        families, or the autotuner's first re-route eats a miss.
        Mesh combos stay on their own backend — the shard_map step has
        no join twin."""
        with self._lock:
            combos = list(self._combos)
            extra = tuple(self.auto_backends)
        out = []
        seen = set()
        for combo in combos:
            backends = (combo[7],) if combo[8] is not None \
                else (combo[7],) + extra
            for be in backends:
                c = combo[:7] + (be,) + combo[8:]
                if c not in seen:
                    seen.add(c)
                    out.append(c)
        return out

    def shape_covered(self, s: int, hb: int) -> bool:
        """Every observed batch combo (crossed with the auto-routing
        backends) already compiled for (s, hb)?"""
        combos = self._expanded_combos()
        with self._lock:
            return bool(combos) and all(
                (b, d, s, hb, a, m, c, f, dn, be, mesh) in self._compiled
                for (b, d, a, m, c, f, dn, be, mesh) in combos
            )

    def prewarm_shape(self, s: int, hb: int) -> int:
        """Compile every observed batch combo against table shape
        ``(s, hb)`` — the background step that makes the NEXT pow2
        resize free — for every backend ``auto`` may route to.
        Returns the number of fresh compiles."""
        n = 0
        for (b, d, a, m, c, f, dn, be, mesh) in self._expanded_combos():
            k = (b, d, s, hb, a, m, c, f, dn, be, mesh)
            with self._lock:
                if k in self._compiled:
                    continue
            self._compile(k)
            n += 1
        return n

    # ------------------------------------------------------------------

    def _compile(self, k: Key):
        with self._lock:
            while k in self._inflight:
                self._done.wait()
            fn = self._compiled.get(k)
            if fn is not None:
                return fn
            self._inflight.add(k)
        try:
            fn = self._lower(k)
            with self._lock:
                self._compiled[k] = fn
                self.compiles += 1
                return fn
        finally:
            with self._lock:
                self._inflight.discard(k)
                self._done.notify_all()

    def _lower(self, k: Key):
        import jax
        import jax.numpy as jnp

        from .compiler import BUCKET_SLOTS
        from .match_kernel import nfa_match, nfa_match_donated

        b, d, s, hb, a, m, compact, flat_cap, donate, backend, mesh = k
        if mesh is not None:
            if self.mesh_lower is None:
                raise RuntimeError(
                    "mesh-keyed compile requested but no mesh_lower "
                    "hook is installed")
            return self.mesh_lower(k)
        i32 = jnp.int32
        sd = jax.ShapeDtypeStruct
        batch = (
            sd((b, d), i32),                      # words
            sd((b,), i32),                        # lens
            sd((b,), jnp.bool_),                  # is_sys
            sd((s, 4), i32),                      # node_tab
        )
        if backend == "join":
            from .join_match import (
                OVERLAY_CAP, join_match, join_match_donated,
                relation_capacity,
            )

            e_cap = relation_capacity(hb)
            fn = join_match_donated if donate else join_match
            lowered = fn.lower(
                *batch,
                sd((s + 1,), i32),                # state_start
                sd((e_cap,), i32),                # edge_word
                sd((e_cap,), i32),                # edge_next
                sd((OVERLAY_CAP, 3), i32),        # overlay
                active_slots=a, max_matches=m,
                compact_output=compact, flat_cap=flat_cap,
            )
            return lowered.compile()
        if backend == "join-pallas":
            from .join_match import OVERLAY_CAP, relation_capacity
            from .pallas_match import (
                pallas_join_match_flat, pallas_join_match_flat_donated,
            )

            if flat_cap <= 0:
                raise ValueError(
                    "join-pallas backend is flat-output only "
                    "(flat_cap > 0 required)")
            e_cap = relation_capacity(hb)
            fn = (pallas_join_match_flat_donated if donate
                  else pallas_join_match_flat)
            lowered = fn.lower(
                *batch,
                sd((s + 1,), i32),                # state_start
                sd((e_cap,), i32),                # edge_word
                sd((e_cap,), i32),                # edge_next
                sd((OVERLAY_CAP, 3), i32),        # overlay
                depth=d, active_slots=a, max_matches=m,
                flat_cap=flat_cap,
                interpret=(jax.default_backend() != "tpu"),
            )
            return lowered.compile()
        fn = nfa_match_donated if donate else nfa_match
        lowered = fn.lower(
            *batch,
            sd((hb, BUCKET_SLOTS * 4), i32),      # edge_tab
            sd((2,), i32),                        # seeds
            active_slots=a, max_matches=m,
            compact_output=compact, flat_cap=flat_cap,
        )
        return lowered.compile()

    def info(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._compiled),
                "combos": len(self._combos),
                "compiles": self.compiles,
                "hits": self.hits,
                "misses": self.misses,
            }
