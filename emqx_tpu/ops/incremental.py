"""Incremental NFA table: O(delta) filter add/remove, no recompiles.

Behavioral reference: ``emqx_trie:insert/1`` / ``delete/1`` [U]
(SURVEY.md §2.1) are O(filter); the round-1 ``compile_filters`` was
O(table) per change — this module closes that gap (VERDICT.md next-round
item 1).  The design follows the mria bootstrap-then-replay-rlog pattern
(SURVEY.md §5.4): the host arrays here are the authoritative mirror, the
device twin (:class:`~emqx_tpu.ops.device_table.DeviceNfa`) consumes
bounded deltas.

Layout is byte-identical to :class:`~emqx_tpu.ops.compiler.NfaTable`
(same node_tab / cuckoo edge_tab / seeds contract, same kernel), plus:

* **state free-list** — deleted trie nodes return their row; growth
  doubles S (amortized O(1), one XLA recompile per doubling);
* **in-place cuckoo mutation** — inserts random-walk kick within the
  live numpy table, deletes clear the slot; every touched bucket row is
  recorded in a dirty set;
* **accept-id free-list** — ``accept_filters`` may contain ``None``
  holes; holes are unreachable (no state references a freed id);
* **dirty tracking** — ``flush()`` drains the dirty state rows / bucket
  rows as index+row arrays sized O(delta), which the device twin
  scatter-applies without reshipping the table.

The vocab is append-only between compactions: a word whose last edge
vanished keeps its id (harmless — no edge row references it), bounded
by ``compact()`` which rebuilds dense arrays from the live filter set.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from .. import topic as T
from .compiler import BUCKET_SLOTS, NfaTable, _bucket, _bucket_hash

__all__ = ["IncrementalNfa", "NfaDelta"]

_MAX_KICKS = 500
_U32 = 0xFFFFFFFF


def _hash_py(state: int, word: int, seed: int, mask: int) -> int:
    """Pure-Python twin of ``compiler._bucket_hash`` — same uint32 mixing,
    ~10× faster than numpy scalar math on the per-edge mutation path
    (property-tested equal in tests/test_incremental.py)."""
    h = (state * 2654435761 + word * 2246822519 + seed) & _U32
    h ^= h >> 16
    h = (h * 3266489917) & _U32
    h ^= h >> 13
    return h & mask


class NfaDelta(NamedTuple):
    """One drained batch of table mutations (host → device scatter)."""

    epoch: int
    resized: bool              # shapes changed ⇒ full re-upload needed
    state_idx: np.ndarray      # (n,) int32 dirty node_tab rows
    state_rows: np.ndarray     # (n, 4) int32 current contents
    bucket_idx: np.ndarray     # (m,) int32 dirty edge_tab rows
    bucket_rows: np.ndarray    # (m, 16) int32 current contents
    # dirty-region resize tracking (``track_regions`` mode, opt-in): when
    # a resized delta STILL carries valid dirty rows, the consumer can
    # grow the device buffers in place (pad + scatter) instead of
    # re-shipping the whole table.  node_grown_from = the S the node_tab
    # had before the first growth since the last flush (-1 = unchanged);
    # edges_rehashed = the edge table was rebuilt with fresh seeds (its
    # contents must ship fully; the default True means "unknown", which
    # legacy producers resolve to the full re-upload path).
    node_grown_from: int = -1
    edges_rehashed: bool = True

    @property
    def empty(self) -> bool:
        return (
            not self.resized
            and len(self.state_idx) == 0
            and len(self.bucket_idx) == 0
        )


class _INode:
    __slots__ = ("sid", "lit", "plus", "parent", "pword", "hash_aid", "aid")

    def __init__(self, sid: int, parent: Optional["_INode"], pword: Optional[str]):
        self.sid = sid
        self.lit: Dict[str, "_INode"] = {}
        self.plus: Optional["_INode"] = None
        self.parent = parent
        self.pword = pword          # literal word of the parent edge; None ⇒ '+' edge
        self.hash_aid = -1
        self.aid = -1

    def prunable(self) -> bool:
        return (
            not self.lit and self.plus is None
            and self.hash_aid < 0 and self.aid < 0
        )


class IncrementalNfa:
    """Mutable flattened NFA with O(filter) add/remove and delta drain."""

    def __init__(
        self,
        depth: int = 8,
        state_bucket: int = 1024,
        edge_bucket: int = 64,
        seed: int = 0xE709,
    ) -> None:
        self.depth = depth
        self._rng = np.random.default_rng(seed)
        self.node_tab = np.full((state_bucket, 4), -1, np.int32)
        self.node_tab[:, 3] = 0
        Hb = _bucket(edge_bucket, 8)
        self.edge_tab = np.full((Hb, BUCKET_SLOTS * 4), -1, np.int32)
        self.seeds = self._rng.integers(1, 2**31 - 1, size=2, dtype=np.int32)
        self._seed_ints = (int(self.seeds[0]), int(self.seeds[1]))
        self.vocab: Dict[str, int] = {}
        self.accept_filters: List[Optional[str]] = []
        self.root = _INode(0, None, None)
        self.epoch = 0
        self.n_states = 1
        self.n_edges = 0
        self.n_filters = 0
        self._free_sids: List[int] = list(range(state_bucket - 1, 0, -1))
        # freed accept ids carry the epoch they were freed at: with a
        # device consumer attached, an id is reusable only once the
        # device has applied that epoch — otherwise a stale device row
        # could fire the old aid and be translated through the NEW
        # accept_filters entry (wrong filter string, never correct at
        # any epoch)
        self._free_aids: "deque[Tuple[int, int]]" = deque()  # (epoch, aid)
        self.device_epoch: Optional[int] = None  # None ⇒ no device consumer
        self.aid_reuses = 0   # times a freed aid was handed out again
        self._alias_aids: set = set()
        self._dirty_states = {0}
        self._dirty_buckets: set = set()
        self._resized = False
        # dirty-region mode (streaming table lifecycle, opt-in): growth
        # keeps the dirty sets valid across the resize so the device
        # twin can pad-and-scatter instead of re-shipping the table.
        # Off by default — flush() behavior is byte-identical when off.
        self.track_regions = False
        self._node_grown_from = -1   # S before the first growth, -1 = none
        self._edges_rehashed = False
        self._node_wholesale = False  # compact(): every node row replaced
        # lazy trie hydration (segment restore): a callable that links
        # the _INode tree from the persisted flat relation; None on
        # normally-built tables.  Mutation/walk entry points call
        # _hydrate() first, so a segment cold start pays only the array
        # load and the relink happens in the background (or on first
        # touch, whichever comes first — the callable is idempotent).
        self._pending_trie = None

    # -- shapes ------------------------------------------------------------

    @property
    def S(self) -> int:
        return int(self.node_tab.shape[0])

    @property
    def Hb(self) -> int:
        return int(self.edge_tab.shape[0])

    def shape_key(self) -> Tuple[int, int, int]:
        return (self.S, self.Hb, self.depth)

    # -- allocation --------------------------------------------------------

    def _alloc_sid(self) -> int:
        if not self._free_sids:
            S = self.S
            grown = np.full((S * 2, 4), -1, np.int32)
            grown[:, 3] = 0
            grown[:S] = self.node_tab
            self.node_tab = grown
            self._free_sids = list(range(S * 2 - 1, S - 1, -1))
            self._resized = True
            if self.track_regions and self._node_grown_from < 0:
                # existing rows were copied verbatim: the dirty set stays
                # valid, the consumer only needs to pad [S, 2S) rows
                self._node_grown_from = S
        return self._free_sids.pop()

    def _alloc_aid(self, flt: str) -> int:
        if self._free_aids:
            freed_epoch, aid = self._free_aids[0]
            if self.device_epoch is None or freed_epoch <= self.device_epoch:
                self._free_aids.popleft()
                self.accept_filters[aid] = flt
                # monotone reuse counter: decoders that translated device
                # rows through accept_filters while a match was in flight
                # check it moved and discard the batch (the in-flight rows
                # may name this aid under its OLD filter)
                self.aid_reuses += 1
                return aid
        self.accept_filters.append(flt)
        return len(self.accept_filters) - 1

    def _free_aid(self, aid: int) -> None:
        self.accept_filters[aid] = None
        self._free_aids.append((self.epoch + 1, aid))

    def _intern(self, w: str) -> int:
        wid = self.vocab.get(w)
        if wid is None:
            wid = self.vocab[w] = len(self.vocab) + 1  # 0 = UNKNOWN
        return wid

    # -- cuckoo edge mutation ---------------------------------------------

    def _buckets_of(self, s: int, w: int) -> List[int]:
        mask = self.Hb - 1
        s0, s1 = self._seed_ints
        return [_hash_py(s, w, s0, mask), _hash_py(s, w, s1, mask)]

    def _edge_insert(self, s: int, wid: int, nxt: int) -> None:
        # grow BEFORE the load factor makes kick chains long: cuckoo
        # insert cost explodes past ~0.8 load, and delta latency (the
        # <50ms bound) matters more than the last 15% of fill
        if self.n_edges >= (self.Hb * BUCKET_SLOTS * 3) // 4:
            self._grow_edges()
        # hot path: scan bucket rows as Python lists — numpy scalar
        # indexing costs ~100ns/element, .tolist() amortizes it away
        tab = self.edge_tab
        cur = (s, wid, nxt)
        for _ in range(_MAX_KICKS):
            b_opts = self._buckets_of(cur[0], cur[1])
            for b in b_opts:
                row = tab[b].tolist()
                for i in range(0, 4 * BUCKET_SLOTS, 4):
                    if row[i] < 0:
                        tab[b, i:i + 3] = cur
                        self._dirty_buckets.add(b)
                        self.n_edges += 1
                        return
            # all 2×4 slots full: evict a random victim and carry it
            b = b_opts[int(self._rng.integers(2))]
            i = 4 * int(self._rng.integers(BUCKET_SLOTS))
            victim = tuple(tab[b, i:i + 3].tolist())
            tab[b, i:i + 3] = cur
            self._dirty_buckets.add(b)
            cur = victim
        self._grow_edges(pending=cur)
        self.n_edges += 1

    def _edge_delete(self, s: int, wid: int) -> None:
        tab = self.edge_tab
        for b in self._buckets_of(s, wid):
            row = tab[b].tolist()
            for i in range(0, 4 * BUCKET_SLOTS, 4):
                if row[i] == s and row[i + 1] == wid:
                    tab[b, i:i + 3] = (-1, -1, -1)
                    self._dirty_buckets.add(b)
                    self.n_edges -= 1
                    return
        raise AssertionError(f"edge ({s},{wid}) not in cuckoo table")

    def _live_edges(self) -> List[Tuple[int, int, int]]:
        tab = self.edge_tab.reshape(-1, 4)
        live = tab[tab[:, 0] >= 0]
        return [(int(a), int(b), int(c)) for a, b, c, _ in live]

    def _grow_edges(self, pending: Optional[Tuple[int, int, int]] = None) -> None:
        """Double Hb and re-place every edge (amortized; rare)."""
        edges = self._live_edges()
        if pending is not None:
            edges.append(pending)
        Hb = self.Hb
        while True:
            Hb <<= 1
            mask = Hb - 1
            for _attempt in range(4):
                seeds = self._rng.integers(1, 2**31 - 1, size=2, dtype=np.int32)
                slots = np.full((Hb, BUCKET_SLOTS, 4), -1, np.int32)
                if self._place_all(edges, slots, seeds, mask):
                    self.edge_tab = slots.reshape(Hb, BUCKET_SLOTS * 4)
                    self.seeds = seeds
                    self._seed_ints = (int(seeds[0]), int(seeds[1]))
                    self._resized = True
                    self._dirty_buckets.clear()
                    if self.track_regions:
                        # every edge moved: bucket dirt restarts against
                        # the NEW table (the consumer ships it fully);
                        # node rows are untouched by an edge rehash
                        self._edges_rehashed = True
                    return

    def _place_all(self, edges, slots, seeds, mask) -> bool:
        s0, s1 = int(seeds[0]), int(seeds[1])
        for edge in edges:
            cur = edge
            placed = False
            for _ in range(_MAX_KICKS):
                b_opts = [
                    _hash_py(cur[0], cur[1], s0, mask),
                    _hash_py(cur[0], cur[1], s1, mask),
                ]
                for b in b_opts:
                    for i in range(BUCKET_SLOTS):
                        if slots[b, i, 0] < 0:
                            slots[b, i] = (*cur, 0)
                            placed = True
                            break
                    if placed:
                        break
                if placed:
                    break
                b = b_opts[int(self._rng.integers(2))]
                i = int(self._rng.integers(BUCKET_SLOTS))
                victim = tuple(int(x) for x in slots[b, i, :3])
                slots[b, i] = (*cur, 0)
                cur = victim
            if not placed:
                return False
        return True

    # -- filter mutation ---------------------------------------------------

    def _hydrate(self) -> None:
        pending = self._pending_trie
        if pending is not None:
            pending()

    def add(self, flt: str) -> bool:
        """Insert ``flt``; returns False if it was already present.
        Raises ValueError when the filter is deeper than the table."""
        self._hydrate()
        ws = T.words(flt)
        if len(ws) > self.depth:
            raise ValueError(
                f"filter {flt!r} has {len(ws)} levels > table depth {self.depth}"
            )
        node = self.root
        for i, w in enumerate(ws):
            if w == "#":
                assert i == len(ws) - 1, "validated upstream"
                if node.hash_aid >= 0:
                    return False
                node.hash_aid = self._alloc_aid(flt)
                self.node_tab[node.sid, 1] = node.hash_aid
                self._dirty_states.add(node.sid)
                self.n_filters += 1
                self.epoch += 1
                return True
            if w == "+":
                if node.plus is None:
                    child = _INode(self._alloc_sid(), node, None)
                    node.plus = child
                    self.node_tab[child.sid] = (-1, -1, -1, 0)
                    self.node_tab[node.sid, 0] = child.sid
                    self._dirty_states.add(node.sid)
                    self._dirty_states.add(child.sid)
                    self.n_states += 1
                node = node.plus
            else:
                child = node.lit.get(w)
                if child is None:
                    child = _INode(self._alloc_sid(), node, w)
                    node.lit[w] = child
                    self.node_tab[child.sid] = (-1, -1, -1, 0)
                    self._dirty_states.add(child.sid)
                    self._edge_insert(node.sid, self._intern(w), child.sid)
                    self.n_states += 1
                node = child
        if node.aid >= 0:
            return False
        node.aid = self._alloc_aid(flt)
        self.node_tab[node.sid, 2] = node.aid
        self._dirty_states.add(node.sid)
        self.n_filters += 1
        self.epoch += 1
        return True

    def remove(self, flt: str) -> bool:
        """Delete ``flt``; returns False if absent.  Prunes now-empty
        trie branches, returning their states/edges to the free lists."""
        self._hydrate()
        ws = T.words(flt)
        if len(ws) > self.depth:
            return False
        node = self.root
        ends_hash = bool(ws) and ws[-1] == "#"
        walk = ws[:-1] if ends_hash else ws
        for w in walk:
            node = node.plus if w == "+" else node.lit.get(w)
            if node is None:
                return False
        if ends_hash:
            if node.hash_aid < 0:
                return False
            self._free_aid(node.hash_aid)
            node.hash_aid = -1
            self.node_tab[node.sid, 1] = -1
        else:
            if node.aid < 0:
                return False
            self._free_aid(node.aid)
            node.aid = -1
            self.node_tab[node.sid, 2] = -1
        self._dirty_states.add(node.sid)
        self._prune(node)
        self.n_filters -= 1
        self.epoch += 1
        return True

    def _prune(self, node: _INode) -> None:
        while node.parent is not None and node.prunable():
            parent = node.parent
            if node.pword is None:
                parent.plus = None
                self.node_tab[parent.sid, 0] = -1
            else:
                del parent.lit[node.pword]
                self._edge_delete(parent.sid, self.vocab[node.pword])
            self.node_tab[node.sid] = (-1, -1, -1, 0)
            self._dirty_states.add(node.sid)
            self._dirty_states.add(parent.sid)
            self._free_sids.append(node.sid)
            self.n_states -= 1
            node = parent

    # -- delta drain / snapshot -------------------------------------------

    def flush(self) -> NfaDelta:
        """Drain dirty rows.  After a resize the row sets are meaningless
        (the whole table moved) — the consumer must re-upload.  In
        ``track_regions`` mode growth keeps the dirty sets valid (node
        rows are copied verbatim on state growth; an edge rehash clears
        only the bucket dirt) and the delta carries the region facts, so
        the consumer can grow the device buffers in place."""
        resized = self._resized
        track = self.track_regions
        if resized and not track:
            sidx = np.zeros(0, np.int32)
            bidx = np.zeros(0, np.int32)
        else:
            sidx = np.fromiter(self._dirty_states, np.int32,
                               len(self._dirty_states))
            bidx = np.fromiter(self._dirty_buckets, np.int32,
                               len(self._dirty_buckets))
        delta = NfaDelta(
            epoch=self.epoch,
            resized=resized,
            state_idx=sidx,
            state_rows=self.node_tab[sidx].copy(),
            bucket_idx=bidx,
            bucket_rows=self.edge_tab[bidx].copy(),
            # node_grown_from doubles as the device-valid node PREFIX:
            # old-S on growth, full-S when the node table didn't move,
            # -1 when every row was replaced (compact) — full upload
            node_grown_from=(
                -1 if (not track or self._node_wholesale)
                else (self._node_grown_from
                      if self._node_grown_from >= 0 else self.S)),
            edges_rehashed=(
                (self._edges_rehashed or self._node_wholesale)
                if track else True),
        )
        self._dirty_states = set()
        self._dirty_buckets = set()
        self._resized = False
        self._node_grown_from = -1
        self._edges_rehashed = False
        self._node_wholesale = False
        return delta

    def snapshot(self) -> NfaTable:
        """Immutable copy in the ``compile_filters`` output format (host
        parity tests, checkpointing).  Holes in ``accept_filters`` are
        unreachable, so downstream indexing by matched aid stays safe."""
        return NfaTable(
            node_tab=self.node_tab.copy(),
            edge_tab=self.edge_tab.copy(),
            seeds=self.seeds.copy(),
            n_states=self.n_states,
            depth=self.depth,
            vocab=dict(self.vocab),
            accept_filters=list(self.accept_filters),  # type: ignore[arg-type]
            epoch=self.epoch,
        )

    def filters(self) -> List[str]:
        """Live NFA filters (aliases excluded)."""
        return [
            f for aid, f in enumerate(self.accept_filters)
            if f is not None and aid not in self._alias_aids
        ]

    def aliases(self) -> Dict[str, int]:
        return {
            self.accept_filters[aid]: aid for aid in self._alias_aids
        }

    def match_host(self, topic: str) -> List[int]:
        """Authoritative host-side match of a concrete topic against the
        live trie: the fail-open answer for rows the device spilled.
        Same semantics as the oracle (``emqx_topic:match`` rules): ``+``
        one level, ``#`` zero-or-more trailing levels, root wildcards
        suppressed for ``$``-topics.  Returns accept ids."""
        self._hydrate()
        ws = T.words(topic)
        is_sys = topic.startswith("$")
        out: List[int] = []
        frontier = [self.root]
        for t, w in enumerate(ws):
            nxt: List[_INode] = []
            for node in frontier:
                if node.hash_aid >= 0 and not (t == 0 and is_sys):
                    out.append(node.hash_aid)
                child = node.lit.get(w)
                if child is not None:
                    nxt.append(child)
                if node.plus is not None and not (t == 0 and is_sys):
                    nxt.append(node.plus)
            frontier = nxt
            if not frontier:
                return out
        for node in frontier:
            if node.hash_aid >= 0:   # '#' matches zero remaining levels
                out.append(node.hash_aid)
            if node.aid >= 0:
                out.append(node.aid)
        return out

    def aid_of(self, flt: str) -> int:
        """Accept id of a present filter, -1 if absent.  O(depth) walk —
        used by the fail-open path to map host-trie matches into the
        device id space."""
        self._hydrate()
        ws = T.words(flt)
        if len(ws) > self.depth:
            return -1
        node = self.root
        ends_hash = bool(ws) and ws[-1] == "#"
        for w in ws[:-1] if ends_hash else ws:
            node = node.plus if w == "+" else node.lit.get(w)
            if node is None:
                return -1
        return node.hash_aid if ends_hash else node.aid

    # -- alias ids ---------------------------------------------------------
    #
    # Filters the device table can't hold (deeper than `depth`) still
    # need ids in the same accept space so one id→filter table serves
    # both paths.  Aliases consume accept ids but no states.

    def alloc_alias(self, flt: str) -> int:
        aid = self._alloc_aid(flt)
        self._alias_aids.add(aid)
        self.epoch += 1
        return aid

    def free_alias(self, aid: int) -> None:
        self._alias_aids.discard(aid)
        self._free_aid(aid)
        self.epoch += 1

    def compact(self) -> None:
        """Rebuild dense arrays from the live filter set (drops vocab
        garbage and accept holes, shrinks over-grown shapes).  O(table);
        run it in the background the way the reference recompacts mnesia
        tables — correctness never requires it.  Alias ids are
        REASSIGNED: callers holding alias maps must rebuild them from
        :meth:`aliases` afterwards.

        Epoch monotonicity and the device ack position survive the
        rebuild (ADVICE.md round-2 low item): the new table presents as
        one more epoch, flagged resized, so an attached consumer's next
        ``drain()`` is a full re-upload — consumers must drain+apply
        before serving resumes (an attached DeviceNfa's rows translated
        through the new ``accept_filters`` are wrong until then)."""
        live = self.filters()
        alias_filters = sorted(self.aliases())
        old_epoch = self.epoch
        old_device_epoch = self.device_epoch
        fresh = IncrementalNfa(
            depth=self.depth,
            state_bucket=_bucket(max(2 * len(live), 8), 1024),
            seed=int(self._rng.integers(1, 2**31 - 1)),
        )
        for f in live:
            fresh.add(f)
        for f in alias_filters:
            fresh.alloc_alias(f)
        old_reuses = self.aid_reuses
        track = self.track_regions
        self.__dict__.update(fresh.__dict__)
        self.epoch = old_epoch + 1
        self.device_epoch = old_device_epoch
        # every aid was reassigned: force in-flight decoders to discard
        self.aid_reuses = old_reuses + 1
        self._resized = True
        # region tracking survives the rebuild, but the rebuild itself is
        # wholesale: no device row survives, so the next drain must ship
        # full tables even in track_regions mode
        self.track_regions = track
        self._node_wholesale = True
