"""Device data plane: NFA compiler + batched match kernels."""

from .compiler import BUCKET_SLOTS, NfaTable, compile_filters, encode_topics
from .device_table import DeviceNfa
from .encode import TopicEncoder, encode_batch
from .incremental import IncrementalNfa, NfaDelta
from .join_match import BackendAutotuner, JoinRelation, join_match
from .match_kernel import MatchResult, build_matcher, match_topics, nfa_match

__all__ = [
    "BackendAutotuner",
    "JoinRelation",
    "join_match",
    "BUCKET_SLOTS",
    "NfaTable",
    "compile_filters",
    "encode_topics",
    "DeviceNfa",
    "TopicEncoder",
    "encode_batch",
    "IncrementalNfa",
    "NfaDelta",
    "MatchResult",
    "build_matcher",
    "match_topics",
    "nfa_match",
]
