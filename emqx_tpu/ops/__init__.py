"""Device data plane: NFA compiler + batched match kernels."""

from .compiler import BUCKET_SLOTS, NfaTable, compile_filters, encode_topics
from .match_kernel import MatchResult, build_matcher, match_topics, nfa_match

__all__ = [
    "BUCKET_SLOTS",
    "NfaTable",
    "compile_filters",
    "encode_topics",
    "MatchResult",
    "build_matcher",
    "match_topics",
    "nfa_match",
]
