"""Server-side forced subscriptions on connect.

Behavioral reference: ``apps/emqx_auto_subscribe`` [U] (SURVEY.md §2.3):
a configured list of topic filters (with ``%c`` clientid / ``%u``
username placeholders) every connecting client is subscribed to, with
fixed SubOpts per entry.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..broker.broker import Broker
from ..broker.session import SubOpts

__all__ = ["AutoSubscribe", "AutoSubEntry"]


@dataclass
class AutoSubEntry:
    topic: str                      # may contain %c / %u placeholders
    opts: SubOpts = field(default_factory=SubOpts)


class AutoSubscribe:
    def __init__(self, entries: Optional[List[AutoSubEntry]] = None) -> None:
        self.entries = list(entries or [])

    def add(self, topic: str, opts: SubOpts = SubOpts()) -> None:
        self.entries.append(AutoSubEntry(topic, opts))

    def topics_for(self, clientid: str, username: Optional[str]) -> List[AutoSubEntry]:
        out = []
        for e in self.entries:
            t = e.topic.replace("%c", clientid).replace("%u", username or "")
            out.append(AutoSubEntry(t, e.opts))
        return out

    def attach(self, broker: Broker) -> "AutoSubscribe":
        def on_connected(clientid, conninfo):
            username = conninfo.get("username") if isinstance(conninfo, dict) else None
            for e in self.topics_for(clientid, username):
                try:
                    broker.subscribe(clientid, e.topic, e.opts)
                except (KeyError, ValueError):
                    pass  # no session yet / bad template — skip like the ref

        broker.hooks.add("client.connected", on_connected,
                         name="auto_subscribe")
        return self
