"""Topic rewrite rules (pub/sub), the ``emqx_modules`` rewrite analog.

Behavioral reference: the topic-rewrite module of ``apps/emqx_modules``
[U] (SURVEY.md §2.3): ordered rules ``{action pub|sub|all, source filter,
regex, dest template}``.  A topic matching the source filter AND the
regex is rewritten to the dest template with ``$N`` capture groups (and
``%c``/``%u`` client placeholders); the LAST matching rule wins, exactly
like the reference's fold over the rule list.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional

from .. import topic as T
from ..broker.broker import Broker
from ..broker.message import Message

__all__ = ["RewriteRule", "TopicRewrite"]


@dataclass
class RewriteRule:
    action: str          # 'pub' | 'sub' | 'all'
    source: str          # topic filter selecting rewritable topics
    re_pattern: str      # regex the topic must match
    dest: str            # template with $1..$9, %c, %u

    def __post_init__(self) -> None:
        if self.action not in ("pub", "sub", "all"):
            raise ValueError(f"bad action {self.action!r}")
        T.validate(self.source, "filter")
        self._re = re.compile(self.re_pattern)

    def apply(
        self, topic: str, clientid: Optional[str], username: Optional[str]
    ) -> Optional[str]:
        if not T.match(topic, self.source):
            return None
        m = self._re.match(topic)
        if m is None:
            return None
        out = self.dest
        for i, g in enumerate(m.groups() or (), start=1):
            out = out.replace(f"${i}", g or "")
        out = out.replace("%c", clientid or "").replace("%u", username or "")
        return out


class TopicRewrite:
    def __init__(self, rules: Optional[List[RewriteRule]] = None) -> None:
        self.rules: List[RewriteRule] = list(rules or [])

    def add_rule(self, rule: RewriteRule) -> None:
        self.rules.append(rule)

    def rewrite(
        self, topic: str, kind: str,
        clientid: Optional[str] = None, username: Optional[str] = None,
    ) -> str:
        """kind 'pub' or 'sub'; last matching rule wins (reference fold)."""
        out = topic
        for rule in self.rules:
            if rule.action != "all" and rule.action != kind:
                continue
            new = rule.apply(topic, clientid, username)
            if new is not None:
                out = new
        return out

    # ------------------------------------------------------------------

    def attach(self, broker: Broker) -> "TopicRewrite":
        def on_publish(acc: Message):
            if acc is None or acc.topic.startswith("$SYS"):
                return acc
            new = self.rewrite(
                acc.topic, "pub", acc.sender,
                broker.usernames.get(acc.sender) if acc.sender else None,
            )
            return acc if new == acc.topic else acc.clone(topic=new)

        def on_subscribe(clientid, pkt):
            # mutate the SUBSCRIBE packet's filters in place (channel
            # passes its live packet through the hook chain)
            u = broker.usernames.get(clientid)
            pkt.topic_filters = [
                (self.rewrite(f, "sub", clientid, u), o)
                for f, o in pkt.topic_filters
            ]

        def on_unsubscribe(clientid, pkt):
            u = broker.usernames.get(clientid)
            pkt.topic_filters = [
                self.rewrite(f, "sub", clientid, u)
                for f in pkt.topic_filters
            ]

        broker.hooks.add("message.publish", on_publish, priority=50,
                         name="rewrite.pub")
        broker.hooks.add("client.subscribe", on_subscribe, priority=50,
                         name="rewrite.sub")
        broker.hooks.add("client.unsubscribe", on_unsubscribe, priority=50,
                         name="rewrite.unsub")
        return self
