"""Retained-message store with device-assisted wildcard replay.

Behavioral reference: ``apps/emqx_retainer`` (``emqx_retainer.erl``,
``emqx_retainer_mnesia.erl`` — wildcard scan via topic index) [U]
(SURVEY.md §2.3).  Semantics kept:

* a PUBLISH with retain=1 stores the message under its topic; an empty
  retained payload deletes the entry (MQTT §3.3.1.3);
* on subscribe, retained messages matching the new filter are replayed
  with the retain flag set, honoring MQTT5 Retain-Handling (rh=0 always,
  rh=1 only if the subscription is new, rh=2 never);
* per-message expiry (``Message-Expiry-Interval`` or the configured
  default) and store-size/payload-size limits.

**Lookup is the transposed match problem** — one *filter* against many
stored *topic names*.  Host path: a literal word-trie over stored topics
walked with the filter (``+`` fans out one level, ``#`` takes the whole
subtree).  Device path (:meth:`replay_batch`): the BASELINE config #5
shape — N new wildcard filters × M retained topics — reuses the SAME
flattened-NFA kernel by compiling the filters and batching the stored
topic names as query topics; the resulting per-topic accept sets are
inverted into per-filter topic lists.  One kernel call replaces N×M host
walks; no bespoke "retained kernel" needed.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .. import topic as T
from ..broker.broker import Broker
from ..broker.message import Message

__all__ = ["Retainer"]


class _TopicNode:
    __slots__ = ("children", "topic")

    def __init__(self) -> None:
        self.children: Dict[str, "_TopicNode"] = {}
        self.topic: Optional[str] = None  # set ⇒ a retained topic ends here


class Retainer:
    def __init__(
        self,
        msg_expiry_interval: float = 0.0,   # 0 = no default expiry
        max_payload_size: int = 1 << 20,
        max_retained_messages: int = 0,     # 0 = unlimited
        enable: bool = True,
    ) -> None:
        self.enable = enable
        self.msg_expiry_interval = msg_expiry_interval
        self.max_payload_size = max_payload_size
        self.max_retained_messages = max_retained_messages
        self._store: Dict[str, Message] = {}
        self._root = _TopicNode()
        self.stats = {"dropped_oversize": 0, "dropped_table_full": 0}
        # change observer (cluster durable replication): called with
        # (topic, message) after a store, (topic, None) after a delete
        self.on_change = None

    # ------------------------------------------------------------------
    # store mutation
    # ------------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._store)

    def topics(self) -> List[str]:
        return list(self._store)

    def get(self, topic: str) -> Optional[Message]:
        """The stored retained message for an exact topic, if any."""
        return self._store.get(topic)

    def insert(self, msg: Message) -> bool:
        """Store (or delete, for empty payloads) a retained message."""
        if not self.enable:
            return False
        if not msg.payload:
            self.delete(msg.topic)
            return True
        if len(msg.payload) > self.max_payload_size:
            self.stats["dropped_oversize"] += 1
            return False
        if (
            self.max_retained_messages > 0
            and msg.topic not in self._store
            and len(self._store) >= self.max_retained_messages
        ):
            self.stats["dropped_table_full"] += 1
            return False
        if self.msg_expiry_interval > 0 and msg.expiry_interval() is None:
            msg = msg.clone(
                properties={
                    **msg.properties,
                    "Message-Expiry-Interval": self.msg_expiry_interval,
                }
            )
        self._store[msg.topic] = msg.clone(retain=True)
        node = self._root
        for w in T.words(msg.topic):
            node = node.children.setdefault(w, _TopicNode())
        node.topic = msg.topic
        if self.on_change is not None:
            self.on_change(msg.topic, self._store[msg.topic])
        return True

    def delete(self, topic: str) -> bool:
        if self._store.pop(topic, None) is None:
            return False
        # prune the index path
        path: List[Tuple[_TopicNode, str]] = []
        node = self._root
        for w in T.words(topic):
            path.append((node, w))
            node = node.children[w]
        node.topic = None
        for parent, w in reversed(path):
            child = parent.children[w]
            if child.topic is None and not child.children:
                del parent.children[w]
            else:
                break
        if self.on_change is not None:
            self.on_change(topic, None)
        return True

    def clean_expired(self, now: Optional[float] = None) -> int:
        now = now if now is not None else time.time()
        stale = [t for t, m in self._store.items() if m.is_expired(now)]
        for t in stale:
            self.delete(t)
        return len(stale)

    # ------------------------------------------------------------------
    # lookup — host walk (single filter)
    # ------------------------------------------------------------------

    def match(self, flt: str, now: Optional[float] = None) -> List[Message]:
        """All live retained messages whose topic matches ``flt``."""
        now = now if now is not None else time.time()
        ws = T.words(flt)
        hits: List[str] = []
        self._walk(self._root, ws, 0, hits, at_root=True)
        return [
            self._store[t] for t in sorted(hits)
            if not self._store[t].is_expired(now)
        ]

    def _walk(
        self, node: _TopicNode, ws: Sequence[str], i: int,
        hits: List[str], at_root: bool,
    ) -> None:
        if i == len(ws):
            if node.topic is not None:
                hits.append(node.topic)
            return
        w = ws[i]
        if w == "#":
            # '#' matches the parent level too, but never $-topics at root
            self._collect(node, hits, skip_dollar=at_root)
            return
        if w == "+":
            for cw, child in node.children.items():
                if at_root and cw.startswith("$"):
                    continue  # MQTT §4.7.2
                self._walk(child, ws, i + 1, hits, False)
            return
        child = node.children.get(w)
        if child is not None:
            self._walk(child, ws, i + 1, hits, False)

    def _collect(self, node: _TopicNode, hits: List[str], skip_dollar: bool) -> None:
        if node.topic is not None:
            hits.append(node.topic)
        for cw, child in node.children.items():
            if skip_dollar and cw.startswith("$"):
                continue
            self._collect(child, hits, False)

    # ------------------------------------------------------------------
    # lookup — device batch (many filters at once; BASELINE config #5)
    # ------------------------------------------------------------------

    def replay_batch(
        self, filters: Sequence[str], depth: int = 16,
        now: Optional[float] = None,
    ) -> Dict[str, List[Message]]:
        """Match many new filters against the whole store in ONE kernel
        call: compile ``filters`` → NFA, batch stored topic names as the
        query, invert accepts.  Falls back to host walks per filter if the
        device path overflows (fail-open, SURVEY.md §5.3)."""
        now = now if now is not None else time.time()
        names = [
            t for t, m in self._store.items() if not m.is_expired(now)
        ]
        out: Dict[str, List[Message]] = {f: [] for f in filters}
        if not names or not filters:
            return out
        try:
            from ..ops import compile_filters, match_topics

            table = compile_filters(set(filters), depth=depth)
            per_topic = match_topics(table, names)
        except (OverflowError, ValueError):
            for f in out:
                out[f] = self.match(f, now)
            return out
        for name, matched in zip(names, per_topic):
            for f in matched:
                out[f].append(self._store[name])
        for f in out:
            out[f].sort(key=lambda m: m.topic)
        return out

    # ------------------------------------------------------------------
    # broker wiring
    # ------------------------------------------------------------------

    def attach(self, broker: Broker) -> "Retainer":
        """Register the publish-store and subscribe-replay hooks."""

        def on_publish(acc: Message):
            # run_fold passes only the accumulator (args=() in publish)
            if (
                acc is not None and acc.retain
                and acc.headers.get("allow_publish") is not False
                and not acc.topic.startswith("$")
            ):
                self.insert(acc)
            return acc

        def on_subscribed(clientid: str, raw_filter: str, opts, is_new: bool):
            if not self.enable or opts.rh == 2 or (opts.rh == 1 and not is_new):
                return
            share = T.parse_share(raw_filter)
            if share is not None:
                return  # $share subs get no retained replay (MQTT5 §4.8.2)
            msgs = self.match(raw_filter)
            if msgs:
                broker.deliver_direct(clientid, opts, msgs)

        broker.hooks.add("message.publish", on_publish, priority=-100,
                         name="retainer.store")
        broker.hooks.add("session.subscribed", on_subscribed,
                         name="retainer.replay")
        return self
