"""Delayed publish: ``$delayed/<secs>/<topic>`` scheduling.

Behavioral reference: ``apps/emqx_delayed`` [U] (SURVEY.md §2.3): a
PUBLISH to ``$delayed/5/a/b`` is intercepted (never routed immediately),
held for 5 seconds, then republished to ``a/b``.  Bad intervals are a
drop; an optional table bound sheds the newest (reference drops when the
mnesia table hits its limit).

Tick-driven like every timer here: the owner's event loop calls
:meth:`tick`, which republishes due messages through the normal broker
pipeline (hooks, retainer, metrics all see them).
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import List, Optional, Tuple

from ..broker.broker import Broker
from ..broker.hooks import STOP
from ..broker.message import Message

__all__ = ["DelayedPublish"]

PREFIX = "$delayed/"
MAX_DELAY = 4294967.0  # reference caps the interval at 2^32 ms


class DelayedPublish:
    def __init__(self, max_delayed_messages: int = 0, enable: bool = True) -> None:
        self.enable = enable
        self.max_delayed_messages = max_delayed_messages
        self._heap: List[Tuple[float, int, Message]] = []
        self._seq = itertools.count()
        self.stats = {"accepted": 0, "dropped_bad_topic": 0, "dropped_full": 0}

    def __len__(self) -> int:
        return len(self._heap)

    # ------------------------------------------------------------------

    def intercept(self, msg: Message, now: Optional[float] = None) -> Optional[Message]:
        """If ``msg`` targets $delayed/..., queue it and return None
        (callers drop it from the normal pipeline); else return ``msg``."""
        if not self.enable or not msg.topic.startswith(PREFIX):
            return msg
        rest = msg.topic[len(PREFIX):]
        secs_str, _, real_topic = rest.partition("/")
        try:
            secs = float(secs_str)
        except ValueError:
            secs = -1.0
        if not real_topic or not 0 <= secs <= MAX_DELAY:
            self.stats["dropped_bad_topic"] += 1
            return None
        if (
            self.max_delayed_messages > 0
            and len(self._heap) >= self.max_delayed_messages
        ):
            self.stats["dropped_full"] += 1
            return None
        now = now if now is not None else time.time()
        heapq.heappush(
            self._heap,
            (now + secs, next(self._seq), msg.clone(topic=real_topic)),
        )
        self.stats["accepted"] += 1
        return None

    def schedule(
        self, msg: Message, delay: float, now: Optional[float] = None
    ) -> None:
        """Direct enqueue of an already-stripped message (persistence
        restore path — bypasses the $delayed/ topic parsing)."""
        now = now if now is not None else time.time()
        heapq.heappush(self._heap, (now + delay, next(self._seq), msg))

    def due(self, now: Optional[float] = None) -> List[Message]:
        """Pop every message whose delay has elapsed."""
        now = now if now is not None else time.time()
        out: List[Message] = []
        while self._heap and self._heap[0][0] <= now:
            _, _, msg = heapq.heappop(self._heap)
            out.append(msg)
        return out

    def next_deadline(self) -> Optional[float]:
        return self._heap[0][0] if self._heap else None

    def to_list(self) -> List[Tuple[float, Message]]:
        return [(at, m) for at, _, m in sorted(self._heap)]

    def entries(self) -> List[Tuple[float, int, Message]]:
        """(fire_at, seq, msg) rows — stable keys for persistence."""
        return sorted(self._heap)

    # ------------------------------------------------------------------

    def attach(self, broker: Broker) -> "DelayedPublish":
        def on_publish(acc: Message):
            if acc is None:
                return acc
            kept = self.intercept(acc)
            if kept is None:
                return (STOP, None)  # swallowed: scheduled or dropped
            return kept

        # intercept before ordinary priority-0 hooks (rule engine etc.)
        broker.hooks.add("message.publish", on_publish, priority=100,
                         name="delayed.intercept")
        self._broker = broker
        return self

    def tick(self, now: Optional[float] = None) -> int:
        """Republish due messages through the normal pipeline."""
        msgs = self.due(now)
        for m in msgs:
            self._broker.publish(m)
        return len(msgs)
