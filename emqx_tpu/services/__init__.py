"""L7 services on the hook bus (SURVEY.md §2.3): retainer, delayed
publish, topic rewrite, auto-subscribe — each the analog of one reference
app (``apps/emqx_retainer``, ``apps/emqx_delayed``, ``apps/emqx_modules``,
``apps/emqx_auto_subscribe`` [U]), attached to a Broker's hook bus.
"""

from .retainer import Retainer
from .delayed import DelayedPublish
from .rewrite import TopicRewrite, RewriteRule
from .auto_subscribe import AutoSubscribe

__all__ = [
    "Retainer", "DelayedPublish", "TopicRewrite", "RewriteRule",
    "AutoSubscribe",
]
