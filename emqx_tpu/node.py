"""BrokerNode: the application assembly — config → running broker.

Behavioral reference: ``emqx_app:start`` / ``emqx_sup`` boot order [U]
(SURVEY.md §3.1): config load → cluster substrate → core workers
(hooks/metrics/router/broker/cm/sys) → dependent services (auth, retainer,
delayed, rewrite, rule engine) → listeners last, so no client connects to a
half-booted node.  Stop order is the reverse.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, List, Optional

from .auth import AuthChain, Authz, attach_auth
from .broker import Broker
from .broker.banned import Banned
from .broker.channel import Channel
from .broker.cm import ConnectionManager
from .broker.flapping import Flapping
from .broker.limiter import LimiterGroup
from .config import Config
from .observe.wiring import observe
from .rule_engine import RuleEngine
from .services.auto_subscribe import AutoSubscribe
from .services.delayed import DelayedPublish
from .services.retainer import Retainer
from .services.rewrite import TopicRewrite
from .transport.connection import ConnInfo, Connection
from .transport.listener import Listener, Listeners

log = logging.getLogger(__name__)


def enable_xla_cache(path: str) -> bool:
    """Point JAX's persistent compilation cache at ``path`` (under the
    segments dir): a process restart finds every previously-compiled
    serve executable on disk, so even the FIRST cold-start compile is
    a cache hit instead of an XLA run.  Returns True when the cache is
    active; best-effort — a jax without the knobs (or no jax at all)
    degrades to in-memory compiles, never a startup failure."""
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:
        log.exception("persistent XLA compilation cache unavailable; "
                      "cold-start compiles stay in-memory")
        return False
    # cache every executable however fast its compile was (the serve
    # kernels are small; the default min-time floor would skip them) —
    # tuning knobs are advisory, absence is not an error
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # noqa: PERF203 — per-knob isolation
            log.debug("XLA cache knob %s unsupported by this jax",
                      knob, exc_info=True)
    return True

__all__ = ["BrokerNode"]


class BrokerNode:
    """One broker node: all subsystems wired, listeners optional.

    Synchronous parts (broker/session/services) work immediately after
    construction; ``await start()`` brings up listeners and periodic jobs.
    """

    def __init__(
        self,
        config: Optional[Config] = None,
        auth_chain: Optional[AuthChain] = None,
        authz: Optional[Authz] = None,
    ) -> None:
        self.config = config if config is not None else Config()
        cfg = self.config
        self.node_name = cfg.get("node.name")
        self.broker = Broker(
            node=self.node_name,
            shared_strategy=cfg.get("broker.shared_subscription_strategy"),
            session_defaults={
                "max_inflight": cfg.get("mqtt.max_inflight"),
                "max_mqueue_len": cfg.get("mqtt.max_mqueue_len"),
                "expiry_interval": cfg.get("mqtt.session_expiry_interval"),
                "max_awaiting_rel": cfg.get("mqtt.max_awaiting_rel"),
            },
        )
        self.cm = ConnectionManager(self.broker)
        self.observed = observe(
            self.broker, sys_interval=cfg.get("broker.sys_msg_interval")
        )
        # supervision tree (supervise.py): every long-lived background
        # task (fanout drain, cluster loops, bridge workers, gateway
        # retry, exhook senders, telemetry/statsd, housekeeping)
        # registers here; restart intensity escalates to an alarm +
        # degraded mode instead of dying
        from .broker.olp import Olp
        from .supervise import Supervisor

        self.supervisor = Supervisor(
            metrics=self.observed.metrics,
            alarms=self.observed.alarms,
            max_restarts=cfg.get("supervisor.max_restarts"),
            window_s=cfg.get("supervisor.window"),
            backoff_base=cfg.get("supervisor.backoff_base"),
            backoff_max=cfg.get("supervisor.backoff_max"),
        )
        self.olp = Olp(
            alarms=self.observed.alarms,
            max_loop_lag=cfg.get("overload_protection.max_loop_lag"),
            max_queue_depth=cfg.get("overload_protection.max_queue_depth"),
            cooloff=cfg.get("overload_protection.cooloff"),
        )
        # sleep-drift sampler: CPU saturation trips overload protection
        # even when no queue grows (started as a supervised child)
        from .broker.olp import LoopLagProbe

        self.lag_probe = None
        probe_interval = cfg.get("overload_protection.lag_probe_interval")
        if probe_interval and probe_interval > 0:
            self.lag_probe = LoopLagProbe(
                self.olp, metrics=self.observed.metrics,
                interval=probe_interval,
            )
        # connection gauges come from the CM (a node-level table), so
        # they wire here rather than in observe(broker)
        self.observed.stats.provide(
            "connections.count", self.cm.total_connection_count)
        self.observed.stats.provide(
            "live_connections.count", self.cm.connection_count)
        self.banned = Banned().attach(self.broker)
        self.flapping = Flapping(
            self.banned,
            max_count=cfg.get("flapping_detect.max_count"),
            window_time=cfg.get("flapping_detect.window_time"),
            ban_time=cfg.get("flapping_detect.ban_time"),
            enable=cfg.get("flapping_detect.enable"),
        ).attach(self.broker)
        # batched admission plane (broker/admission.py): per-client
        # behavior features scored in one vectorized pass per tick by
        # the supervised admission.score child, feeding the quarantine
        # ladder (throttle via the client's TokenBucket, QoS0-shed,
        # temp-ban via Banned).  Off keeps broker.admission None —
        # every seam stays one attr load + identity test.
        self.admission = None
        if cfg.get("admission.enable"):
            from .broker.admission import Admission

            self.admission = Admission(
                banned=self.banned,
                alarms=self.observed.alarms,
                metrics=self.observed.metrics,
                olp=self.olp,
                tick_s=cfg.get("admission.tick"),
                fan_window=cfg.get("admission.fan_window"),
                alpha=cfg.get("admission.alpha"),
                threshold=cfg.get("admission.threshold"),
                clear_ratio=cfg.get("admission.clear_ratio"),
                hold_ticks=cfg.get("admission.hold_ticks"),
                decay_ticks=cfg.get("admission.decay_ticks"),
                throttle_rate=cfg.get("admission.throttle_rate"),
                restore_rate=cfg.get("limiter.max_messages_rate"),
                ban_time=cfg.get("admission.ban_time"),
                idle_expiry=cfg.get("admission.idle_expiry"),
                max_connect_rate=cfg.get("admission.max_connect_rate"),
                max_malformed_rate=cfg.get(
                    "admission.max_malformed_rate"),
                max_auth_fail_rate=cfg.get(
                    "admission.max_auth_fail_rate"),
                max_publish_rate=cfg.get("admission.max_publish_rate"),
                max_publish_bytes_rate=cfg.get(
                    "admission.max_publish_bytes_rate"),
                max_topic_fan=cfg.get("admission.max_topic_fan"),
            ).attach(self.broker)
            self.admission.throttle_cb = self._admission_throttle
            self.admission.kick_cb = self.kick_client
        self.retainer = (
            Retainer(
                msg_expiry_interval=cfg.get("retainer.msg_expiry_interval"),
                max_payload_size=cfg.get("retainer.max_payload_size"),
                max_retained_messages=cfg.get("retainer.max_retained_messages"),
            ).attach(self.broker)
            if cfg.get("retainer.enable")
            else None
        )
        self.delayed = (
            DelayedPublish(
                max_delayed_messages=cfg.get("delayed.max_delayed_messages")
            ).attach(self.broker)
            if cfg.get("delayed.enable")
            else None
        )
        self.rewrite = TopicRewrite([]).attach(self.broker)
        self.auto_subscribe = AutoSubscribe()
        self.auto_subscribe.attach(self.broker)
        self.rule_engine = RuleEngine(self.broker)
        from .bridge import BridgeManager

        self.bridges = BridgeManager(self)
        self.access_control = None
        self._auth_confs: list = []    # REST-created authenticator confs
        self._authz_confs: list = []   # REST-created source confs
        if auth_chain is not None or authz is not None:
            self.access_control = attach_auth(
                self.broker,
                auth_chain if auth_chain is not None else AuthChain(
                    allow_anonymous=cfg.get("authn.allow_anonymous")),
                authz if authz is not None else Authz(
                    no_match=cfg.get("authz.no_match")
                ),
            )

        from .observe.trace import TraceManager

        self.tracing = TraceManager(self)
        # stage-level latency observatory: the main plane's histogram
        # set (None = every recording site is zero-call) + the
        # always-on flight recorder dumping into the TraceManager dir
        # on breaker trip / brownout escalation / supervisor_degraded /
        # the mgmt manual trigger
        from .observe.flightrec import FlightRecorder
        from .observe.hist import HistSet

        self.hists = HistSet("main") if cfg.get("obs.hist.enable") \
            else None
        if self.hists is not None:
            # sync publish path spans: traffic bypassing the fanout
            # pipeline (shape gate, fanout off) records into the same
            # deliver/flush/e2e histograms the batched drain writes
            self.broker.attach_hists(self.hists)
        self.flightrec = FlightRecorder(
            self.tracing.dir,
            depth=cfg.get("obs.flightrec.depth"),
            metrics=self.observed.metrics,
        )
        self.supervisor.flightrec = self.flightrec
        if self.admission is not None:
            # built above, before the recorder existed: escalation
            # dumps (reason admission_escalation) wire up here
            self.admission.flightrec = self.flightrec
        self.observed.sys.attach_hists(self.hist_percentiles)
        from .observe.slow_subs import SlowSubs
        from .plugins import PluginManager

        self.slow_subs = (
            SlowSubs(
                threshold_ms=cfg.get("slow_subs.threshold") * 1e3,
                top_k=cfg.get("slow_subs.top_k"),
                window_s=cfg.get("slow_subs.window_time"),
                max_ms=cfg.get("slow_subs.latency_ceiling") * 1e3,
            ).attach(self.broker)
            if cfg.get("slow_subs.enable") else None
        )
        from .observe.topic_metrics import TopicMetrics

        self.topic_metrics = TopicMetrics(
            max_topics=cfg.get("topic_metrics.max_topics")
        ).attach(self.broker)
        self.plugins = PluginManager(self)
        self.psk = None
        if cfg.get("psk.enable"):
            from .auth.psk import PskStore

            self.psk = PskStore(
                (cfg.get("psk.entries") or "").replace(",", "\n")
            )
        self.statsd = None
        self.telemetry = None
        self._attach_client_metrics()
        self._register_config_handlers()
        # session expiry: clientid -> disconnect time, swept by
        # housekeeping; must exist before restore so restored disconnected
        # sessions enter the expiry sweep immediately
        self._disconnected_at: Dict[str, float] = {}
        self.persistence = None
        data_dir = (cfg.get("node.data_dir") or "").strip()
        if data_dir:
            from .storage import Persistence

            self.persistence = Persistence(self, data_dir)
            self.persistence.restore()

        self.exhook = None  # built lazily in start() (needs a loop + grpc)
        self.ocsp_cache = None  # OCSP stapling cache (ssl listener)
        self.quic = None        # QUIC endpoint (quic listener)
        self.quic_port = 0
        self.cluster = None  # built lazily in start() (needs a loop)
        self.match_service = None  # in-process TPU matcher (start())
        self.fanout_pipeline = None  # batched publish fanout (start())
        self.mgmt = None
        self.mgmt_server = None
        self.gateways = None  # GatewayManager, built in start()
        self.dashboard_users = None  # DashboardUsers, built in _start_mgmt
        self.limiter = LimiterGroup(
            max_conn_rate=cfg.get("limiter.max_conn_rate"),
            max_messages_rate=cfg.get("limiter.max_messages_rate"),
            max_bytes_rate=cfg.get("limiter.max_bytes_rate"),
        )
        # hashed timer wheel (transport/timerwheel.py), part of the one
        # batched-stack opt-in: per-connection keepalive/retry ticks and
        # gateway sweeps ride coarse buckets — one scheduled callback
        # per tick regardless of connection count.  Flag off keeps the
        # PR-5 per-connection loop.call_later timers byte-for-byte.
        self.timer_wheel = None
        self.shard_pool = None  # connection-plane shards (start())
        if cfg.get("broker.fanout.enable"):
            from .transport.timerwheel import TimerWheel

            self.timer_wheel = TimerWheel()
        self.listeners = Listeners()
        self.connections: Dict[str, Connection] = {}  # clientid -> conn
        # every accepted connection, incl. pre-CONNECT ones — stop() must
        # be able to close sockets that never completed a handshake
        self._all_conns: set = set()
        self.broker.on_deliver = self._on_deliver
        self._jobs: List[Any] = []  # tasks or supervised Child handles
        self.started_at = time.time()
        self._running = False
        self._last_idle_sweep = time.monotonic()
        self._configure_listeners()

    # ------------------------------------------------------------------

    def _attach_client_metrics(self) -> None:
        m = self.observed.metrics
        hooks = self.broker.hooks
        hooks.add("client.connect",
                  lambda cid, pkt: m.inc("client.connect"),
                  name="metrics.client.connect")
        hooks.add("client.connected",
                  lambda cid, info: (m.inc("client.connected"),
                                     self._disconnected_at.pop(cid, None))[0],
                  name="metrics.client.connected")
        hooks.add("client.disconnected",
                  lambda cid, reason: (m.inc("client.disconnected"),
                                       self._mark_disconnected(cid))[0],
                  name="metrics.client.disconnected")
        hooks.add("client.subscribe",
                  lambda cid, pkt: m.inc("client.subscribe"),
                  name="metrics.client.subscribe")
        hooks.add("client.unsubscribe",
                  lambda cid, pkt: m.inc("client.unsubscribe"),
                  name="metrics.client.unsubscribe")

    def _register_config_handlers(self) -> None:
        """Hot-update plumbing (emqx_config_handler analog): push runtime
        config changes into the live components, so PUT /api/v5/configs
        actually takes effect (SURVEY.md §5.6)."""
        cfg = self.config
        cfg.on_update(
            "limiter.max_conn_rate",
            lambda p, o, n: self.limiter.reconfigure(max_conn_rate=n),
        )
        cfg.on_update(
            "limiter.max_messages_rate",
            lambda p, o, n: self.limiter.reconfigure(max_messages_rate=n),
        )
        cfg.on_update(
            "limiter.max_bytes_rate",
            lambda p, o, n: self.limiter.reconfigure(max_bytes_rate=n),
        )
        cfg.on_update(
            "mqtt.max_inflight",
            lambda p, o, n: self.broker.session_defaults.__setitem__(
                "max_inflight", n
            ),
        )
        cfg.on_update(
            "mqtt.max_mqueue_len",
            lambda p, o, n: self.broker.session_defaults.__setitem__(
                "max_mqueue_len", n
            ),
        )
        cfg.on_update(
            "broker.shared_subscription_strategy",
            lambda p, o, n: setattr(self.broker.shared, "strategy", n),
        )
        if self.retainer is not None:
            cfg.on_update(
                "retainer.msg_expiry_interval",
                lambda p, o, n: setattr(
                    self.retainer, "msg_expiry_interval", n
                ),
            )
        if self.delayed is not None:
            cfg.on_update(
                "delayed.max_delayed_messages",
                lambda p, o, n: setattr(
                    self.delayed, "max_delayed_messages", n
                ),
            )
        if self.access_control is not None:
            cfg.on_update(
                "authz.no_match",
                lambda p, o, n: setattr(
                    self.access_control.authz, "no_match", n
                ),
            )

    def _admission_throttle(self, clientid: str,
                            rate: Optional[float]) -> bool:
        """Admission-ladder level 1: retune the live connection's
        message TokenBucket IN PLACE (the proto holds a direct
        reference, so a dict swap would detach it).  ``rate`` None
        restores the configured limiter.max_messages_rate.  Shard-owned
        connections share the same bucket object; the retune is a pair
        of float stores — a racy read on the shard loop sees either
        rate, both valid (the gauge-not-invariant discipline)."""
        conn = self.connections.get(clientid)
        if conn is None:
            return False
        bucket = getattr(conn, "_msg_bucket", None)
        if bucket is None:
            return False
        if rate is None:
            restore = float(self.config.get("limiter.max_messages_rate"))
            bucket.retune(restore)
        else:
            bucket.retune(rate)
        return True

    def _mark_disconnected(self, clientid: str) -> None:
        sess = self.broker.sessions.get(clientid)
        if sess is not None:
            self._disconnected_at[clientid] = time.time()

    def _configure_listeners(self) -> None:
        cfg = self.config
        if cfg.get("listeners.tcp.default.enable"):
            self.listeners.add(
                Listener(
                    "default",
                    cfg.get("listeners.tcp.default.bind"),
                    self.handle_stream,
                    kind="tcp",
                    max_connections=cfg.get(
                        "listeners.tcp.default.max_connections"
                    ),
                    max_conn_rate=cfg.get("limiter.max_conn_rate"),
                    reuse_port=cfg.get("listeners.tcp.default.reuse_port"),
                    proto_factory=(
                        self.make_protocol
                        if cfg.get("listeners.tcp.default.fast_path")
                        else None
                    ),
                )
            )
        if cfg.get("listeners.ssl.default.enable"):
            ctx = self._build_ssl_context()
            if ctx is not None:
                self.listeners.add(
                    Listener(
                        "ssl-default",
                        cfg.get("listeners.ssl.default.bind"),
                        self.handle_stream,
                        kind="tcp",
                        ssl_context=ctx,
                    )
                )
        if cfg.get("listeners.ws.default.enable"):
            self.listeners.add(
                Listener(
                    "default",
                    cfg.get("listeners.ws.default.bind"),
                    self.handle_stream,
                    kind="ws",
                )
            )

    def _build_ssl_context(self):
        """Server TLS context for the ssl listener: certfile/keyfile,
        optional client-cert verification, optional PSK identities
        (gated on runtime support — SURVEY.md §2.4 posture)."""
        import ssl as _ssl

        cfg = self.config
        cert = (cfg.get("listeners.ssl.default.certfile") or "").strip()
        key = (cfg.get("listeners.ssl.default.keyfile") or "").strip()
        ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
        try:
            if cert:
                ctx.load_cert_chain(cert, key or None)
            elif self.psk is None:
                log.error("ssl listener enabled without certfile or psk")
                return None
            ca = (cfg.get("listeners.ssl.default.cacertfile") or "").strip()
            if ca:
                ctx.load_verify_locations(ca)
            if cfg.get("listeners.ssl.default.verify"):
                ctx.verify_mode = _ssl.CERT_REQUIRED
            crl = (cfg.get("listeners.ssl.default.crlfile") or "").strip()
            if crl:
                # revocation: load_verify_locations accepts CRL PEMs;
                # the flag decides leaf-only vs whole-chain checking.
                # A CRL without client-cert verification would be
                # silently inert (no cert is ever requested) — fail
                # closed by implying CERT_REQUIRED.
                ctx.load_verify_locations(cafile=crl)
                check = (cfg.get("listeners.ssl.default.crl_check")
                         or "leaf").strip().lower()
                if check not in ("leaf", "chain"):
                    # unknown value fails CLOSED (the stricter scope) —
                    # a typo must not silently weaken revocation
                    log.warning("unknown crl_check %r; using 'chain'",
                                check)
                    check = "chain"
                ctx.verify_flags |= (
                    _ssl.VERIFY_CRL_CHECK_CHAIN if check == "chain"
                    else _ssl.VERIFY_CRL_CHECK_LEAF)
                if ctx.verify_mode != _ssl.CERT_REQUIRED:
                    log.warning(
                        "crlfile set without verify=true; enabling "
                        "client-cert verification (CRL would otherwise "
                        "never be consulted)")
                    ctx.verify_mode = _ssl.CERT_REQUIRED
            if self.psk is not None:
                self.psk.wire_into(ctx)
            sni = (cfg.get("listeners.ssl.default.sni") or "").strip()
            if sni:
                # per-hostname contexts: "host=cert.pem;key.pem" list
                by_host = {}
                for entry in sni.split(","):
                    entry = entry.strip()
                    if not entry:
                        continue  # trailing comma etc.
                    host_part, eq, files = entry.partition("=")
                    c, _, k = files.partition(";")
                    if not eq or not c.strip():
                        log.warning("ignoring bad sni entry %r", entry)
                        continue
                    hctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
                    hctx.load_cert_chain(c.strip(), k.strip() or None)
                    by_host[host_part.strip().lower()] = hctx

                def pick(sock, server_name, _ctx):
                    if server_name:
                        hctx = by_host.get(server_name.lower())
                        if hctx is not None:
                            sock.context = hctx
                    return None  # unmatched names use the default chain

                ctx.sni_callback = pick
        except (OSError, _ssl.SSLError):
            log.exception("ssl listener context build failed; disabled")
            return None
        return ctx

    # ------------------------------------------------------------------
    # connection plumbing
    # ------------------------------------------------------------------

    def ensure_access_control(self):
        """REST-driven auth management attaches lazily: a node that
        booted with no auth gets a live chain on the first authenticator
        create (reference: authn/authz are runtime-configured)."""
        if self.access_control is None:
            self.access_control = attach_auth(
                self.broker,
                AuthChain(allow_anonymous=self.config.get(
                    "authn.allow_anonymous")),
                Authz(no_match=self.config.get("authz.no_match")),
            )
        return self.access_control

    def make_channel(self, conninfo: Optional[dict] = None) -> Channel:
        cfg = self.config
        return Channel(
            self.broker,
            self.cm,
            conninfo=conninfo,
            max_topic_alias=cfg.get("mqtt.max_topic_alias"),
            max_inflight=cfg.get("mqtt.max_inflight"),
            server_keepalive=(cfg.get("mqtt.server_keepalive") or None),
        )

    def _wants_intercept(self) -> bool:
        return (
            self.exhook is not None
            or self.cluster is not None
            or self.match_service is not None
            or (self.access_control is not None
                and self.access_control.needs_async())
        )

    def _register_on_connect(self, channel, conn) -> None:
        """Wrap handle_in so the clientid→connection registry fills the
        moment CONNECT lands (cheap and race-free on one loop)."""
        prev = channel.handle_in

        def handle_in_and_register(pkt):
            acts = prev(pkt)
            cid = channel.clientid
            if cid is not None and self.connections.get(cid) is not conn:
                if channel.state == "connected":
                    self.connections[cid] = conn
            return acts

        channel.handle_in = handle_in_and_register

    def make_protocol(self, info: ConnInfo):
        """Listener factory for the protocol-mode TCP datapath."""
        from .transport.proto_conn import MqttProtocol

        channel = self.make_channel(conninfo={"listener": info.listener})
        proto = MqttProtocol(
            channel,
            conninfo=info,
            max_packet_size=self.config.get("mqtt.max_packet_size"),
            limiter=self.limiter,
            on_closed=self._proto_closed,
            intercept=self._intercept if self._wants_intercept() else None,
            metrics=self.observed.metrics,
            # the batched-delivery stack is one opt-in: fanout pipeline
            # + ack-burst batching + write coalescing ride the same
            # flag, so the default datapath stays per-packet identical
            coalesce=bool(self.config.get("broker.fanout.enable")),
            wheel=self.timer_wheel,
        )
        if self.hists is not None:
            proto._h_parse = self.hists.hist("obs.stage.ingest_parse")
        channel.conn = proto
        self._register_on_connect(channel, proto)
        self._all_conns.add(proto)
        return proto

    def make_shard_protocol(self, shard):
        """Accept-time factory for a SHARD-owned connection: runs on
        the shard's loop, so everything it builds is shard-affine —
        the ShardChannel marshals broker-touching packets back here
        (transport/shards.py has the full thread-safety contract)."""
        from .transport.proto_conn import MqttProtocol  # noqa: F401
        from .transport.shards import ShardChannel, _ShardProtocol

        pool = self.shard_pool
        cfg = self.config
        info = ConnInfo(listener="tcp:default")
        channel = ShardChannel(
            pool, shard, self.broker, self.cm,
            conninfo={"listener": info.listener},
            max_topic_alias=cfg.get("mqtt.max_topic_alias"),
            max_inflight=cfg.get("mqtt.max_inflight"),
            server_keepalive=(cfg.get("mqtt.server_keepalive") or None),
        )
        proto = _ShardProtocol(
            channel,
            conninfo=info,
            max_packet_size=cfg.get("mqtt.max_packet_size"),
            limiter=shard.limiter,
            on_closed=pool.conn_closed,
            intercept=None,
            metrics=self.observed.metrics,
            coalesce=True,
            wheel=shard.wheel,
        )
        proto.shard = shard
        if shard.hists is not None:
            # the shard's OWN ingest_parse histogram: written only by
            # this shard's loop thread, merged at read time
            proto._h_parse = shard.hists.hist("obs.stage.ingest_parse")
        channel.conn = proto
        self._all_conns.add(proto)
        return proto

    def _proto_closed(self, proto) -> None:
        self._all_conns.discard(proto)
        self._conn_closed(proto)

    async def handle_stream(self, stream: Any, info: ConnInfo) -> None:
        """Listener entry: run one client connection to completion."""
        channel = self.make_channel(
            conninfo={"peername": stream.peername(), "listener": info.listener}
        )
        conn = Connection(
            stream,
            channel,
            conninfo=info,
            max_packet_size=self.config.get("mqtt.max_packet_size"),
            limiter=self.limiter,
            on_closed=self._conn_closed,
            # stream-path parity: the one batched-stack opt-in also
            # turns on ack-run ingest here (ws/quic/tcp-stream riders)
            coalesce=bool(self.config.get("broker.fanout.enable")),
            wheel=self.timer_wheel,
        )
        channel.conn = conn  # takeover routing (connection.py)
        self._register_on_connect(channel, conn)
        if self._wants_intercept():
            conn.intercept = self._intercept
        self._all_conns.add(conn)
        try:
            await conn.run()
        finally:
            self._all_conns.discard(conn)
            self.limiter.drop_conn(str(id(conn)))

    def _conn_closed(self, conn: Connection) -> None:
        cid = conn.channel.clientid
        if cid is not None and self.connections.get(cid) is conn:
            del self.connections[cid]

    def _on_deliver(self, clientid: str, pubs: List[Any]) -> None:
        conn = self.connections.get(clientid)
        if conn is None:
            self.broker.outbox_put(clientid, pubs)
            return
        shard = getattr(conn, "shard", None)
        if shard is not None:
            # reverse delivery path: serialize + write on the OWNING
            # shard loop (batched: one wakeup per drained burst)
            shard.post_deliver(conn, pubs)
        else:
            conn.deliver(pubs)

    def _kick_conn(self, conn, reason: str) -> None:
        """Kick that respects loop affinity: a shard-owned connection
        must be closed on its own loop."""
        shard = getattr(conn, "shard", None)
        if shard is None:
            conn.kick(reason)
        elif shard.alive():
            shard.post(lambda: conn.kick(reason))
        # dead shard: its cleanup already closed the socket

    def kick_client(self, clientid: str) -> bool:
        """Management 'kick out client' (emqx_mgmt:kickout_client).
        Also evicts an offline durable session (no live channel)."""
        had_session = clientid in self.broker.sessions
        chan = self.cm.kick(clientid)  # discards the broker session too
        conn = self.connections.pop(clientid, None)
        if conn is not None:
            self._kick_conn(conn, "kicked by management")
        self._disconnected_at.pop(clientid, None)
        return chan is not None or conn is not None or had_session

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def _intercept(self, channel, pkt):
        """Composite async pre-handle_in stage: cluster session migration
        first (a takeover must land before CONNECT resumes the session),
        then the TPU match prefetch (micro-batches concurrent publishes
        into one kernel call; Broker.publish consumes the hint), then the
        exhook advisory round trips."""
        from .mqtt import packet as P

        if (
            self.cluster is not None
            and pkt.type == P.CONNECT
            and channel.state == "idle"
        ):
            try:
                await self.cluster.prepare_connect(pkt)
            except Exception:
                log.exception("cluster takeover stage failed")
        if self.match_service is not None and pkt.type == P.PUBLISH \
                and self.broker.fanout is None:
            # with the fanout pipeline active the per-publish prefetch is
            # redundant: the pipeline batch-prefetches every topic in a
            # batch through ONE prefetch_many call at drain time
            try:
                await self.match_service.prefetch(pkt.topic, qos=pkt.qos)
            except Exception:
                log.exception("match prefetch failed (host path serves)")
        ac = self.access_control
        if ac is not None and ac.needs_async():
            # resolve network auth backends OFF the sync hook fold: the
            # verdicts park in the backends and the fold consumes them
            try:
                if pkt.type == P.CONNECT:
                    # enhanced-auth CONNECTs never run the authn chain —
                    # pre-resolving would query backends for nothing
                    if not (pkt.proto_ver == 5 and pkt.properties.get(
                            "Authentication-Method")):
                        await ac.preauthenticate(channel, pkt)
                elif pkt.type == P.PUBLISH:
                    # MQTT5 topic-alias publishes carry an empty topic;
                    # resolve through the channel's alias map so the
                    # prefetch covers the topic the sync fold authorizes
                    # (publish rewrite runs LATER, in broker.publish —
                    # the channel authorizes the original topic)
                    topic = channel.peek_topic(pkt)
                    if topic:
                        await ac.preauthorize(
                            channel.clientid, "publish", topic, pkt.qos)
                elif pkt.type == P.SUBSCRIBE:
                    # the subscribe rewrite hook (client.subscribe, prio
                    # 50) mutates the filters BEFORE the channel's
                    # authorize fold — prefetch the rewritten form
                    for flt, opts in pkt.topic_filters:
                        flt = self.rewrite.rewrite(
                            flt, "sub", channel.clientid,
                            self.broker.usernames.get(channel.clientid))
                        await ac.preauthorize(
                            channel.clientid, "subscribe", flt,
                            opts.get("qos", 0))
            except Exception:
                log.exception("async auth pre-resolution failed")
        if self.exhook is not None:
            return await self.exhook.intercept(channel, pkt)
        return None

    async def start(self) -> None:
        await self._start_match_service()
        await self._start_fanout()
        await self._start_cluster()
        await self._start_exhook()
        await self._start_mgmt()
        await self._start_gateways()
        if self.config.get("statsd.enable"):
            from .observe.statsd import StatsdPusher

            self.statsd = StatsdPusher(
                self.observed,
                server=self.config.get("statsd.server"),
                interval=self.config.get("statsd.flush_interval"),
                supervisor=self.supervisor,
                hist_source=self.hist_percentiles,
            )
            await self.statsd.start()
        if self.config.get("telemetry.enable"):
            from .observe.telemetry import Telemetry

            self.telemetry = Telemetry(
                self, url=self.config.get("telemetry.url"),
                interval=self.config.get("telemetry.interval"),
                supervisor=self.supervisor,
            )
            await self.telemetry.start()
        self._start_ocsp()
        await self._start_quic()
        self._maybe_shard()
        await self.listeners.start_all()
        self._running = True
        self._jobs.append(self.supervisor.start_child(
            "node.housekeeping", self._housekeeping))
        if self.lag_probe is not None:
            self._jobs.append(self.supervisor.start_child(
                "olp.lag_probe", self.lag_probe.run))
        if self.admission is not None:
            # the vectorized anomaly scorer: a crash/kill/injected
            # fault fails open (decisions clear, admission_degraded
            # alarm) and the supervisor restarts it
            self._jobs.append(self.supervisor.start_child(
                "admission.score", self.admission.run))

    def _maybe_shard(self) -> None:
        """Attach the connection-plane shard pool to the default TCP
        listener when configured and compatible (plain TCP fast path,
        batched stack on, no async advisory stage — see
        transport/shards.py for the exact contract)."""
        cfg = self.config
        n = int(cfg.get("broker.conn.shards") or 0)
        if n <= 0:
            return
        if not cfg.get("broker.fanout.enable"):
            log.warning("broker.conn.shards needs broker.fanout.enable; "
                        "sharding disabled")
            return
        if self._wants_intercept():
            log.warning("broker.conn.shards is incompatible with the "
                        "async advisory stage (exhook/cluster/tpu/async "
                        "auth); sharding disabled")
            return
        lst = self.listeners.get("tcp:default")
        if lst is None or lst.proto_factory is None \
                or lst.ssl_context is not None:
            log.warning("broker.conn.shards needs the plain-TCP "
                        "fast_path listener; sharding disabled")
            return
        from .transport.shards import ShardPool

        self.shard_pool = ShardPool(self, n)
        lst.shard_pool = self.shard_pool

    async def _start_quic(self) -> None:
        """MQTT-over-QUIC listener (quicer analog): the in-repo
        RFC 9000/9001 stack feeding stream 0 into handle_stream."""
        cfg = self.config
        if not cfg.get("listeners.quic.default.enable"):
            return
        cert = (cfg.get("listeners.quic.default.certfile")
                or cfg.get("listeners.ssl.default.certfile") or "").strip()
        key = (cfg.get("listeners.quic.default.keyfile")
               or cfg.get("listeners.ssl.default.keyfile") or "").strip()
        if not cert or not key:
            log.warning("quic listener enabled without a cert pair")
            return
        try:
            # cert reads off-loop: a slow/network filesystem must not
            # stall connections already being served (staticcheck:
            # no-blocking-in-async)
            from pathlib import Path
            cert_pem = await asyncio.to_thread(Path(cert).read_bytes)
            key_pem = await asyncio.to_thread(Path(key).read_bytes)
            from .transport.connection import ConnInfo
            from .transport.quic import QuicEndpoint

            bind = cfg.get("listeners.quic.default.bind")
            host, _, port = bind.rpartition(":")
            loop = asyncio.get_running_loop()

            class _Proto(asyncio.DatagramProtocol):
                def __init__(p) -> None:  # noqa: N805
                    pass

                def connection_made(p, transport) -> None:  # noqa: N805
                    self._quic_transport = transport

                def datagram_received(p, data, addr) -> None:  # noqa: N805
                    if self.quic is not None:
                        self.quic.datagram_received(data, addr)

            self._quic_transport, _ = await loop.create_datagram_endpoint(
                _Proto, local_addr=(host or "0.0.0.0", int(port)))
            self.quic_port = \
                self._quic_transport.get_extra_info("sockname")[1]
            try:
                # DF on outgoing datagrams: DPLPMTUD probes must test
                # the path, not be silently IP-fragmented en route
                import socket as _socket
                sock = self._quic_transport.get_extra_info("socket")
                if sock.family == _socket.AF_INET6:
                    sock.setsockopt(_socket.IPPROTO_IPV6,
                                    _socket.IPV6_MTU_DISCOVER,
                                    _socket.IPV6_PMTUDISC_DO)
                else:
                    sock.setsockopt(_socket.IPPROTO_IP,
                                    _socket.IP_MTU_DISCOVER,
                                    _socket.IP_PMTUDISC_DO)
            except (OSError, AttributeError):
                pass                    # non-Linux / wrapped transport

            async def on_connection(stream, info):
                await self.handle_stream(stream, ConnInfo(
                    peername=info.get("peername"),
                    listener="quic:default",
                ))

            self.quic = QuicEndpoint(
                self._quic_transport, cert_pem, key_pem, on_connection,
                max_connections=int(cfg.get(
                    "listeners.quic.default.max_connections")),
                supervisor=self.supervisor)
            log.info("quic listener on udp %s:%d", host, self.quic_port)
        except Exception:
            log.exception("quic listener failed to start")

    def _start_ocsp(self) -> None:
        """OCSP stapling cache for the TLS listener (emqx_ocsp_cache
        analog); the staple hand-off itself is gated on runtime ssl
        support — the cache keeps a fresh validated response either
        way (`node.ocsp_cache.info()` on the health surface)."""
        cfg = self.config
        if not cfg.get("listeners.ssl.default.ocsp.enable") \
                or not cfg.get("listeners.ssl.default.enable"):
            return  # no TLS listener ⇒ nothing to staple for
        cert = (cfg.get("listeners.ssl.default.certfile") or "").strip()
        issuer = (cfg.get("listeners.ssl.default.cacertfile") or "").strip()
        if not cert or not issuer:
            log.warning("ocsp enabled but certfile/cacertfile missing")
            return
        try:
            from .transport.ocsp import OcspCache

            with open(cert, "rb") as f:
                cert_pem = f.read()
            with open(issuer, "rb") as f:
                issuer_pem = f.read()
            self.ocsp_cache = OcspCache(
                cert_pem, issuer_pem,
                responder_url=(cfg.get(
                    "listeners.ssl.default.ocsp.responder_url") or None),
                refresh_interval_s=cfg.get(
                    "listeners.ssl.default.ocsp.refresh_interval"),
                refresh_http_timeout_s=cfg.get(
                    "listeners.ssl.default.ocsp.refresh_http_timeout"),
                supervisor=self.supervisor,
            )
            self.ocsp_cache.start()
        except Exception:
            log.exception("ocsp cache failed to start")

    async def _start_gateways(self) -> None:
        from .gateway import GatewayManager

        self.gateways = GatewayManager(self)
        for name in ("stomp", "mqttsn", "coap", "exproto", "lwm2m"):
            if not self.config.get(f"gateway.{name}.enable"):
                continue
            conf = {"bind": self.config.get(f"gateway.{name}.bind")}
            if name == "mqttsn":
                conf["gateway_id"] = self.config.get(
                    "gateway.mqttsn.gateway_id")
            elif name == "exproto":
                conf["handler"] = self.config.get("gateway.exproto.handler")
                conf["adapter_listen"] = self.config.get(
                    "gateway.exproto.adapter_listen")
            if name in ("coap", "lwm2m"):
                psk_raw = self.config.get(f"gateway.{name}.dtls.psk")
                psk = {}
                for p in psk_raw.split(","):
                    p = p.strip()
                    if ":" not in p:
                        continue
                    ident, hexkey = p.split(":", 1)
                    try:
                        psk[ident.strip()] = bytes.fromhex(hexkey.strip())
                    except ValueError:
                        # one bad entry disables one identity, not the
                        # whole gateway
                        log.warning("gateway.%s.dtls.psk: bad hex key for "
                                    "identity %r; entry skipped",
                                    name, ident.strip())
                conf["dtls"] = {
                    "enable": self.config.get(
                        f"gateway.{name}.dtls.enable"),
                    "psk": psk,
                }
            try:
                await self.gateways.load(name, conf)
            except Exception:
                log.exception("gateway %s failed to start", name)

    async def _start_match_service(self) -> None:
        if not self.config.get("tpu.enable"):
            return
        from .broker.match_service import MatchService

        cfg = self.config
        seg_dir = ""
        if cfg.get("match.segments.enable"):
            seg_dir = cfg.get("match.segments.dir") or os.path.join(
                cfg.get("node.data_dir") or "data", "segments")
            if cfg.get("match.segments.xla_cache"):
                enable_xla_cache(os.path.join(seg_dir, "xla_cache"))
        try:
            self.match_service = MatchService(
                self.broker,
                metrics=self.observed.metrics,
                depth=min(cfg.get("tpu.max_levels"), 16),
                batch_window_s=cfg.get("tpu.batch_deadline"),
                max_batch=cfg.get("tpu.batch_size"),
                debounce_s=cfg.get("tpu.mirror_refresh_interval"),
                active_slots=cfg.get("tpu.active_slots"),
                max_matches=cfg.get("tpu.max_matches"),
                max_stale_deltas=cfg.get("tpu.max_stale_deltas"),
                bypass_rate=cfg.get("tpu.bypass_rate"),
                prefetch_timeout_s=cfg.get("tpu.prefetch_timeout"),
                table=cfg.get("tpu.table"),
                short_depth=cfg.get("tpu.short_depth"),
                split_min=cfg.get("tpu.split_min"),
                deadline=cfg.get("match.deadline.enable"),
                deadline_s=cfg.get("match.deadline_ms") / 1e3,
                pipeline=cfg.get("match.pipeline.enable"),
                pipeline_depth=cfg.get("match.pipeline.depth"),
                breaker_threshold=cfg.get("match.breaker.threshold"),
                breaker_probe_interval_s=cfg.get(
                    "match.breaker.probe_interval"),
                alarms=self.observed.alarms,
                olp=self.olp,
                segments=cfg.get("match.segments.enable"),
                segments_dir=seg_dir,
                compact_interval_s=cfg.get(
                    "match.segments.compact_interval"),
                compact_min_mutations=cfg.get(
                    "match.segments.compact_min_mutations"),
                dirty_threshold=cfg.get("match.segments.dirty_threshold"),
                prewarm=cfg.get("match.segments.prewarm"),
                backend=cfg.get("match.backend"),
                autotune=cfg.get("match.autotune.enable"),
                autotune_reps=cfg.get("match.autotune.reps"),
                multichip=cfg.get("match.multichip.enable"),
                multichip_tp=cfg.get("match.multichip.tp"),
                multichip_native=cfg.get("match.multichip.native"),
                multichip_ep=cfg.get("match.multichip.ep.enable"),
                multichip_ep_slack=cfg.get(
                    "match.multichip.ep.capacity_slack"),
                multichip_ep_micro=cfg.get(
                    "match.multichip.ep.micro_matches"),
                multichip_ep_compact=cfg.get(
                    "match.multichip.ep.compact"),
                multichip_degraded=cfg.get(
                    "match.multichip.degraded.enable"),
                multichip_degraded_threshold=cfg.get(
                    "match.multichip.degraded.fail_threshold"),
                multichip_ep_overflow_warn=cfg.get(
                    "match.multichip.ep.overflow_warn"),
                multichip_ep_autotune=cfg.get(
                    "match.multichip.ep.autotune.enable"),
                multichip_ep_grow_threshold=cfg.get(
                    "match.multichip.ep.autotune.grow_threshold"),
                multichip_ep_shrink_threshold=cfg.get(
                    "match.multichip.ep.autotune.shrink_threshold"),
                multichip_ep_max_cap_class=cfg.get(
                    "match.multichip.ep.autotune.max_cap_class"),
                multichip_balance_budget=cfg.get(
                    "match.multichip.ep.autotune.max_moved_roots"),
                readback_mode=cfg.get("match.readback.mode"),
                readback_auto_slack=cfg.get("match.readback.auto_slack"),
                hists=self.hists,
                flightrec=self.flightrec,
            )
            self.match_service.supervisor = self.supervisor
            await asyncio.wait_for(
                self.match_service.start(),
                timeout=cfg.get("tpu.start_timeout"),
            )
            self.broker.device_match = self.match_service.hint_routes
            self.rule_engine.attach_match_service(self.match_service)
        except (Exception, asyncio.TimeoutError):
            log.exception("TPU match service unavailable; host trie serves")
            self.match_service = None

    async def _start_fanout(self) -> None:
        if not self.config.get("broker.fanout.enable"):
            return
        from .broker.fanout import FanoutPipeline

        cfg = self.config
        self.fanout_pipeline = FanoutPipeline(
            self.broker,
            metrics=self.observed.metrics,
            match_service=self.match_service,
            max_batch=cfg.get("broker.fanout.max_batch"),
            min_batch=cfg.get("broker.fanout.min_batch"),
            window_s=cfg.get("broker.fanout.window"),
            adapt_window_s=cfg.get("broker.fanout.adapt_window"),
            bypass_rate=cfg.get("broker.fanout.bypass_rate"),
            queue_cap=cfg.get("broker.fanout.queue_cap"),
            shape_routes=cfg.get("broker.fanout.shape_routes"),
            shape_probe_s=cfg.get("broker.fanout.shape_probe"),
            supervisor=self.supervisor,
            olp=self.olp,
            hists=self.hists,
            e2e_per_leg_sample=cfg.get("obs.hist.e2e_per_leg_sample"),
            flightrec=self.flightrec,
        )
        await self.fanout_pipeline.start()
        self.broker.fanout = self.fanout_pipeline
        self.observed.stats.provide(
            "broker.fanout.depth", self.fanout_pipeline.depth)

    async def _start_mgmt(self) -> None:
        if not self.config.get("dashboard.enable"):
            return
        from .mgmt import HttpServer, MgmtApi, basic_auth_checker
        from .mgmt.dashboard import DashboardUsers

        data_dir = (self.config.get("node.data_dir") or "").strip()
        self.dashboard_users = DashboardUsers(
            os.path.join(data_dir, "dashboard_users.json")
            if data_dir else None
        )

        bind = self.config.get("dashboard.listen")
        host, _, port = bind.rpartition(":")
        auth = None
        if self.config.get("dashboard.auth") or self.config.get(
            "api_key.enable"
        ):
            basic = (
                basic_auth_checker(
                    self.config.get("api_key.key"),
                    self.config.get("api_key.secret"),
                )
                if self.config.get("api_key.enable") else None
            )
            dash = self.dashboard_users
            # dashboard.auth=false + api_key.enable=true means the
            # operator chose api-key-ONLY auth: login tokens must not
            # reopen the write surface
            bearer_ok = bool(self.config.get("dashboard.auth"))

            def auth(req):
                # dashboard bearer token (role gates writes: viewer is
                # read-only, except self-service logout / own-password
                # change) OR api-key basic auth when enabled
                hdr = req.headers.get("authorization", "")
                if hdr.startswith("Bearer ") and bearer_ok:
                    tok = hdr.removeprefix("Bearer ").strip()
                    write = req.method not in ("GET", "HEAD")
                    if req.path == "/api/v5/logout":
                        write = False
                    elif (req.path.startswith("/api/v5/users/")
                          and req.path.endswith("/change_pwd")):
                        who = req.path.removeprefix(
                            "/api/v5/users/").removesuffix("/change_pwd")
                        if dash.token_user(tok) == who:
                            write = False
                    return dash.check_token(tok, write=write)
                return basic(req) if basic is not None else False
        elif (host or "0.0.0.0") not in ("127.0.0.1", "localhost", "::1"):
            log.warning(
                "management API on %s without auth: any network peer can "
                "kick clients, publish, and mutate config", bind
            )
        self.mgmt_server = HttpServer(
            host or "0.0.0.0", int(port), auth=auth,
            auth_exempt=("/api/v5/status", "/api/v5/login",
                         "/", "/dashboard"),
        )
        self.mgmt = MgmtApi(self, self.mgmt_server)
        await self.mgmt_server.start()

    async def _start_cluster(self) -> None:
        if not self.config.get("cluster.enable"):
            return
        from .cluster import Cluster

        self.cluster = Cluster(
            self,
            listen=self.config.get("cluster.listen"),
            seeds=self.config.get("cluster.seeds"),
            cluster_name=self.config.get("cluster.name"),
        )
        self.cluster.HEARTBEAT_INTERVAL = self.config.get(
            "cluster.heartbeat_interval"
        )
        self.cluster.NODE_TIMEOUT = self.config.get("cluster.node_timeout")
        await self.cluster.start()

    async def _start_exhook(self) -> None:
        spec = (self.config.get("exhook.servers") or "").strip()
        if not spec:
            return
        from .exhook import ExHookManager, ServerSpec

        servers = []
        for part in spec.split(","):
            name, _, url = part.strip().partition("=")
            if not url:
                log.warning(
                    "exhook.servers entry %r ignored (expected name=host:port)",
                    part.strip(),
                )
                continue
            servers.append(
                ServerSpec(
                    name=name, url=url,
                    timeout=self.config.get("exhook.request_timeout"),
                    failure_action=self.config.get("exhook.failure_action"),
                )
            )
        if servers:
            self.exhook = ExHookManager(self, servers)
            await self.exhook.start()

    async def stop(self) -> None:
        self._running = False
        self.plugins.stop_all()
        if self.statsd is not None:
            await self.statsd.stop()
            self.statsd = None
        if self.telemetry is not None:
            await self.telemetry.stop()
            self.telemetry = None
        if getattr(self, "gateways", None) is not None:
            await self.gateways.stop_all()
        if self.ocsp_cache is not None:
            self.ocsp_cache.stop()
            self.ocsp_cache = None
        if self.quic is not None:
            self.quic.close()
            self.quic = None
        await self.bridges.stop_all()
        if self.fanout_pipeline is not None:
            # detach first so the drain-on-stop republishes (and any
            # in-flight channel offers) take the sync path
            self.broker.fanout = None
            await self.fanout_pipeline.stop()
            self.fanout_pipeline = None
        if self.match_service is not None:
            await self.match_service.stop()
            self.broker.device_match = None
            self.match_service = None
        if self.exhook is not None:
            await self.exhook.stop()
            self.exhook = None
        if self.cluster is not None:
            await self.cluster.stop()
            self.cluster = None
        if self.mgmt_server is not None:
            await self.mgmt_server.stop()
            self.mgmt_server = None
            self.mgmt = None
        # housekeeping must be gone BEFORE persistence.close(): a
        # sync_async still running _write in a worker thread would race
        # close()'s final sync/compact on the same WAL handle
        for job in self._jobs:
            job.cancel()
        if self._jobs:
            await asyncio.gather(*self._jobs, return_exceptions=True)
        self._jobs.clear()
        # sweep the supervision tree: any child not already stopped by
        # its subsystem's stop() goes down here, reverse boot order
        await self.supervisor.stop()
        if self.persistence is not None:
            self.persistence.close()
        # kick live connections BEFORE awaiting listener close: 3.12's
        # Server.wait_closed() blocks until every connection handler
        # returns, so the order matters.  _all_conns covers sockets that
        # never completed CONNECT (absent from self.connections).
        for conn in list(self._all_conns):
            self._kick_conn(conn, "node shutdown")
        # give connections a beat to flush their goodbyes
        await asyncio.sleep(0)
        await self.listeners.stop_all()
        if self.timer_wheel is not None:
            self.timer_wheel.close()

    async def _housekeeping(self) -> None:
        """Periodic jobs: delayed-publish firing, retained expiry, session
        expiry, banned-table cleanup ($SYS heartbeat lives in observe)."""
        interval = 1.0
        while self._running:
            await asyncio.sleep(interval)
            try:
                if self.timer_wheel is not None:
                    # aggregate wheel-resident timer gauge: main-loop
                    # wheel + every shard wheel (racy cross-thread int
                    # reads — a gauge, not an invariant)
                    conns = len(self.timer_wheel)
                    if self.shard_pool is not None:
                        conns += self.shard_pool.wheel_conns()
                    self.observed.metrics.set(
                        "broker.timer.wheel_conns", conns)
                if self.delayed is not None:
                    self.delayed.tick()
                if self.retainer is not None:
                    self.retainer.clean_expired()
                self.banned.clean_expired()
                # per-client keyed-state growth bounds (churn audit):
                # flapping windows and idle limiter bucket pairs are
                # swept here; admission feature rows evict themselves
                # inside score_tick (idle_expiry)
                now_mono = time.monotonic()
                if now_mono - self._last_idle_sweep >= 60.0:
                    self._last_idle_sweep = now_mono
                    self.flapping.sweep()
                    self.limiter.sweep_idle(600.0)
                self._expire_sessions()
                if self.quic is not None:
                    self.quic.sweep()
                if self.persistence is not None:
                    sync_iv = self.config.get(
                        "durable_storage.sync_interval"
                    )
                    if time.time() - self.persistence.last_sync >= sync_iv:
                        await self.persistence.sync_async()
            except Exception:
                log.exception("housekeeping job failed")

    def _expire_sessions(self) -> None:
        """MQTT session-expiry: drop sessions whose client stayed away past
        Session-Expiry-Interval (emqx_cm session GC)."""
        now = time.time()
        for cid, t in list(self._disconnected_at.items()):
            sess = self.broker.sessions.get(cid)
            if sess is None or self.cm.lookup_channel(cid) is not None:
                del self._disconnected_at[cid]
                continue
            if now - t >= sess.expiry_interval:
                self.broker.close_session(cid, discard=True)
                del self._disconnected_at[cid]

    # ------------------------------------------------------------------

    def quic_listener_info(self) -> list:
        """QUIC listener row(s) — ONE shape shared by node.info() and
        GET /api/v5/listeners (drift between the two was a review
        finding)."""
        if self.quic is None:
            return []
        conns = self.quic.live_conns()
        return [{
            "id": "quic:default", "type": "quic",
            "bind": f"udp:{self.quic_port}", "running": True,
            "current_connections": len(self.quic.streams),
            "handshakes": self.quic.handshakes,
            "dropped_initials": self.quic.dropped_initials,
            "retransmits": self.quic.retransmits,
            # recovery/path state rolled up over live connections: the
            # operator-facing view of RFC 9002 loss detection and
            # DPLPMTUD (fast_retransmits = ack-evidence losses healed
            # without a timer; mtu_validated_max = largest datagram
            # budget any live path proved)
            "fast_retransmits": sum(c.fast_retransmits for c in conns),
            "mtu_probes_sent": sum(c.mtu_probes_sent for c in conns),
            "mtu_validated_max": max(
                (c.mtu_validated for c in conns), default=1252),
        }]

    def info(self) -> dict:
        from . import __version__

        return {
            "node": self.node_name,
            "version": __version__,
            "uptime": time.time() - self.started_at,
            "connections": len(self.connections),
            "listeners": ([l.info() for l in self.listeners.all()]
                          + self.quic_listener_info()),
            "gateways": (self.gateways.list()
                         if self.gateways is not None else []),
            "bridges": len(self.bridges.list()),
            "rules": len(self.rule_engine.rules),
            "plugins": self.plugins.list(),
            "auth": {"authenticators": len(self._auth_confs),
                     "sources": len(self._authz_confs),
                     "attached": self.access_control is not None},
            "topic_metrics": len(self.topic_metrics.topics()),
            "cluster_peers": sorted(self.cluster.peers)
            if self.cluster is not None else [],
            "tpu_match": (self.match_service.info()
                          if self.match_service is not None else None),
            "fanout": (self.fanout_pipeline.info()
                       if self.fanout_pipeline is not None else None),
            "supervisor": self.supervisor.info(),
            "flightrec": self.flightrec.info(),
            "admission": (self.admission.info()
                          if self.admission is not None else None),
            **self.broker.stats(),
        }

    # -- stage-level latency observatory (observe/hist.py) -------------

    def hist_sets(self) -> List[Any]:
        """Every live plane's histogram set: the main set (also written
        by the match worker stages — one writer per histogram) plus one
        per shard loop.  Empty when ``obs.hist.enable`` is off."""
        if self.hists is None:
            return []
        sets = [self.hists]
        pool = self.shard_pool
        if pool is not None:
            sets.extend(s.hists for s in pool.shards
                        if s.hists is not None)
        return sets

    def hist_percentiles(self) -> Dict[str, Dict[str, float]]:
        """Merged cross-plane percentiles — the one latency definition
        every export surface ($SYS, REST/CLI, statsd, bench) reads."""
        from .observe.hist import HistSet

        return HistSet.percentiles(self.hist_sets())
