"""Config-4 device stage: ``$share`` group member selection on-chip.

The reference picks one member per shared group per message on the host
(``emqx_shared_sub:dispatch`` strategies, SURVEY.md §2.1).  At BASELINE
config-4 scale the candidate sets live in the TP-sharded subscriber
bitmap, so selection runs where the bits already are:

* inputs (inside the same mesh as the fan-out step): the per-topic
  subscriber bitmap (B, W) sharded ``(dp, tp)``, per-group membership
  masks (G, W) sharded ``(None, tp)``, and a per-topic selector hash
  (the ``hash_topic``/``random`` strategy seed) sharded ``(dp,)``;
* per (topic, group): candidates = row ∧ mask, member counts psum over
  ``tp``, the hash picks an ordinal, and the one shard holding that
  ordinal extracts the subscriber id (cumsum-popcount word walk + 32-way
  bit probe) — combined across ``tp`` with a max-reduce.

Output: (B, G) int32 subscriber id, -1 where the group has no member
with a matching subscription — exactly the host strategy's pick for
``hash_topic``-style selection, provable in parity tests.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ._shard_compat import shard_map

__all__ = ["build_shared_selector", "make_group_masks", "host_pick"]


def make_group_masks(groups, n_subs: int, words: int) -> np.ndarray:
    """(G, words) uint32 membership masks from ``groups``: iterable of
    iterables of subscriber ids."""
    g = len(groups)
    bm = np.zeros((g, words), np.uint32)
    for gi, members in enumerate(groups):
        for sub in members:
            if not 0 <= sub < n_subs:
                raise ValueError(f"subscriber id {sub} out of range")
            bm[gi, sub >> 5] |= np.uint32(1) << np.uint32(sub & 31)
    return bm


def host_pick(row_bitmap: np.ndarray, mask: np.ndarray, sel_hash: int) -> int:
    """Reference pick: the ``(hash % n_members)``-th live member in
    subscriber-id order (-1 when empty) — the parity oracle."""
    cand = row_bitmap & mask
    ids = []
    for w in range(len(cand)):
        v = int(cand[w])
        while v:
            b = (v & -v).bit_length() - 1
            ids.append(w * 32 + b)
            v &= v - 1
    if not ids:
        return -1
    return ids[sel_hash % len(ids)]


def _nth_set_bit(word, n):
    """n-th (0-based) set bit index of a uint32 via 32-step probe;
    word/n are (..,) arrays.  Caller guarantees n < popcount(word)."""
    idx = jnp.full(word.shape, -1, jnp.int32)
    seen = jnp.zeros(word.shape, jnp.int32)
    for b in range(32):
        bit = (word >> jnp.uint32(b)) & jnp.uint32(1)
        hit = (bit == 1) & (seen == n) & (idx < 0)
        idx = jnp.where(hit, b, idx)
        seen = seen + bit.astype(jnp.int32)
    return idx


def build_shared_selector(mesh: Mesh):
    """Returns jitted ``select(bitmap, masks, sel_hash) -> (B, G) int32``.

    ``bitmap`` (B, W) uint32 sharded (dp, tp); ``masks`` (G, W) uint32
    sharded (None, tp); ``sel_hash`` (B,) int32 sharded (dp,)."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp", "tp"), P(None, "tp"), P("dp")),
        out_specs=P("dp", None),
        check_vma=False,
    )
    def select(bitmap, masks, sel_hash):
        # candidates per (topic, group): (Bl, G, Wl)
        cand = bitmap[:, None, :] & masks[None, :, :]
        wc = jax.lax.population_count(cand).astype(jnp.int32)
        count_l = jnp.sum(wc, axis=-1)                      # (Bl, G)
        total = jax.lax.psum(count_l, "tp")                 # (Bl, G)
        # exclusive prefix of counts across tp shards
        tp_idx = jax.lax.axis_index("tp")
        ntp = mesh.shape["tp"]
        all_counts = jax.lax.all_gather(count_l, "tp")      # (ntp, Bl, G)
        before = jnp.sum(
            jnp.where(jnp.arange(ntp)[:, None, None] < tp_idx,
                      all_counts, 0),
            axis=0,
        )                                                   # (Bl, G)
        sel = sel_hash[:, None] % jnp.maximum(total, 1)     # (Bl, G)
        local_ord = sel - before
        mine = (local_ord >= 0) & (local_ord < count_l) & (total > 0)
        # word holding the local ordinal: cumsum-popcount walk
        cum = jnp.cumsum(wc, axis=-1) - wc                  # exclusive (Bl,G,Wl)
        o = jnp.where(mine, local_ord, 0)[:, :, None]
        in_word = (o >= cum) & (o < cum + wc)
        word_idx = jnp.argmax(in_word, axis=-1)             # (Bl, G)
        word = jnp.take_along_axis(cand, word_idx[:, :, None],
                                   axis=-1)[:, :, 0]
        rem = (o[:, :, 0] - jnp.take_along_axis(
            cum, word_idx[:, :, None], axis=-1)[:, :, 0])
        bit = _nth_set_bit(word, rem)                       # (Bl, G)
        Wl = bitmap.shape[1]
        sub_id = (tp_idx * Wl + word_idx) * 32 + bit
        picked = jnp.where(mine, sub_id, -1)
        # exactly one shard claims each (topic, group) with members
        return jax.lax.pmax(picked, "tp")

    return jax.jit(select)
