"""Multichip serve backend: the match TABLE sharded by topic-prefix
over the mesh, serving real publish traffic (ISSUE 15).

Every 8-device configuration in MULTICHIP_r05 passed dry runs with
parity checks, but the serving path was capped at one chip's table.
This module is the on-device analog of the reference's cluster routing
(PAPER.md: ekka/mria replicated route tables): instead of replicating
the NFA everywhere and sharding the *subscriber bitmap*
(:func:`~emqx_tpu.parallel.sharded_match.build_sharded_matcher_compact`),
the **table itself shards** — each ``tp`` shard owns the filters whose
root token hashes to it, so 8 chips hold 8× the filters:

* ``dp`` — publish-batch rows (each chip matches its slice, zero comms);
* ``tp`` — table shards; the batch is **fanned** (replicated) over this
  axis and every shard walks its OWN subtable;
* per-shard matches map through a local→service accept-id table and
  leave the mesh as the **dense compact contract**
  (:class:`~emqx_tpu.parallel.sharded_match.CompactFanoutResult`):
  per-row id segments in disjoint per-shard order, concat-no-dedup,
  decoded by the same :func:`decode_compact_rows` the bitmap
  compaction path uses — what crosses the wire is proportional to
  MATCHES, never to table width, so the ring/ICI traffic is dense end
  to end (ROADMAP dispatch-tax residual (d));
* per-row truncation/active-set spills are ``psum``'d over ``tp``
  (the fail-open set — the host re-runs exactly those rows on the CPU
  trie, the single-chip spill contract unchanged).

Maintenance rides the existing drain/apply cycle: the service's
``_table_add``/``_table_del`` seams note filter mutations here, the
sync loop applies them off the event loop (per-shard host subtables →
``flush()`` deltas → scatters into the stacked device arrays, full
restack only on a resize — the DeviceNfa discipline), and a compaction
swap rebuilds the whole partition from the fresh aid space.

Failure semantics: a dead (``kill_shard``) or fault-injected
(``match.shard`` point) shard raises at dispatch — the affected batch
fails over to the CPU trie through the serve plane's existing
device-failure paths (breaker strike in deadline mode, probe recovery,
stale-slot discards stay strike-free), exactly like any other device
failure.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import zlib
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import faultinject as _fi
from ._shard_compat import shard_map
from .sharded_match import CompactFanoutResult, decode_compact_rows

log = logging.getLogger(__name__)

__all__ = ["MultichipMatcher", "ShardDead", "build_multichip_step",
           "serve_mesh_shape", "shard_of_filter"]


class ShardDead(RuntimeError):
    """A mesh shard is down: the dispatch cannot produce a trustworthy
    answer for ANY row (every shard owns part of the table).  Treated
    by the serve plane as a device failure — CPU trie serves the
    batch, breaker accounting applies."""


def serve_mesh_shape(n_devices: int, tp: int = 0) -> Dict[str, int]:
    """Mesh factorization for the serve backend: ``tp`` table shards
    (0 = the widest pow2 ≤ 4 that divides the device count — the
    :func:`~emqx_tpu.parallel.mesh.pick_shape` default), rest ``dp``
    batch rows."""
    from .mesh import pick_shape

    return pick_shape(n_devices, tp if tp > 0 else None)


def shard_of_filter(flt: str, tp: int) -> int:
    """Topic-prefix partition: a filter lives on the shard its ROOT
    token hashes to.  Wildcard roots (``+``/``#``) hash their literal
    token — ownership is arbitrary for them (every topic visits every
    shard), it only has to be deterministic."""
    root = flt.split("/", 1)[0]
    return zlib.crc32(root.encode("utf-8")) % tp


@partial(jax.jit, donate_argnums=(0,))
def _scatter_stacked(tab, tvec, idx, rows):
    """stacked[t, idx] = rows, in place (donated) — the per-shard
    delta scatter into the (tp, ...) stacked table.  Callers hold the
    matcher lock across the scatter AND every dispatch-side read of
    ``_arrs``, so a donated-away buffer is never re-dispatched."""
    return tab.at[tvec, idx].set(rows, mode="drop", unique_indices=False)


def build_multichip_step(mesh, active_slots: int = 16,
                         max_matches: int = 32):
    """Return a jitted ``step(words, lens, is_sys, node_stk, edge_stk,
    seeds_stk, aid_stk) -> CompactFanoutResult``.

    Input layouts: batch arrays sharded over ``dp`` (replicated —
    *fanned* — over ``tp``); the stacked per-shard tables
    ``node_stk (tp, S, 4)``, ``edge_stk (tp, Hb, slots·4)``,
    ``seeds_stk (tp, 2)`` and the local→service accept-id map
    ``aid_stk (tp, A)`` sharded over ``tp``.  Output ``ids`` is the
    dense compact contract: (B, tp·K) service accept ids, -1 padded,
    per-shard segments disjoint by partition construction; ``counts``
    (B, tp); ``overflow`` (B, tp) per-segment truncation; the spill
    vectors psum over ``tp``."""
    from ..ops.match_kernel import nfa_match

    K = max_matches

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P("dp", None),        # words
            P("dp"),              # lens
            P("dp"),              # is_sys
            P("tp", None, None),  # node_stk
            P("tp", None, None),  # edge_stk
            P("tp", None),        # seeds_stk
            P("tp", None),        # aid_stk
        ),
        out_specs=CompactFanoutResult(
            ids=P("dp", "tp"),
            counts=P("dp", "tp"),
            overflow=P("dp", "tp"),
            n_matches=P("dp"),
            active_overflow=P("dp"),
            match_overflow=P("dp"),
        ),
        check_vma=False,
    )
    def step(words, lens, is_sys, node_stk, edge_stk, seeds_stk, aid_stk):
        node, edge, seeds, amap = (
            node_stk[0], edge_stk[0], seeds_stk[0], aid_stk[0])
        res = nfa_match(
            words, lens, is_sys, node, edge, seeds,
            active_slots=active_slots, max_matches=K,
        )
        m = res.matches                                  # (Bl, K) local
        gids = jnp.where(m >= 0, amap[jnp.maximum(m, 0)], -1)
        return CompactFanoutResult(
            ids=gids,
            counts=jnp.minimum(res.n_matches, K)[:, None],
            overflow=res.match_overflow[:, None],
            n_matches=jax.lax.psum(res.n_matches, "tp"),
            active_overflow=jax.lax.psum(res.active_overflow, "tp"),
            match_overflow=jax.lax.psum(res.match_overflow, "tp"),
        )

    return jax.jit(step)


class MultichipMatcher:
    """Host side of the multichip serve backend: per-shard subtables
    (shared vocab, one encode serves every shard), the stacked device
    twin, and the mesh-compiled step cache.

    Threading model (the MatchService discipline): ``note_add``/
    ``note_del``/``rebuild`` run on the event loop and only append to a
    pending op list; ``apply_pending`` runs in the sync loop's worker
    thread and is the single writer of the subtables + stacked arrays;
    ``dispatch`` runs in the serve plane's encode worker thread and
    captures one consistent (arrays, aid map) snapshot under the lock.
    """

    MANIFEST_VERSION = 1
    #: serve-plane dispatch routing marker (MatchService checks this
    #: instead of importing the class on its hot path)
    is_multichip = True

    def __init__(
        self,
        depth: int = 8,
        tp: int = 0,
        devices: Optional[Sequence[Any]] = None,
        active_slots: int = 16,
        max_matches: int = 32,
        metrics: Any = None,
        kernel_cache: Any = None,
    ) -> None:
        from .mesh import make_mesh

        devs = list(devices if devices is not None else jax.devices())
        shape = serve_mesh_shape(len(devs), tp)
        self.mesh = make_mesh(shape, devs)
        self.dp = shape["dp"]
        self.tp = shape["tp"]
        self.n_devices = self.dp * self.tp
        self.depth = depth
        self.active_slots = active_slots
        self.max_matches = max_matches
        self.metrics = metrics
        self.kernel_cache = kernel_cache
        if kernel_cache is not None:
            # mesh-keyed executables compile through the shared cache
            # (CompileMiss semantics, zero-compile prewarm spies)
            kernel_cache.mesh_lower = self._lower_step

        self.vocab: Dict[str, int] = {}
        self._subs: List[Any] = []
        self._aid_maps: List[np.ndarray] = []
        self._reset_subs()

        self._lock = threading.Lock()
        self._pending: List[Tuple[str, str, int]] = []  # (op, flt, aid)
        self._rebuild_pairs: Optional[List[Tuple[str, int]]] = None
        self._restack_due = False      # segment restore awaiting upload
        self._arrs: Optional[Tuple[Any, Any, Any, Any]] = None
        self._stacked_shape: Optional[Tuple[int, int, int]] = None
        self._steps: Dict[Tuple[int, int], Any] = {}
        self._dead: set = set()
        self.gen = 0                    # bumped on every restack
        self.dispatches = 0
        self.failovers = 0
        self.applies = 0
        self.restacks = 0
        self.seeded_from_segments = False
        self._persist_due = False
        if metrics is not None:
            metrics.set("tpu.match.shard_devices", self.n_devices)

    # ------------------------------------------------------------------
    # partition maintenance (event loop: enqueue; worker thread: apply)
    # ------------------------------------------------------------------

    def _reset_subs(self) -> None:
        from ..ops.incremental import IncrementalNfa

        self.vocab = {}
        self._subs = []
        self._aid_maps = []
        for _ in range(self.tp):
            sub = IncrementalNfa(depth=self.depth)
            # one vocab dict shared by every subtable: a single encode
            # pass serves all shards (interning appends consistently)
            sub.vocab = self.vocab
            self._subs.append(sub)
            self._aid_maps.append(np.full(64, -1, np.int32))

    def note_add(self, flt: str, service_aid: int) -> None:
        with self._lock:
            self._pending.append(("add", flt, service_aid))

    def note_del(self, flt: str) -> None:
        with self._lock:
            self._pending.append(("del", flt, -1))

    def rebuild(self, pairs: List[Tuple[str, int]]) -> None:
        """Full repartition (cold start, compaction swap — the service
        aid space was reassigned wholesale).  Cheap on the loop: the
        build itself happens at the next ``apply_pending``; until then
        ``ready`` is False and the single-chip path serves."""
        with self._lock:
            self._rebuild_pairs = list(pairs)
            self._pending = []
            self._restack_due = False
            self._arrs = None
            self._steps = {}

    @property
    def ready(self) -> bool:
        return self._arrs is not None

    @property
    def dirty(self) -> bool:
        return (bool(self._pending) or self._rebuild_pairs is not None
                or self._restack_due)

    def _host_add(self, flt: str, service_aid: int) -> None:
        t = shard_of_filter(flt, self.tp)
        sub = self._subs[t]
        sub.add(flt)
        laid = sub.aid_of(flt)
        amap = self._aid_maps[t]
        if laid >= len(amap):
            grown = np.full(max(2 * len(amap), laid + 1), -1, np.int32)
            grown[:len(amap)] = amap
            amap = self._aid_maps[t] = grown
        amap[laid] = service_aid

    def _host_del(self, flt: str) -> None:
        t = shard_of_filter(flt, self.tp)
        sub = self._subs[t]
        laid = sub.aid_of(flt)
        if laid < 0:
            return
        self._aid_maps[t][laid] = -1
        sub.remove(flt)

    def apply_pending(self) -> bool:
        """WORKER-THREAD step (the sync loop's ``to_thread`` hop):
        drain the queued mutations into the per-shard subtables, then
        ship the result — per-shard ``flush()`` deltas scatter into the
        stacked arrays in place; any resize/repartition restacks (the
        DeviceNfa full-upload analog).  Returns True when the device
        state changed."""
        with self._lock:
            ops, self._pending = self._pending, []
            rebuild, self._rebuild_pairs = self._rebuild_pairs, None
            restack_due, self._restack_due = self._restack_due, False
        if rebuild is not None:
            self._reset_subs()
            for flt, aid in rebuild:
                self._host_add(flt, aid)
            # notes enqueued AFTER the rebuild request (rebuild()
            # clears the pending log, so every drained op postdates
            # it) apply on top — dropping them would serve a partition
            # missing live mutations
            for op, flt, aid in ops:
                if op == "add":
                    self._host_add(flt, aid)
                else:
                    self._host_del(flt)
            for sub in self._subs:
                sub.flush()     # clear dirty sets; restack ships all
            self._restack()
            self._persist_due = True
            return True
        if not ops:
            if self._arrs is None and restack_due:
                # segment restore: the subtables are populated but the
                # stacked device twin was never shipped
                self._restack()
                return True
            return False
        for op, flt, aid in ops:
            if op == "add":
                self._host_add(flt, aid)
            else:
                self._host_del(flt)
        deltas = [sub.flush() for sub in self._subs]
        shape = self._required_shape()
        if (self._arrs is None or self._stacked_shape != shape
                or any(d.resized for d in deltas)):
            self._restack()
            return True
        from ..ops.device_table import _chunks

        # the scatters DONATE the stacked buffers: the lock must span
        # the whole read-modify-publish so a concurrent dispatch never
        # captures a donated-away array
        with self._lock:
            node_stk, edge_stk, seeds_stk, _ = self._arrs
            for t, d in enumerate(deltas):
                if d.empty:
                    continue
                for idx, rows in _chunks(d.state_idx, d.state_rows):
                    node_stk = _scatter_stacked(
                        node_stk, jnp.full(idx.shape, t, jnp.int32),
                        jnp.asarray(idx), jnp.asarray(rows))
                for idx, rows in _chunks(d.bucket_idx, d.bucket_rows):
                    edge_stk = _scatter_stacked(
                        edge_stk, jnp.full(idx.shape, t, jnp.int32),
                        jnp.asarray(idx), jnp.asarray(rows))
            aid_stk = jnp.asarray(self._stacked_aid_maps(shape[2]))
            self._arrs = (node_stk, edge_stk, seeds_stk, aid_stk)
        self.applies += 1
        return True

    def _required_shape(self) -> Tuple[int, int, int]:
        """Common stacked (S, Hb, A_cap): node tables pad (states index
        directly — pad rows are unreachable), edge tables must SHARE a
        real bucket count (lookups hash modulo Hb), aid maps pad."""
        smax = max(sub.S for sub in self._subs)
        hbmax = max(sub.Hb for sub in self._subs)
        acap = 64
        for amap in self._aid_maps:
            while acap < len(amap):
                acap *= 2
        return smax, hbmax, acap

    def _stacked_aid_maps(self, acap: int) -> np.ndarray:
        out = np.full((self.tp, acap), -1, np.int32)
        for t, amap in enumerate(self._aid_maps):
            out[t, :len(amap)] = amap
        return out

    def _restack(self) -> None:
        """Full re-upload of the stacked per-shard tables.  Smaller
        shards grow their edge table to the common Hb (hash-correct —
        a padded edge table would probe modulo the wrong size), node
        tables pad with inert rows."""
        hbmax = max(sub.Hb for sub in self._subs)
        for sub in self._subs:
            while sub.Hb < hbmax:
                sub._grow_edges()
            sub.flush()         # growth marked dirty; the restack ships all
        shape = self._required_shape()
        smax, hbmax, acap = shape
        nodes = []
        for sub in self._subs:
            tab = np.full((smax, 4), -1, np.int32)
            tab[:, 3] = 0
            tab[:sub.S] = sub.node_tab
            nodes.append(tab)
        node_stk = jnp.asarray(np.stack(nodes))
        edge_stk = jnp.asarray(np.stack(
            [sub.edge_tab for sub in self._subs]))
        seeds_stk = jnp.asarray(np.stack(
            [sub.seeds for sub in self._subs]))
        aid_stk = jnp.asarray(self._stacked_aid_maps(acap))
        with self._lock:
            self._arrs = (node_stk, edge_stk, seeds_stk, aid_stk)
            self._stacked_shape = shape
        self.gen += 1
        self.applies += 1
        self.restacks += 1
        if self.metrics is not None:
            self.metrics.set("tpu.match.shard_restacks", self.restacks)

    # ------------------------------------------------------------------
    # serving (encode worker thread)
    # ------------------------------------------------------------------

    def encode(self, topics: Sequence[str], batch: int,
               depth: Optional[int] = None):
        """Encode against the SHARED shard vocab (one pass serves every
        shard) — the service's table vocab assigns different word ids,
        so multichip-routed groups must encode here."""
        from ..ops.encode import encode_batch

        return encode_batch(self, topics, batch=batch, depth=depth)

    def kill_shard(self, t: int) -> None:
        """Chaos surface: mark shard ``t`` dead.  Every subsequent
        dispatch raises :class:`ShardDead` until ``revive_shard`` —
        the whole table is partition-resident, so no shard can answer
        alone."""
        self._dead.add(int(t))

    def revive_shard(self, t: int) -> None:
        self._dead.discard(int(t))

    def _gate(self) -> None:
        if self._dead:
            self._note_failover()
            raise ShardDead(f"mesh shard(s) {sorted(self._dead)} dead")
        if _fi._injector is not None:
            act = _fi._injector.act("match.shard")
            if act == "raise":
                self._note_failover()
                raise _fi.InjectedFault("match.shard")
            if act == "delay":
                # sync seam (worker thread): a plain blocking sleep,
                # the match.compile idiom
                import time

                time.sleep(_fi._injector.last_delay)

    def _note_failover(self) -> None:
        self.failovers += 1
        if self.metrics is not None:
            self.metrics.inc("tpu.match.shard_failover")

    def dispatch(self, enc, *, block_compile: bool = True):
        """One mesh dispatch of an already-encoded batch; returns the
        lazy :class:`CompactFanoutResult` handle (readback blocks
        later, outside any lock).  Raises :class:`ShardDead` /
        :class:`~emqx_tpu.faultinject.InjectedFault` at the
        ``match.shard`` seam, :class:`CompileMiss` on a cold mesh
        shape when a kernel cache is attached."""
        self._gate()
        words, lens, is_sys = enc
        step = self._step_for(
            (int(words.shape[0]), int(words.shape[1])),
            block_compile=block_compile)
        with self._lock:
            if self._arrs is None:
                raise RuntimeError("multichip mirror not synced yet")
            res = step(jnp.asarray(words), jnp.asarray(lens),
                       jnp.asarray(is_sys), *self._arrs)
        self.dispatches += 1
        if self.metrics is not None:
            self.metrics.inc("tpu.match.shard_dispatches")
        return res

    def readback(self, res, n: int):
        """Block on the dense compact readback and decode to per-topic
        SERVICE accept-id rows: per-shard segments concatenate (the
        partition makes them disjoint — no dedup), rows flagged by the
        psum'd spill vectors go back to the host tables.  Returns
        ``(rows, spilled row indices, d2h bytes)``."""
        ids, counts, nm, ao, mo = jax.device_get(
            (res.ids, res.counts, res.n_matches,
             res.active_overflow, res.match_overflow))
        rows = decode_compact_rows(ids, counts, self.max_matches)[:n]
        out = [[int(a) for a in row if a >= 0] for row in rows]
        sp = (ao > 0) | (mo > 0)
        nbytes = 4 * int(ids.size + counts.size + nm.size
                         + ao.size + mo.size)
        return out, np.flatnonzero(sp[:n]).tolist(), nbytes

    def _step_for(self, batch_shape: Tuple[int, int], *,
                  block_compile: bool = True):
        kc = self.kernel_cache
        if kc is not None and self._stacked_shape is not None:
            smax, hbmax, acap = self._stacked_shape
            return kc.executable(
                batch_shape, smax, hbmax,
                active_slots=self.active_slots,
                max_matches=self.max_matches,
                compact_output=True, flat_cap=0,
                mesh=(self.dp, self.tp, acap),
                block=block_compile,
            )
        key = (int(batch_shape[0]), int(batch_shape[1]))
        fn = self._steps.get(key)
        if fn is None:
            fn = self._steps[key] = build_multichip_step(
                self.mesh, self.active_slots, self.max_matches)
        return fn

    def _lower_step(self, key):
        """Mesh half of the kernel cache's ``_lower``: AOT-compile the
        shard_map step for one (B, D, S, Hb, ..., (dp, tp, acap)) key
        (proven on the CPU mesh — jit(shard_map).lower(
        ShapeDtypeStruct...) works)."""
        from ..ops.compiler import BUCKET_SLOTS

        b, d, s, hb = key[0], key[1], key[2], key[3]
        acap = key[10][2]
        step = build_multichip_step(self.mesh, key[4], key[5])
        sd = jax.ShapeDtypeStruct
        i32 = jnp.int32
        return step.lower(
            sd((b, d), i32), sd((b,), i32), sd((b,), jnp.bool_),
            sd((self.tp, s, 4), i32),
            sd((self.tp, hb, BUCKET_SLOTS * 4), i32),
            sd((self.tp, 2), i32),
            sd((self.tp, acap), i32),
        ).compile()

    def warm(self, batches=(64,), depths=None) -> None:
        """Pre-pay the mesh step compiles for the serve shapes (the
        service ``_warm`` twin); no-op until the first apply."""
        if self._arrs is None:
            return
        for b in batches:
            for d in (depths or (self.depth,)):
                enc = self.encode([], batch=b, depth=d)
                res = self.dispatch(enc)
                self.readback(res, 0)

    # ------------------------------------------------------------------
    # per-shard segment persistence (opt-in via match.segments.enable)
    # ------------------------------------------------------------------

    @staticmethod
    def _seg_dir(segments_dir: str) -> str:
        return os.path.join(segments_dir, "multichip")

    def save_segments(self, segments_dir: str, epoch: int) -> None:
        """WORKER-THREAD step: persist every shard subtable (the
        existing segment format — trie relation, shared vocab verbatim)
        plus a checksummed manifest carrying the service-table epoch
        and the local→service aid maps.  Cold start seeds from these
        iff the epoch still matches (the ``_seg_join_seed`` idiom)."""
        from ..storage.segments import save_segment

        d = self._seg_dir(segments_dir)
        os.makedirs(d, exist_ok=True)
        for t, sub in enumerate(self._subs):
            save_segment(os.path.join(d, f"shard{t}.seg.npz"), sub,
                         deep={}, routing_aids=set(),
                         filters=sub.filters())
        maps = {f"m{t}": amap for t, amap in enumerate(self._aid_maps)}
        meta = {"version": self.MANIFEST_VERSION, "epoch": int(epoch),
                "tp": self.tp, "depth": self.depth}
        digest = self._manifest_checksum(meta, maps)
        np.savez(os.path.join(d, "aid_maps.npz"), **maps)
        # the manifest lands LAST (atomic replace = the commit point):
        # a crash mid-save leaves either the old manifest or none
        tmp = os.path.join(d, "manifest.json.tmp")
        with open(tmp, "w") as f:
            json.dump({**meta, "checksum": digest}, f, sort_keys=True)
        os.replace(tmp, os.path.join(d, "manifest.json"))
        self._persist_due = False

    @staticmethod
    def _manifest_checksum(meta: dict, maps: Dict[str, np.ndarray]) -> str:
        import hashlib

        h = hashlib.sha1(json.dumps(meta, sort_keys=True).encode())
        for k in sorted(maps):
            h.update(k.encode())
            h.update(np.ascontiguousarray(maps[k]).tobytes())
        return h.hexdigest()

    def load_segments(self, segments_dir: str, expect_epoch: int) -> bool:
        """Cold start: restore the shard partition from the persisted
        per-shard segments iff the manifest's service epoch matches the
        just-restored main table (no drift since the save) — else the
        caller rebuilds the partition from the live service state.
        Returns True when seeded."""
        from ..storage.segments import load_segment, restore_incremental

        d = self._seg_dir(segments_dir)
        try:
            with open(os.path.join(d, "manifest.json")) as f:
                meta = json.load(f)
            if meta.get("version") != self.MANIFEST_VERSION \
                    or meta.get("tp") != self.tp \
                    or meta.get("depth") != self.depth \
                    or meta.get("epoch") != int(expect_epoch):
                return False
            npz = np.load(os.path.join(d, "aid_maps.npz"))
            maps = {k: np.asarray(npz[k], np.int32) for k in npz.files}
            want = meta.get("checksum")
            meta_core = {k: meta[k] for k in
                         ("version", "epoch", "tp", "depth")}
            if want != self._manifest_checksum(meta_core, maps):
                log.warning("multichip manifest checksum mismatch; "
                            "repartition serves")
                return False
            subs = []
            for t in range(self.tp):
                seg = load_segment(os.path.join(d, f"shard{t}.seg.npz"))
                if seg.kind != "state" or seg.depth != self.depth:
                    return False
                subs.append(restore_incremental(seg))
        except FileNotFoundError:
            return False
        except Exception:
            log.warning("multichip segment load failed; repartition "
                        "serves", exc_info=True)
            return False
        # every shard persisted the SAME shared vocab — rebind them to
        # one dict instance so future interning stays consistent
        v0 = subs[0].vocab
        for sub in subs[1:]:
            if sub.vocab != v0:
                log.warning("multichip shard vocabs diverged; "
                            "repartition serves")
                return False
            sub.vocab = v0
        with self._lock:
            self.vocab = v0
            self._subs = subs
            self._aid_maps = [maps.get(f"m{t}",
                                       np.full(64, -1, np.int32))
                              for t in range(self.tp)]
            self._pending = []
            self._rebuild_pairs = None
            self._restack_due = True
            self._arrs = None
        self.seeded_from_segments = True
        return True

    def info(self) -> dict:
        return {
            "devices": self.n_devices,
            "mesh": {"dp": self.dp, "tp": self.tp},
            "ready": self.ready,
            "gen": self.gen,
            "dispatches": self.dispatches,
            "failovers": self.failovers,
            "applies": self.applies,
            "restacks": self.restacks,
            "dead_shards": sorted(self._dead),
            "shard_filters": [sub.n_filters for sub in self._subs],
            "seeded_from_segments": self.seeded_from_segments,
        }
