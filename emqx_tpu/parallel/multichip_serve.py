"""Multichip serve backend: the match TABLE sharded by topic-prefix
over the mesh, serving real publish traffic (ISSUE 15, extended to the
100M-filter regime in ISSUE 16).

Every 8-device configuration in MULTICHIP_r05 passed dry runs with
parity checks, but the serving path was capped at one chip's table.
This module is the on-device analog of the reference's cluster routing
(PAPER.md: ekka/mria replicated route tables): instead of replicating
the NFA everywhere and sharding the *subscriber bitmap*
(:func:`~emqx_tpu.parallel.sharded_match.build_sharded_matcher_compact`),
the **table itself shards** — each ``tp`` shard owns the filters whose
root token hashes to it, so 8 chips hold 8× the filters:

* ``dp`` — publish-batch rows (each chip matches its slice, zero comms);
* ``tp`` — table shards.  In the default **replicated** mode the batch
  is fanned over this axis and every shard walks its OWN subtable; in
  **EP-routed** mode (``match.multichip.ep.enable``, the
  ``prefix_ep.py`` dryrun promoted to serving) each row is bucketed by
  its ROOT-token owner and ``all_to_all``-routed only to the one shard
  that can match it — per-shard batch width drops from ``B/dp`` to
  ``slack·B/(dp·tp)`` for literal-rooted tables, and ICI traffic with
  it.  Bucket overflow (a hot root skewing one owner) fails open to
  the CPU trie exactly like the dead-shard path;
* **wildcard-root micro-table** — ``+``/``#``-first filters would
  crc32-hash to one arbitrary shard and break single-owner routing;
  they live instead in a small table replicated to every device and
  merged into the owning shard's answer segment (shard 0's in
  replicated mode), so EP answers stay complete and a hot wildcard
  set can't skew one shard;
* per-shard matches map through a local→service accept-id table and
  leave the mesh as the **dense compact contract**
  (:class:`~emqx_tpu.parallel.sharded_match.CompactFanoutResult`):
  per-row id segments in disjoint per-shard order, concat-no-dedup,
  decoded by the same :func:`decode_compact_rows` the bitmap
  compaction path uses — what crosses the wire is proportional to
  MATCHES, never to table width (ROADMAP dispatch-tax residual (d));
* per-row truncation/active-set/bucket-overflow spills are ``psum``'d
  over ``tp`` (the fail-open set — the host re-runs exactly those rows
  on the CPU trie, the single-chip spill contract unchanged).

Shard subtables are **native** (``native/nfa.cpp``) when the toolchain
built the .so — per-shard capacity then matches the single-chip native
table (10M filters, BENCH_r03/r05), putting ``tp × 10M`` within one
mesh.  Every subtable (and the micro-table) interns the SAME word
sequence, so all vocabs stay identical to the shared encode vocab by
construction (ids assign append-only).  The Python ``IncrementalNfa``
path remains as the no-toolchain fallback (one literally shared dict).

Maintenance rides the existing drain/apply cycle: the service's
``_table_add``/``_table_del`` seams note filter mutations here, the
sync loop applies them off the event loop (per-shard host subtables →
``flush()`` deltas → scatters into the stacked device arrays, full
restack only on a resize — the DeviceNfa discipline), and a compaction
swap rebuilds the whole partition from the fresh aid space.

Failure semantics: a dead (``kill_shard``) or fault-injected
(``match.shard`` point; ``ep.route`` for the routed front end) shard
raises at dispatch — the affected batch fails over to the CPU trie
through the serve plane's existing device-failure paths (breaker
strike in deadline mode, probe recovery, stale-slot discards stay
strike-free), exactly like any other device failure.

Degraded-mesh mode (ISSUE 18, opt-in ``match.multichip.degraded.
enable``) scopes that failover to the dead shard alone: EP-routed
rows owned by a dead shard divert to the CPU trie (host-side
``word_owner`` lookup — the device grid still runs, the dead owner's
answers are discarded), replicated dispatches mask the dead shard's
answer segment and the service CPU-fills only ``shard_of_filter(flt)
== dead`` filters, and the replicated micro-table's merge point
migrates to the lowest LIVE shard when shard 0 dies.  Per-shard
consecutive-failure counters (injected ``match.shard`` faults
attribute round-robin over the live shards) drive the health ladder
healthy → degraded(S) → cpu-only; ``rebuild_shard`` reconstructs a
lost subtable (epoch-guarded per-shard segment + delta-tail replay
from the service filter state) and the service re-admits it only
after a bit-parity canary passes.  Flag off, every path above is
byte-identical to the whole-plane failover.

Load-adaptive plane (ISSUE 20, opt-in ``match.multichip.ep.autotune.
enable``): two feedback loops close the ROADMAP 100M residuals (b)/(c)
on the PR 18 measurement plumbing.  (1) **EP capacity auto-resize** —
when the routed overflow EWMA crosses ``grow_threshold`` the bucket
grid rebuilds at the next pow2 capacity class (hysteresis band +
cooldown for shrink) on a background thread: the new-capacity step
compiles through the kernel cache / a local warm exec FIRST and the
class flips under the lock afterwards, so no dispatch ever parks
behind XLA and overflow rows keep failing open to the CPU trie
throughout the window.  A successful grow re-arms the overflow-warn
log-once latch and zeroes the EWMA so it measures the NEW grid.
(2) **Popularity-aware placement** — routed dispatches bump a per-root
popularity slab (numpy, the admission-plane feature-row idiom);
:meth:`MultichipMatcher.plan_rebalance` (the service's ``table.
compact`` worker cadence) greedily reassigns the hottest roots off the
most-loaded shard within a max-moved-roots budget and stages a small
``root → shard`` override map that :meth:`MultichipMatcher.shard_of`
consults before the crc32 default.  The staged map swaps in at the
next ``rebuild()`` apply — aid spans remap during that restack, and
in-flight slots discard via the service's table-gen guard exactly like
any compaction swap.  The map persists in the per-shard segment
manifest (format v3; checksum-rejected on skew) so cold start restores
placement.  A rebalance proposed while any shard is dead/rebuilding
defers — roots never remap onto a dead owner.  Flag off, every path
above is byte-identical: class stays 0, the override map stays empty.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import zlib
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import faultinject as _fi
from .. import topic as T
from ._shard_compat import shard_map
from .sharded_match import CompactFanoutResult, decode_compact_rows

log = logging.getLogger(__name__)

__all__ = ["MultichipMatcher", "ShardDead", "build_multichip_step",
           "serve_mesh_shape", "shard_of_filter", "is_micro_filter"]


class ShardDead(RuntimeError):
    """A mesh shard is down: the dispatch cannot produce a trustworthy
    answer for ANY row (every shard owns part of the table).  Treated
    by the serve plane as a device failure — CPU trie serves the
    batch, breaker accounting applies."""


def serve_mesh_shape(n_devices: int, tp: int = 0) -> Dict[str, int]:
    """Mesh factorization for the serve backend: ``tp`` table shards
    (0 = the widest pow2 ≤ 4 that divides the device count — the
    :func:`~emqx_tpu.parallel.mesh.pick_shape` default), rest ``dp``
    batch rows."""
    from .mesh import pick_shape

    return pick_shape(n_devices, tp if tp > 0 else None)


def shard_of_filter(flt: str, tp: int) -> int:
    """Topic-prefix partition: a filter lives on the shard its ROOT
    token hashes to.  Wildcard roots (``+``/``#``) hash their literal
    token here too (deterministic), but the matcher diverts them to
    the replicated micro-table (:func:`is_micro_filter`) — a filter
    every topic can match has no single owner under EP routing.

    This is the DEFAULT placement only: the load-adaptive matcher
    consults its popularity override map first
    (:meth:`MultichipMatcher.shard_of`); use that instance method
    wherever a live matcher is in hand."""
    root = flt.split("/", 1)[0]
    return zlib.crc32(root.encode("utf-8")) % tp


def is_micro_filter(flt: str) -> bool:
    """Wildcard-root filters (``+``/``#`` first token) match topics
    with ANY root — they live in the replicated micro-table, merged
    into the owning shard's answer segment."""
    return flt.split("/", 1)[0] in ("+", "#")


@partial(jax.jit, donate_argnums=(0,))
def _scatter_stacked(tab, tvec, idx, rows):
    """stacked[t, idx] = rows, in place (donated) — the per-shard
    delta scatter into the (tp, ...) stacked table.  Callers hold the
    matcher lock across the scatter AND every dispatch-side read of
    ``_arrs``, so a donated-away buffer is never re-dispatched."""
    return tab.at[tvec, idx].set(rows, mode="drop", unique_indices=False)


def build_multichip_step(mesh, active_slots: int = 16,
                         max_matches: int = 32, micro_matches: int = 8,
                         routed: bool = False, capacity: int = 0,
                         compact: bool = False, micro_owner: int = 0):
    """Return a jitted ``step(words, lens, is_sys, node_stk, edge_stk,
    seeds_stk, aid_stk, micro_node, micro_edge, micro_seeds,
    micro_amap, word_owner) -> CompactFanoutResult``.

    Input layouts: batch arrays sharded over ``dp`` (replicated —
    *fanned* — over ``tp``); the stacked per-shard tables
    ``node_stk (tp, S, 4)``, ``edge_stk (tp, Hb, slots·4)``,
    ``seeds_stk (tp, 2)`` and the local→service accept-id map
    ``aid_stk (tp, A)`` sharded over ``tp``; the wildcard-root
    micro-table arrays and the root-token ``word_owner`` routing map
    fully replicated.  Output ``ids`` is the dense compact contract:
    (B, tp·(K+Km)) service accept ids, -1 padded, per-shard segments
    disjoint by partition construction; ``counts`` (B, tp); the spill
    vectors psum over ``tp``.

    ``routed=True`` compiles the EP front end: each ``tp`` instance
    takes its 1/tp source slice of the dp-local batch, buckets rows
    by ``word_owner[root]`` into a (tp, ``capacity``) grid, and one
    ``all_to_all`` lands every row on the single shard that owns its
    root.  The owner merges its own + micro answers into ITS segment
    (other segments stay count-0 for that row), so no return
    ``all_to_all`` is needed.  Rows past ``capacity`` fail open
    (match_overflow) at the source.

    ``micro_owner`` names the shard that merges the replicated
    micro-table's answers in replicated mode (default 0; the degraded
    mesh migrates it to the lowest LIVE shard when shard 0 dies, so
    wildcard-root answers never go dark with their merge point).

    ``compact=True`` (routed only) applies the count-compact contract
    to the ROUTED output: exactly one owner writes each row, so a
    psum over ``tp`` of the bias-encoded segments collapses the
    (B, tp·W) id plane to (B, W) and counts to (B, 1) — routed d2h
    drops ~tp× with identical decoded rows (the owner's segment is
    already contiguous from 0)."""
    from ..ops.match_kernel import nfa_match

    K = max_matches
    Km = micro_matches
    W = K + Km
    tp = mesh.shape["tp"]
    C = capacity
    compact = bool(compact) and bool(routed)
    seg_spec = P("dp", None) if compact else P("dp", "tp")

    def merge_micro(gids, cnt_own, mg, mcnt):
        """Pack ``mcnt`` micro ids behind each row's ``cnt_own`` own
        ids — decode_compact_rows prefix-takes ``count`` entries per
        segment, so the merged segment must be contiguous from 0."""
        R = gids.shape[0]
        out = jnp.full((R, W), -1, jnp.int32).at[:, :K].set(gids)
        pos = cnt_own[:, None] + jnp.arange(Km, dtype=jnp.int32)[None, :]
        pos = jnp.where(
            jnp.arange(Km, dtype=jnp.int32)[None, :] < mcnt[:, None],
            pos, W)
        out = out.at[jnp.arange(R)[:, None], pos].set(mg, mode="drop")
        return out, cnt_own + mcnt

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P("dp", None),        # words
            P("dp"),              # lens
            P("dp"),              # is_sys
            P("tp", None, None),  # node_stk
            P("tp", None, None),  # edge_stk
            P("tp", None),        # seeds_stk
            P("tp", None),        # aid_stk
            P(None, None),        # micro_node (replicated)
            P(None, None),        # micro_edge
            P(None),              # micro_seeds
            P(None),              # micro_amap
            P(None),              # word_owner
        ),
        out_specs=CompactFanoutResult(
            ids=seg_spec,
            counts=seg_spec,
            overflow=seg_spec,
            n_matches=P("dp"),
            active_overflow=P("dp"),
            match_overflow=P("dp"),
        ),
        check_vma=False,
    )
    def step(words, lens, is_sys, node_stk, edge_stk, seeds_stk, aid_stk,
             micro_node, micro_edge, micro_seeds, micro_amap, word_owner):
        node, edge, seeds, amap = (
            node_stk[0], edge_stk[0], seeds_stk[0], aid_stk[0])

        def match_both(w, l, s):
            res = nfa_match(
                w, l, s, node, edge, seeds,
                active_slots=active_slots, max_matches=K,
            )
            gids = jnp.where(
                res.matches >= 0, amap[jnp.maximum(res.matches, 0)], -1)
            mres = nfa_match(
                w, l, s, micro_node, micro_edge, micro_seeds,
                active_slots=active_slots, max_matches=Km,
            )
            mg = jnp.where(
                mres.matches >= 0,
                micro_amap[jnp.maximum(mres.matches, 0)], -1)
            return res, gids, mres, mg

        if not routed:
            res, gids, mres, mg = match_both(words, lens, is_sys)
            # segments must stay DISJOINT per row: exactly one shard
            # (the micro owner — shard 0 unless the degraded mesh
            # migrated the merge point) merges the replicated micro
            # answers
            is0 = jax.lax.axis_index("tp") == micro_owner
            mcnt = jnp.where(is0, jnp.minimum(mres.n_matches, Km), 0)
            ids, cnt = merge_micro(
                gids, jnp.minimum(res.n_matches, K), mg, mcnt)
            seg_ov = (res.match_overflow
                      + jnp.where(is0, mres.match_overflow, 0))
            return CompactFanoutResult(
                ids=ids,
                counts=cnt[:, None],
                overflow=seg_ov[:, None],
                n_matches=jax.lax.psum(
                    res.n_matches + jnp.where(is0, mres.n_matches, 0),
                    "tp"),
                active_overflow=jax.lax.psum(
                    res.active_overflow
                    + jnp.where(is0, mres.active_overflow, 0), "tp"),
                match_overflow=jax.lax.psum(seg_ov, "tp"),
            )

        # -- EP-routed front end ----------------------------------------
        Bl, D = words.shape
        i = jax.lax.axis_index("tp")
        Bs = Bl // tp
        start = i * Bs
        myw = jax.lax.dynamic_slice_in_dim(words, start, Bs)
        myl = jax.lax.dynamic_slice_in_dim(lens, start, Bs)
        mys = jax.lax.dynamic_slice_in_dim(is_sys, start, Bs)
        root = jnp.clip(myw[:, 0], 0, word_owner.shape[0] - 1)
        owner = word_owner[root]                            # (Bs,) in [0,tp)
        routable = myl <= D          # encode pads with the D+2 sentinel
        # rank within each owner group (cumsum compaction, prefix_ep)
        onehot = ((owner[:, None] == jnp.arange(tp)[None, :])
                  & routable[:, None])
        rank = jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1
        my_rank = jnp.take_along_axis(rank, owner[:, None], axis=1)[:, 0]
        keep = routable & (my_rank < C)
        bucket_ov = (routable & (my_rank >= C)).astype(jnp.int32)
        # overflowed/pad rows must scatter NOWHERE (an in-range dummy
        # slot would clobber a legitimate row): route them out of range
        # and let mode="drop" discard the write
        owner_idx = jnp.where(keep, owner, tp)
        slot = jnp.where(keep, my_rank, 0)
        grid_w = jnp.zeros((tp, C, D), jnp.int32).at[owner_idx, slot].set(
            myw, mode="drop")
        grid_l = jnp.full((tp, C), D + 2, jnp.int32).at[
            owner_idx, slot].set(myl, mode="drop")
        grid_s = jnp.ones((tp, C), bool).at[owner_idx, slot].set(
            mys, mode="drop")
        grid_src = jnp.full((tp, C), -1, jnp.int32).at[
            owner_idx, slot].set(
                jnp.arange(Bs, dtype=jnp.int32), mode="drop")

        # ragged all-to-all: (owner, C, ...) leaves, (source, C, ...)
        # lands — each shard now holds exactly the rows it owns
        w2 = jax.lax.all_to_all(grid_w, "tp", 0, 0, tiled=False)
        l2 = jax.lax.all_to_all(grid_l, "tp", 0, 0, tiled=False)
        s2 = jax.lax.all_to_all(grid_s, "tp", 0, 0, tiled=False)
        src2 = jax.lax.all_to_all(grid_src, "tp", 0, 0, tiled=False)

        R = tp * C
        res, gids, mres, mg = match_both(
            w2.reshape(R, D), l2.reshape(R), s2.reshape(R))
        # the owner is the ONLY shard seeing this row: merge micro here
        merged, merged_cnt = merge_micro(
            gids, jnp.minimum(res.n_matches, K),
            mg, jnp.minimum(mres.n_matches, Km))

        # scatter into MY output segment at the row's dp-local position
        # (source j's slice starts at j*Bs); no return all_to_all —
        # other shards' segments stay count-0 for rows they don't own
        flat_src = src2.reshape(R)
        pos = (jnp.arange(tp, dtype=jnp.int32)[:, None] * Bs
               + src2).reshape(R)
        safe = jnp.where(flat_src >= 0, pos, Bl)
        ids_out = jnp.full((Bl, W), -1, jnp.int32).at[safe].set(
            merged, mode="drop")
        cnt_out = jnp.zeros((Bl,), jnp.int32).at[safe].set(
            merged_cnt, mode="drop")
        seg_ov = jnp.zeros((Bl,), jnp.int32).at[safe].set(
            res.match_overflow + mres.match_overflow, mode="drop")
        nm = jnp.zeros((Bl,), jnp.int32).at[safe].set(
            res.n_matches + mres.n_matches, mode="drop")
        ao = jnp.zeros((Bl,), jnp.int32).at[safe].set(
            res.active_overflow + mres.active_overflow, mode="drop")
        # source-side bucket overflow flags MY slice's rows: psum folds
        # them into the fail-open set alongside owner-side truncation
        src_ov = jax.lax.dynamic_update_slice(
            jnp.zeros((Bl,), jnp.int32), bucket_ov, (start,))
        if compact:
            # exactly ONE owner wrote each row (the partition makes
            # segments disjoint; non-owners left -1/0), so a psum of
            # the +1-biased ids collapses tp segments into one (B, W)
            # plane — the contiguous-from-0 owner segment survives
            # verbatim and routed d2h bytes drop ~tp×
            ids_c = jax.lax.psum(
                jnp.where(ids_out >= 0, ids_out + 1, 0), "tp") - 1
            return CompactFanoutResult(
                ids=ids_c,
                counts=jax.lax.psum(cnt_out, "tp")[:, None],
                overflow=jax.lax.psum(seg_ov, "tp")[:, None],
                n_matches=jax.lax.psum(nm, "tp"),
                active_overflow=jax.lax.psum(ao, "tp"),
                match_overflow=jax.lax.psum(seg_ov + src_ov, "tp"),
            )
        return CompactFanoutResult(
            ids=ids_out,
            counts=cnt_out[:, None],
            overflow=seg_ov[:, None],
            n_matches=jax.lax.psum(nm, "tp"),
            active_overflow=jax.lax.psum(ao, "tp"),
            match_overflow=jax.lax.psum(seg_ov + src_ov, "tp"),
        )

    return jax.jit(step)


class MultichipMatcher:
    """Host side of the multichip serve backend: per-shard subtables
    (identical vocabs, one encode serves every shard), the wildcard
    micro-table, the stacked device twin, and the mesh-compiled step
    cache.

    Threading model (the MatchService discipline): ``note_add``/
    ``note_del``/``rebuild`` run on the event loop and only append to a
    pending op list; ``apply_pending`` runs in the sync loop's worker
    thread and is the single writer of the subtables + stacked arrays;
    ``dispatch`` runs in the serve plane's encode worker thread and
    captures one consistent (arrays, aid map) snapshot under the lock.
    """

    # v3 (ISSUE 20): the manifest's aid_maps.npz additionally carries
    # the popularity placement override map (NUL-framed roots + int32
    # owners, covered by the same sha1) so cold start restores
    # placement; v2 manifests are version-rejected (one repartition
    # serves after upgrade — same contract as any manifest skew)
    MANIFEST_VERSION = 3
    #: serve-plane dispatch routing marker (MatchService checks this
    #: instead of importing the class on its hot path)
    is_multichip = True
    #: smoothing factor for the per-dispatch routed overflow-rate EWMA
    EP_OVERFLOW_ALPHA = 0.1
    #: routed readbacks that must land at the current capacity class
    #: before a shrink is considered — the EWMA zeroes on every flip,
    #: so an immediate shrink-back would thrash the grid
    EP_SHRINK_COOLDOWN = 64

    def __init__(
        self,
        depth: int = 8,
        tp: int = 0,
        devices: Optional[Sequence[Any]] = None,
        active_slots: int = 16,
        max_matches: int = 32,
        metrics: Any = None,
        kernel_cache: Any = None,
        native: bool = True,
        ep: bool = False,
        ep_slack: float = 2.0,
        ep_micro_matches: int = 8,
        ep_compact: bool = False,
        degraded: bool = False,
        degraded_fail_threshold: int = 3,
        ep_overflow_warn: float = 0.5,
        ep_autotune: bool = False,
        ep_grow_threshold: float = 0.05,
        ep_shrink_threshold: float = 0.01,
        ep_max_cap_class: int = 3,
        balance_budget: int = 64,
    ) -> None:
        from .mesh import make_mesh

        devs = list(devices if devices is not None else jax.devices())
        shape = serve_mesh_shape(len(devs), tp)
        self.mesh = make_mesh(shape, devs)
        self.dp = shape["dp"]
        self.tp = shape["tp"]
        self.n_devices = self.dp * self.tp
        self.depth = depth
        self.active_slots = active_slots
        self.max_matches = max_matches
        self.metrics = metrics
        self.kernel_cache = kernel_cache
        self.ep = bool(ep)
        self.ep_slack = float(ep_slack)
        self.ep_micro_matches = int(ep_micro_matches)
        # count-compact the routed output before d2h (ISSUE 17): the
        # (B, tp·W) segment plane collapses to (B, W) on-mesh, so
        # routed readback bytes drop ~tp× on literal-rooted tables
        self.ep_compact = bool(ep_compact)
        # degraded-mesh serving (ISSUE 18): scoped shard failover +
        # the health ladder; flag off every dead shard fails the
        # whole plane over (the PR 17 contract, byte-identical)
        self.degraded = bool(degraded)
        self.fail_threshold = max(1, int(degraded_fail_threshold))
        self.ep_overflow_warn = float(ep_overflow_warn)
        # load-adaptive plane (ISSUE 20, module docstring): capacity
        # auto-resize + popularity-aware placement; flag off every
        # structure below stays inert (class 0, empty override map)
        self.ep_autotune = bool(ep_autotune)
        self.ep_grow_threshold = float(ep_grow_threshold)
        self.ep_shrink_threshold = float(ep_shrink_threshold)
        self.ep_max_cap_class = max(0, int(ep_max_cap_class))
        self.balance_budget = max(0, int(balance_budget))
        self._cap_class = 0            # live pow2 capacity exponent
        self._class_readbacks = 0      # routed readbacks at this class
        self._resize_busy = False      # one background resize at a time
        self._resize_thread: Optional[threading.Thread] = None
        self._ep_shapes: set = set()   # observed routed (B, D) shapes
        # popularity placement: override map consulted before the crc32
        # default, the staged map the next rebuild swaps in, and the
        # per-root load slab (indexed by root word id, lock-free stats
        # — a dropped bump under a concurrent aging pass is benign)
        self._placement: Dict[str, int] = {}
        self._placement_next: Optional[Dict[str, int]] = None
        self._root_load = np.zeros(1024, np.float64)
        self.ep_resizes = 0
        self.ep_rebalances = 0
        self.moved_roots = 0
        if native:
            from ..native.nfa import available

            native = available()
            if not native:
                log.warning("native nfa unavailable; multichip shard "
                            "subtables fall back to IncrementalNfa")
        self.native = bool(native)
        if kernel_cache is not None:
            # mesh-keyed executables compile through the shared cache
            # (CompileMiss semantics, zero-compile prewarm spies)
            kernel_cache.mesh_lower = self._lower_step

        self.vocab: Dict[str, int] = {}
        self._subs: List[Any] = []
        self._aid_maps: List[np.ndarray] = []
        self._filters: List[Dict[str, int]] = []
        self._micro: Any = None
        self._micro_amap: np.ndarray = np.full(8, -1, np.int32)
        self._micro_filters: Dict[str, int] = {}
        self._word_owner = np.zeros(1024, np.int32)
        self._word_owner_n = 0
        self._reset_subs()

        self._lock = threading.Lock()
        # serializes table maintenance (apply_pending / save_segments /
        # rebuild_shard) — the rebuild child's worker hop must not race
        # the sync loop's
        self._maint_lock = threading.Lock()
        self._pending: List[Tuple[str, str, int]] = []  # (op, flt, aid)
        self._rebuild_pairs: Optional[List[Tuple[str, int]]] = None
        self._restack_due = False      # segment restore awaiting upload
        self._arrs: Optional[Tuple[Any, ...]] = None
        self._stacked_shape: Optional[Tuple[int, ...]] = None
        self._steps: Dict[Tuple[int, ...], Any] = {}
        self._routed_live: set = set()  # id(res) of in-flight EP handles
        self._dead: set = set()
        # degraded-mesh state: per-dispatch failover metadata keyed by
        # id(res), per-shard consecutive-failure strikes, and the
        # round-robin cursor that attributes anonymous match.shard
        # faults to a live shard
        self._degraded_meta: Dict[int, Tuple[Any, ...]] = {}
        self._fail_counts: Dict[int, int] = {}
        self._fault_rr = 0
        self.degraded_batches = 0
        self.cpu_filled_rows = 0
        self.rebuilds = 0
        self.readmit_canary_fails = 0
        # satellite: routed overflow-rate EWMA (the bucket-grid resize
        # input) + its log-once warning latch
        self._ov_ewma = 0.0
        self._ov_warned = False
        self.gen = 0                    # bumped on every restack
        self.dispatches = 0
        self.ep_dispatches = 0
        self.failovers = 0
        self.applies = 0
        self.restacks = 0
        self.seeded_from_segments = False
        self._persist_due = False
        if metrics is not None:
            metrics.set("tpu.match.shard_devices", self.n_devices)

    # ------------------------------------------------------------------
    # partition maintenance (event loop: enqueue; worker thread: apply)
    # ------------------------------------------------------------------

    def _new_sub(self):
        if self.native:
            from ..native.nfa import NativeNfa

            return NativeNfa(depth=self.depth)
        from ..ops.incremental import IncrementalNfa

        sub = IncrementalNfa(depth=self.depth)
        # one vocab dict shared by every subtable: a single encode
        # pass serves all shards (interning appends consistently)
        sub.vocab = self.vocab
        return sub

    def _reset_subs(self) -> None:
        self.vocab = {}
        self._subs = []
        self._aid_maps = []
        self._filters = []
        self._word_owner = np.zeros(1024, np.int32)
        self._word_owner_n = 0
        for _ in range(self.tp):
            self._subs.append(self._new_sub())
            self._aid_maps.append(np.full(64, -1, np.int32))
            self._filters.append({})
        self._micro = self._new_sub()
        self._micro_amap = np.full(8, -1, np.int32)
        self._micro_filters = {}

    def _all_tables(self) -> List[Any]:
        return [*self._subs, self._micro]

    def shard_of(self, flt: str) -> int:
        """Placement-aware :func:`shard_of_filter`: the popularity
        override map (root → shard, staged by :meth:`plan_rebalance`
        and swapped in at a rebuild) is consulted before the crc32
        default.  Empty map (flag off, or nothing hot enough to move)
        → byte-identical to the pure hash."""
        if self._placement:
            o = self._placement.get(flt.split("/", 1)[0])
            if o is not None:
                return int(o)
        return shard_of_filter(flt, self.tp)

    def note_add(self, flt: str, service_aid: int) -> None:
        with self._lock:
            self._pending.append(("add", flt, service_aid))

    def note_del(self, flt: str) -> None:
        with self._lock:
            self._pending.append(("del", flt, -1))

    def rebuild(self, pairs: List[Tuple[str, int]]) -> None:
        """Full repartition (cold start, compaction swap — the service
        aid space was reassigned wholesale).  Cheap on the loop: the
        build itself happens at the next ``apply_pending``; until then
        ``ready`` is False and the single-chip path serves."""
        with self._lock:
            self._rebuild_pairs = list(pairs)
            self._pending = []
            self._restack_due = False
            self._arrs = None
            self._steps = {}

    @property
    def ready(self) -> bool:
        return self._arrs is not None

    @property
    def dirty(self) -> bool:
        return (bool(self._pending) or self._rebuild_pairs is not None
                or self._restack_due)

    def _intern_filter_words(self, flt: str) -> None:
        """Intern the filter's literal words into the shared encode
        vocab AND every subtable (native vocabs are per-table; ids
        assign append-only, so replaying one word sequence everywhere
        keeps them all identical — the EP word_owner map and the
        stacked edge tables then agree with encode_batch)."""
        for w in T.words(flt):
            if w in ("+", "#") or w in self.vocab:
                continue
            wid = len(self.vocab) + 1
            self.vocab[w] = wid
            if self.native:
                for tbl in self._all_tables():
                    tbl.intern(w)

    def _host_add(self, flt: str, service_aid: int) -> None:
        self._intern_filter_words(flt)
        if is_micro_filter(flt):
            sub = self._micro
            sub.add(flt)
            laid = sub.aid_of(flt)
            amap = self._micro_amap
            if laid >= len(amap):
                grown = np.full(max(2 * len(amap), laid + 1), -1, np.int32)
                grown[:len(amap)] = amap
                amap = self._micro_amap = grown
            amap[laid] = service_aid
            self._micro_filters[flt] = service_aid
            return
        t = self.shard_of(flt)
        sub = self._subs[t]
        sub.add(flt)
        laid = sub.aid_of(flt)
        amap = self._aid_maps[t]
        if laid >= len(amap):
            grown = np.full(max(2 * len(amap), laid + 1), -1, np.int32)
            grown[:len(amap)] = amap
            amap = self._aid_maps[t] = grown
        amap[laid] = service_aid
        self._filters[t][flt] = service_aid

    def _host_del(self, flt: str) -> None:
        if is_micro_filter(flt):
            laid = self._micro.aid_of(flt)
            if laid < 0:
                return
            self._micro_amap[laid] = -1
            self._micro.remove(flt)
            self._micro_filters.pop(flt, None)
            return
        t = self.shard_of(flt)
        sub = self._subs[t]
        laid = sub.aid_of(flt)
        if laid < 0:
            return
        self._aid_maps[t][laid] = -1
        sub.remove(flt)
        self._filters[t].pop(flt, None)

    def _sync_word_owner(self) -> bool:
        """Fill routing owners (the device twin of :meth:`shard_of` —
        placement override first, crc32(word) % tp default) for vocab
        words interned since the last sync; pow2 growth.  Returns True
        when entries changed."""
        n = len(self.vocab)
        if self._word_owner_n >= n:
            return False
        cap = len(self._word_owner)
        if n + 1 > cap:
            while cap < n + 1:
                cap *= 2
            grown = np.zeros(cap, np.int32)
            grown[:len(self._word_owner)] = self._word_owner
            self._word_owner = grown
        place = self._placement
        for w, wid in list(self.vocab.items())[self._word_owner_n:]:
            o = place.get(w) if place else None
            self._word_owner[wid] = (
                zlib.crc32(w.encode("utf-8")) % self.tp
                if o is None else int(o))
        self._word_owner_n = n
        return True

    def apply_pending(self) -> bool:
        """WORKER-THREAD step (the sync loop's ``to_thread`` hop):
        drain the queued mutations into the per-shard subtables, then
        ship the result — per-shard ``flush()`` deltas scatter into the
        stacked arrays in place; any resize/repartition restacks (the
        DeviceNfa full-upload analog).  Returns True when the device
        state changed."""
        with self._maint_lock:
            return self._apply_locked()

    def _apply_locked(self) -> bool:
        with self._lock:
            ops, self._pending = self._pending, []
            rebuild, self._rebuild_pairs = self._rebuild_pairs, None
            restack_due, self._restack_due = self._restack_due, False
        if rebuild is not None:
            with self._lock:
                staged, self._placement_next = self._placement_next, None
            if staged is not None:
                # a full repartition rebuilds every aid span anyway —
                # the staged override map swaps in HERE so the restack
                # below remaps spans and word_owner in the same pass
                # (in-flight slots discard via the service table-gen
                # guard, like any compaction swap)
                self._placement = staged
                self._persist_due = True
                log.info("EP placement override map applied: %d "
                         "root(s) off their crc32 shard", len(staged))
            self._reset_subs()
            if self.native:
                # pre-intern the whole word sequence with one native
                # call per table (the bulk-build fast path; per-filter
                # interning would pay tp+1 ctypes hops per word)
                words: List[str] = []
                for flt, _aid in rebuild:
                    for w in T.words(flt):
                        if w not in ("+", "#") and w not in self.vocab:
                            self.vocab[w] = len(self.vocab) + 1
                            words.append(w)
                for tbl in self._all_tables():
                    tbl.bulk_intern(words)
            for flt, aid in rebuild:
                self._host_add(flt, aid)
            # notes enqueued AFTER the rebuild request (rebuild()
            # clears the pending log, so every drained op postdates
            # it) apply on top — dropping them would serve a partition
            # missing live mutations
            for op, flt, aid in ops:
                if op == "add":
                    self._host_add(flt, aid)
                else:
                    self._host_del(flt)
            for tbl in self._all_tables():
                tbl.flush()     # clear dirty sets; restack ships all
            self._restack()
            self._persist_due = True
            return True
        if not ops:
            if self._arrs is None and restack_due:
                # segment restore: the subtables are populated but the
                # stacked device twin was never shipped
                self._restack()
                return True
            return False
        for op, flt, aid in ops:
            if op == "add":
                self._host_add(flt, aid)
            else:
                self._host_del(flt)
        deltas = [sub.flush() for sub in self._subs]
        mdelta = self._micro.flush()
        wo_changed = self._sync_word_owner()
        shape = self._required_shape()
        if (self._arrs is None or self._stacked_shape != shape
                or any(d.resized for d in deltas) or mdelta.resized):
            self._restack()
            return True
        from ..ops.device_table import _chunks

        # the scatters DONATE the stacked buffers: the lock must span
        # the whole read-modify-publish so a concurrent dispatch never
        # captures a donated-away array
        with self._lock:
            (node_stk, edge_stk, seeds_stk, _aid_stk,
             micro_node, micro_edge, micro_seeds, micro_amap,
             word_owner) = self._arrs
            for t, d in enumerate(deltas):
                if d.empty:
                    continue
                for idx, rows in _chunks(d.state_idx, d.state_rows):
                    node_stk = _scatter_stacked(
                        node_stk, jnp.full(idx.shape, t, jnp.int32),
                        jnp.asarray(idx), jnp.asarray(rows))
                for idx, rows in _chunks(d.bucket_idx, d.bucket_rows):
                    edge_stk = _scatter_stacked(
                        edge_stk, jnp.full(idx.shape, t, jnp.int32),
                        jnp.asarray(idx), jnp.asarray(rows))
            aid_stk = jnp.asarray(self._stacked_aid_maps(shape[2]))
            if not mdelta.empty:
                # the micro-table is small and replicated: a dirty
                # micro ships as a full (fresh-array) upload
                mn, me, ms = self._table_arrays(self._micro)
                micro_node = jnp.asarray(mn)
                micro_edge = jnp.asarray(me)
                micro_seeds = jnp.asarray(ms)
            if not mdelta.empty or wo_changed:
                micro_amap = jnp.asarray(
                    self._padded_micro_amap(shape[5]))
                word_owner = jnp.asarray(self._word_owner)
            self._arrs = (node_stk, edge_stk, seeds_stk, aid_stk,
                          micro_node, micro_edge, micro_seeds,
                          micro_amap, word_owner)
        self.applies += 1
        return True

    @staticmethod
    def _table_shape(sub) -> Tuple[int, int]:
        """(S, Hb) for either table implementation."""
        if hasattr(sub, "node_tab"):
            return int(sub.S), int(sub.Hb)
        s, hb, _depth = sub.shape_key()
        return int(s), int(hb)

    @staticmethod
    def _table_arrays(sub):
        """(node_tab, edge_tab, seeds) for either table implementation."""
        if hasattr(sub, "node_tab"):
            return sub.node_tab, sub.edge_tab, sub.seeds
        return sub.tables()

    def _required_shape(self) -> Tuple[int, int, int, int, int, int, int]:
        """Common stacked (S, Hb, A_cap) plus the replicated shapes
        (micro S, micro Hb, micro A_cap, word_owner cap): node tables
        pad (states index directly — pad rows are unreachable), edge
        tables must SHARE a real bucket count (lookups hash modulo
        Hb), aid maps pad."""
        smax = max(self._table_shape(sub)[0] for sub in self._subs)
        hbmax = max(self._table_shape(sub)[1] for sub in self._subs)
        acap = 64
        for amap in self._aid_maps:
            while acap < len(amap):
                acap *= 2
        sm, hbm = self._table_shape(self._micro)
        am = 8
        while am < len(self._micro_amap):
            am *= 2
        return (smax, hbmax, acap, sm, hbm, am, len(self._word_owner))

    def _stacked_aid_maps(self, acap: int) -> np.ndarray:
        out = np.full((self.tp, acap), -1, np.int32)
        for t, amap in enumerate(self._aid_maps):
            out[t, :len(amap)] = amap
        return out

    def _padded_micro_amap(self, am: int) -> np.ndarray:
        out = np.full(am, -1, np.int32)
        out[:len(self._micro_amap)] = self._micro_amap
        return out

    def _restack(self) -> None:
        """Full re-upload of the stacked per-shard tables (+ the
        replicated micro/word_owner arrays).  Smaller shards grow
        their edge table to the common Hb (hash-correct — a padded
        edge table would probe modulo the wrong size), node tables pad
        with inert rows."""
        hbmax = max(self._table_shape(sub)[1] for sub in self._subs)
        for sub in self._subs:
            if hasattr(sub, "grow_edges_to"):
                sub.grow_edges_to(hbmax)
            else:
                while sub.Hb < hbmax:
                    sub._grow_edges()
            sub.flush()     # growth marked dirty; the restack ships all
        self._micro.flush()
        self._sync_word_owner()
        shape = self._required_shape()
        smax, hbmax, acap, _sm, _hbm, am, _wcap = shape
        nodes, edges, seeds = [], [], []
        for sub in self._subs:
            node, edge, sd = self._table_arrays(sub)
            tab = np.full((smax, 4), -1, np.int32)
            tab[:, 3] = 0
            tab[:node.shape[0]] = node
            nodes.append(tab)
            edges.append(edge)
            seeds.append(sd)
        node_stk = jnp.asarray(np.stack(nodes))
        edge_stk = jnp.asarray(np.stack(edges))
        seeds_stk = jnp.asarray(np.stack(seeds))
        aid_stk = jnp.asarray(self._stacked_aid_maps(acap))
        mn, me, ms = self._table_arrays(self._micro)
        arrs = (node_stk, edge_stk, seeds_stk, aid_stk,
                jnp.asarray(mn), jnp.asarray(me), jnp.asarray(ms),
                jnp.asarray(self._padded_micro_amap(am)),
                jnp.asarray(self._word_owner))
        with self._lock:
            self._arrs = arrs
            self._stacked_shape = shape
        self.gen += 1
        self.applies += 1
        self.restacks += 1
        if self.metrics is not None:
            self.metrics.set("tpu.match.shard_restacks", self.restacks)

    # ------------------------------------------------------------------
    # serving (encode worker thread)
    # ------------------------------------------------------------------

    def encode(self, topics: Sequence[str], batch: int,
               depth: Optional[int] = None):
        """Encode against the SHARED shard vocab (one pass serves every
        shard) — the service's table vocab assigns different word ids,
        so multichip-routed groups must encode here."""
        from ..ops.encode import encode_batch

        return encode_batch(self, topics, batch=batch, depth=depth)

    def kill_shard(self, t: int) -> None:
        """Chaos surface: mark shard ``t`` dead.  Flag off, every
        subsequent dispatch raises :class:`ShardDead` until
        ``revive_shard`` (whole-plane failover); degraded mode keeps
        serving on the survivors and diverts only the dead shard's
        share of the answers to the CPU trie (scoped failover)."""
        self._dead.add(int(t))
        self._fail_counts.pop(int(t), None)
        self._set_state_metric()

    def revive_shard(self, t: int) -> None:
        self._dead.discard(int(t))
        self._fail_counts.pop(int(t), None)
        self._set_state_metric()

    # -- health ladder -------------------------------------------------

    def mesh_state(self) -> int:
        """Health-ladder rung: 0 healthy, 1 degraded(S) (scoped
        failover serving on the survivors around ONE dead shard), 2
        cpu-only (every dispatch refused: two or more shards dead —
        the double-kill rung — or any dead shard with the flag off)."""
        if not self._dead:
            return 0
        if self.degraded_serving:
            return 1
        return 2

    @property
    def dead_shards(self) -> List[int]:
        return sorted(self._dead)

    @property
    def degraded_serving(self) -> bool:
        """True while scoped failover is answering on the survivors.
        Scoped failover covers exactly ONE dead shard (degraded(S));
        a second death drops the plane to cpu-only until the staged
        re-admit climbs back through degraded(S) to healthy."""
        return bool(self.degraded and len(self._dead) == 1
                    and self.tp > 1)

    def note_shard_failure(self, t: int) -> bool:
        """One consecutive-failure strike against shard ``t`` (the
        health ladder's input); at ``fail_threshold`` strikes the
        shard is marked dead.  Returns True when this strike killed
        it."""
        t = int(t)
        if t in self._dead:
            return False
        c = self._fail_counts.get(t, 0) + 1
        self._fail_counts[t] = c
        if c < self.fail_threshold:
            return False
        self._fail_counts.pop(t, None)
        self._dead.add(t)
        log.warning("mesh shard %d dead after %d consecutive failures",
                    t, c)
        self._set_state_metric()
        return True

    def _note_fault_failure(self) -> None:
        """An injected ``match.shard`` fault names no shard: attribute
        it round-robin over the LIVE shards so a sustained fault storm
        marches the ladder one shard at a time toward cpu-only."""
        live = [t for t in range(self.tp) if t not in self._dead]
        if not live:
            return
        t = live[self._fault_rr % len(live)]
        self._fault_rr += 1
        self.note_shard_failure(t)

    def _set_state_metric(self) -> None:
        if self.degraded and self.metrics is not None:
            self.metrics.set("tpu.mesh.state", self.mesh_state())

    def dead_aids(self, exclude: Optional[int] = None) -> frozenset:
        """Service accept ids owned by dead shards — the replicated
        scoped-failover CPU-fill set (host-known: ``shard_of_filter``
        is a pure function of the filter)."""
        out: set = set()
        for t in self._dead:
            if exclude is not None and int(t) == int(exclude):
                continue
            out.update(self._filters[t].values())
        return frozenset(out)

    def _gate(self) -> None:
        if self._dead:
            if not self.degraded_serving:
                self._note_failover()
                raise ShardDead(
                    f"mesh shard(s) {sorted(self._dead)} dead")
        if _fi._injector is not None:
            act = _fi._injector.act("match.shard")
            if act == "raise":
                self._note_failover()
                if self.degraded:
                    self._note_fault_failure()
                raise _fi.InjectedFault("match.shard")
            if act == "delay":
                # sync seam (worker thread): a plain blocking sleep,
                # the match.compile idiom
                import time

                time.sleep(_fi._injector.last_delay)

    def _gate_ep(self) -> None:
        """The routed front end's own chaos seam: an injected
        ``ep.route`` fault refuses the dispatch (CPU trie serves the
        batch) without taking the whole mesh down."""
        if _fi._injector is not None:
            act = _fi._injector.act("ep.route")
            if act == "raise":
                self._note_failover()
                raise _fi.InjectedFault("ep.route")
            if act == "delay":
                import time

                time.sleep(_fi._injector.last_delay)

    def _note_failover(self) -> None:
        self.failovers += 1
        if self.metrics is not None:
            self.metrics.inc("tpu.match.shard_failover")

    def ep_capacity(self, batch: int) -> int:
        """Per-(source, owner) bucket size for a routed batch: the
        uniform share ``Bs/tp`` with ``ep_slack`` headroom.  Per-shard
        processed width is ``tp * C <= ceil(slack * Bl / tp)`` — the
        ``gate_shard_width_le_batch_over_tp`` contract.  The autotune
        capacity class scales this by pow2 steps, ceilinged at the
        full source-slice width (where bucket overflow is impossible);
        class 0 — flag off, or never grown — is byte-identical."""
        bs = (batch // self.dp) // self.tp
        base = max(1, int(math.ceil(self.ep_slack * bs / self.tp)))
        if self._cap_class:
            base = min(max(bs, 1), base << self._cap_class)
        return base

    def _capacity_at(self, batch: int, cap_class: int) -> int:
        """:meth:`ep_capacity` at an explicit class — what the resize
        worker compiles for before flipping ``_cap_class``."""
        bs = (batch // self.dp) // self.tp
        base = max(1, int(math.ceil(self.ep_slack * bs / self.tp)))
        if cap_class:
            base = min(max(bs, 1), base << cap_class)
        return base

    def _routed_for(self, batch: int) -> bool:
        """EP routing serves a batch iff the dp-local slice splits
        evenly into tp source slices; anything else (odd warm shapes)
        falls back to the replicated step for that dispatch."""
        return (self.ep and self.tp > 1
                and batch % (self.dp * self.tp) == 0
                and (batch // self.dp) >= self.tp)

    def dispatch(self, enc, *, block_compile: bool = True):
        """One mesh dispatch of an already-encoded batch; returns the
        lazy :class:`CompactFanoutResult` handle (readback blocks
        later, outside any lock).  Raises :class:`ShardDead` /
        :class:`~emqx_tpu.faultinject.InjectedFault` at the
        ``match.shard`` / ``ep.route`` seams, :class:`CompileMiss` on
        a cold mesh shape when a kernel cache is attached."""
        self._gate()
        words, lens, is_sys = enc
        b, d = int(words.shape[0]), int(words.shape[1])
        routed = self._routed_for(b)
        if routed:
            self._gate_ep()
        dead = (frozenset(int(x) for x in self._dead)
                if self.degraded_serving else None)
        owner = 0
        dead_rows: List[int] = []
        if dead is not None:
            if routed:
                # scoped EP failover: the rows whose crc32-root owner
                # is dead divert to the CPU trie at readback (the
                # device grid still runs; the dead owner's segment is
                # discarded with them)
                dead_rows = self._dead_row_indices(words, lens, d, dead)
            else:
                # replicated micro-merge owner migrates to the lowest
                # live shard when its default owner (shard 0) is dead
                owner = min(x for x in range(self.tp) if x not in dead)
        step = self._step_for((b, d), routed=routed, micro_owner=owner,
                              block_compile=block_compile)
        with self._lock:
            if self._arrs is None:
                raise RuntimeError("multichip mirror not synced yet")
            res = step(jnp.asarray(words), jnp.asarray(lens),
                       jnp.asarray(is_sys), *self._arrs)
        if dead is not None:
            self._degraded_meta[id(res)] = (dead, dead_rows)
            self.degraded_batches += 1
            if self.metrics is not None:
                self.metrics.inc("tpu.mesh.degraded_batches")
                self.metrics.set("tpu.mesh.state", self.mesh_state())
        if self.degraded and self._fail_counts:
            # a dispatch that made it out clears the CONSECUTIVE
            # failure strikes on the still-live shards
            self._fail_counts.clear()
        self.dispatches += 1
        if self.metrics is not None:
            self.metrics.inc("tpu.match.shard_dispatches")
        if routed:
            self.ep_dispatches += 1
            self._routed_live.add(id(res))
            if self.ep_autotune:
                self._ep_shapes.add((b, d))
                self._note_root_load(words, lens, d)
            if self.metrics is not None:
                cap = self.ep_capacity(b)
                self.metrics.inc("tpu.match.ep_dispatches")
                self.metrics.set("tpu.match.ep_shard_width",
                                 self.tp * cap)
                # analytic ICI bill for the routing all_to_all: each
                # instance ships (tp-1)/tp of its (tp, C) grid — words
                # + lens + is_sys + src per slot
                self.metrics.inc(
                    "tpu.match.ep_ici_bytes",
                    self.dp * self.tp * (self.tp - 1) * cap
                    * (d + 3) * 4)
        return res

    def readback(self, res, n: int):
        """Block on the dense compact readback and decode to per-topic
        SERVICE accept-id rows: per-shard segments concatenate (the
        partition makes them disjoint — no dedup), rows flagged by the
        psum'd spill vectors go back to the host tables.  Degraded
        serving masks the dead shards' replicated answer segments and
        appends the dead-owned routed rows to the spill set (the
        scoped CPU-fill contract).  Returns ``(rows, spilled row
        indices, d2h bytes)``."""
        routed = id(res) in self._routed_live
        self._routed_live.discard(id(res))
        meta = self._degraded_meta.pop(id(res), None)
        ids, counts, nm, ao, mo = jax.device_get(
            (res.ids, res.counts, res.n_matches,
             res.active_overflow, res.match_overflow))
        if meta is not None and not routed \
                and counts.shape[1] == self.tp:
            # replicated scoped failover: zero the dead shards'
            # per-row counts so their (stale) segments decode empty —
            # the service CPU-fills exactly those shards' filters
            counts = np.array(counts)
            counts[:, sorted(meta[0])] = 0
        cap_row = ids.shape[1] // counts.shape[1]
        rows = decode_compact_rows(ids, counts, cap_row)[:n]
        out = [[int(a) for a in row if a >= 0] for row in rows]
        sp = (ao > 0) | (mo > 0)
        spilled = np.flatnonzero(sp[:n]).tolist()
        if routed and spilled and self.metrics is not None:
            # the routed fail-open set: bucket overflow + truncation
            # rows the CPU trie re-runs
            self.metrics.inc("tpu.match.ep_overflow_rows", len(spilled))
        if routed and n:
            # overflow-rate EWMA over the psum'd flags (the input the
            # bucket-grid resize will key on), warn once on crossing
            frac = len(spilled) / n
            self._ov_ewma += self.EP_OVERFLOW_ALPHA * (
                frac - self._ov_ewma)
            if self.metrics is not None:
                self.metrics.set("tpu.match.ep_overflow_ewma",
                                 round(self._ov_ewma, 6))
            if self._ov_ewma >= self.ep_overflow_warn > 0:
                if not self._ov_warned:
                    self._ov_warned = True
                    log.warning(
                        "EP bucket overflow EWMA %.3f crossed %.3f: "
                        "a hot root is skewing one owner shard "
                        "(rows fail open to the CPU trie)",
                        self._ov_ewma, self.ep_overflow_warn)
            else:
                self._ov_warned = False
            self._class_readbacks += 1
            if self.ep_autotune:
                self._maybe_resize()
        if meta is not None and routed:
            extra = [r for r in meta[1] if r < n and not sp[r]]
            if extra:
                self.cpu_filled_rows += len(extra)
                if self.metrics is not None:
                    self.metrics.inc("tpu.mesh.cpu_filled_rows",
                                     len(extra))
                spilled = sorted(set(spilled).union(extra))
        nbytes = 4 * int(ids.size + counts.size + nm.size
                         + ao.size + mo.size)
        return out, spilled, nbytes

    def _dead_row_indices(self, words, lens, depth: int,
                          dead: frozenset) -> List[int]:
        """Routable rows whose crc32-root owner shard is dead, from
        the HOST ``word_owner`` map (the same array the device routes
        by) — the scoped EP failover's CPU divert set."""
        wo = self._word_owner
        roots = np.clip(np.asarray(words)[:, 0], 0, len(wo) - 1)
        owners = wo[roots]
        routable = np.asarray(lens) <= depth
        return np.flatnonzero(
            routable & np.isin(owners, sorted(dead))).tolist()

    def _step_for(self, batch_shape: Tuple[int, int], routed: bool, *,
                  micro_owner: int = 0, block_compile: bool = True):
        cap = self.ep_capacity(batch_shape[0]) if routed else 0
        # mesh-key ``kind``: 0 = replicated, 1 = routed, 2 = routed
        # with the count-compact output contract
        compact = routed and self.ep_compact
        kind = (2 if compact else 1) if routed else 0
        kc = self.kernel_cache
        if kc is not None and self._stacked_shape is not None:
            smax, hbmax, acap, sm, hbm, am, wcap = self._stacked_shape
            mesh_key = (self.dp, self.tp, acap, kind, cap,
                        sm, hbm, am, wcap, self.ep_micro_matches)
            if micro_owner:
                # degraded-only key extension: flag off (or owner 0)
                # the cache keys stay the PR 17 shape verbatim
                mesh_key += (int(micro_owner),)
            return kc.executable(
                batch_shape, smax, hbmax,
                active_slots=self.active_slots,
                max_matches=self.max_matches,
                compact_output=True, flat_cap=0,
                mesh=mesh_key,
                block=block_compile,
            )
        key: Tuple[int, ...] = (
            int(batch_shape[0]), int(batch_shape[1]), kind)
        if self.ep_autotune:
            # autotune-only key extension: a class flip must select a
            # freshly built grid, never silently reuse the old one;
            # flag off the keys stay the PR 17 shape verbatim
            key += (cap,)
        if micro_owner:
            key += (int(micro_owner),)
        fn = self._steps.get(key)
        if fn is None:
            fn = self._steps[key] = build_multichip_step(
                self.mesh, self.active_slots, self.max_matches,
                micro_matches=self.ep_micro_matches,
                routed=routed, capacity=cap, compact=compact,
                micro_owner=int(micro_owner))
        return fn

    def _lower_step(self, key):
        """Mesh half of the kernel cache's ``_lower``: AOT-compile the
        shard_map step for one (B, D, S, Hb, ..., (dp, tp, acap, kind,
        C, Sm, Hbm, Am, Wcap, Km[, micro_owner])) key (proven on the
        CPU mesh — jit(shard_map).lower(ShapeDtypeStruct...) works)."""
        from ..ops.compiler import BUCKET_SLOTS

        b, d, s, hb = key[0], key[1], key[2], key[3]
        mk = key[10]
        _dp, _tp, acap, kind, cap, sm, hbm, am, wcap, km = mk[:10]
        owner = int(mk[10]) if len(mk) > 10 else 0
        step = build_multichip_step(
            self.mesh, key[4], key[5], micro_matches=km,
            routed=kind >= 1, capacity=cap, compact=kind == 2,
            micro_owner=owner)
        sd = jax.ShapeDtypeStruct
        i32 = jnp.int32
        return step.lower(
            sd((b, d), i32), sd((b,), i32), sd((b,), jnp.bool_),
            sd((self.tp, s, 4), i32),
            sd((self.tp, hb, BUCKET_SLOTS * 4), i32),
            sd((self.tp, 2), i32),
            sd((self.tp, acap), i32),
            sd((sm, 4), i32),
            sd((hbm, BUCKET_SLOTS * 4), i32),
            sd((2,), i32),
            sd((am,), i32),
            sd((wcap,), i32),
        ).compile()

    def warm(self, batches=(64,), depths=None) -> None:
        """Pre-pay the mesh step compiles for the serve shapes (the
        service ``_warm`` twin); no-op until the first apply."""
        if self._arrs is None:
            return
        for b in batches:
            for d in (depths or (self.depth,)):
                enc = self.encode([], batch=b, depth=d)
                res = self.dispatch(enc)
                self.readback(res, 0)

    # ------------------------------------------------------------------
    # load-adaptive plane: capacity auto-resize + popularity placement
    # (ISSUE 20, opt-in match.multichip.ep.autotune.enable)
    # ------------------------------------------------------------------

    def _note_root_load(self, words, lens, depth: int) -> None:
        """Per-root popularity counters (numpy slab indexed by root
        word id — the admission-plane feature-row idiom): every
        routable row of a routed dispatch bumps its root.  The slab
        ages by halving at each balance pass, so it behaves as an EWMA
        at compaction cadence.  Lock-free: a bump lost under a
        concurrent aging pass skews a statistic, never an answer."""
        w = np.asarray(words)[:, 0]
        routable = (np.asarray(lens) <= depth) & (w > 0)
        if not routable.any():
            return
        if len(self._root_load) < len(self._word_owner):
            grown = np.zeros(len(self._word_owner), np.float64)
            grown[:len(self._root_load)] = self._root_load
            self._root_load = grown
        roots = np.clip(w[routable], 0, len(self._root_load) - 1)
        np.add.at(self._root_load, roots, 1.0)

    def _maybe_resize(self) -> None:
        """Capacity-class trigger (routed readback, worker thread):
        grow one pow2 class when the overflow EWMA crosses the grow
        threshold; shrink one class inside the hysteresis band after
        ``EP_SHRINK_COOLDOWN`` readbacks at the current class.  The
        rebuild runs on a background thread — dispatches keep serving
        the old grid (overflow failing open to the CPU trie) until the
        new step is compiled.  Deferred entirely while any shard is
        dead: the degraded mesh owns the plane then."""
        if self._resize_busy or self._dead:
            return
        target = None
        if (self._ov_ewma >= self.ep_grow_threshold
                and self._cap_class < self.ep_max_cap_class):
            target = self._cap_class + 1
            shapes = list(self._ep_shapes)
            if shapes and all(
                    self.ep_capacity(b) >= max(1, (b // self.dp)
                                               // self.tp)
                    for b, _d in shapes):
                return   # already at the source-slice ceiling
        elif (self._cap_class > 0
              and self._class_readbacks >= self.EP_SHRINK_COOLDOWN
              and self._ov_ewma <= self.ep_shrink_threshold):
            target = self._cap_class - 1
        if target is None:
            return
        self._resize_busy = True
        self._resize_thread = threading.Thread(
            target=self._resize_worker, args=(target,),
            name="mc-ep-resize", daemon=True)
        self._resize_thread.start()

    def drain_resize(self, timeout: Optional[float] = None) -> bool:
        """Teardown drain: join the in-flight capacity rebuild.  The
        worker is a daemon thread, but daemon only helps at interpreter
        exit — a compile left churning after the matcher's owner stops
        keeps XLA on every host core, stealing CPU from whatever the
        process runs next.  Returns True when no resize is in flight."""
        t = self._resize_thread
        if t is not None and t.is_alive():
            t.join(timeout)
        return not self._resize_busy

    def _resize_worker(self, target: int) -> None:
        """Background capacity rebuild: compile the routed step at the
        target class for every observed serve shape FIRST (kernel
        cache AOT when attached — the prewarm machinery — else a local
        warm exec), then flip ``_cap_class`` under the lock.  The flip
        is a key swap, so no dispatch ever parks behind XLA; rows keep
        failing open throughout the compile window.  A successful GROW
        re-arms the overflow-warn latch and zeroes the EWMA (satellite
        bugfix: it must measure the new grid, and a later regression
        must warn again)."""
        grew = target > self._cap_class
        try:
            for b, d in sorted(self._ep_shapes):
                self._warm_capacity((b, d), target)
            with self._lock:
                self._cap_class = target
                self._class_readbacks = 0
                if grew:
                    self._ov_ewma = 0.0
                    self._ov_warned = False
            self.ep_resizes += 1
            if self.metrics is not None:
                self.metrics.set("tpu.match.ep_cap_class", target)
                self.metrics.inc("tpu.match.ep_resizes")
            log.warning("EP bucket grid %s to capacity class %d "
                        "(overflow EWMA keyed)",
                        "grew" if grew else "shrank", target)
        except Exception:
            log.warning("EP capacity resize to class %d failed; grid "
                        "unchanged", target, exc_info=True)
        finally:
            self._resize_busy = False

    def _warm_capacity(self, batch_shape: Tuple[int, int],
                       cap_class: int) -> None:
        """Compile the routed step for ``batch_shape`` at an explicit
        capacity class WITHOUT flipping the live class.  With a kernel
        cache the compile lands in the shared cache (a post-flip
        dispatch with ``block=False`` hits, never a CompileMiss); the
        no-cache path warm-executes the local step once so its jit
        cache is hot."""
        b, d = int(batch_shape[0]), int(batch_shape[1])
        cap = self._capacity_at(b, cap_class)
        compact = self.ep_compact
        kind = 2 if compact else 1
        kc = self.kernel_cache
        if kc is not None and self._stacked_shape is not None:
            smax, hbmax, acap, sm, hbm, am, wcap = self._stacked_shape
            mesh_key = (self.dp, self.tp, acap, kind, cap,
                        sm, hbm, am, wcap, self.ep_micro_matches)
            kc.executable(
                (b, d), smax, hbmax,
                active_slots=self.active_slots,
                max_matches=self.max_matches,
                compact_output=True, flat_cap=0,
                mesh=mesh_key, block=True)
            return
        key = (b, d, kind, cap)
        if key in self._steps:
            return
        fn = build_multichip_step(
            self.mesh, self.active_slots, self.max_matches,
            micro_matches=self.ep_micro_matches,
            routed=True, capacity=cap, compact=compact)
        with self._lock:
            arrs = self._arrs
        if arrs is not None:
            try:
                enc = self.encode([], batch=b, depth=d)
                res = fn(jnp.asarray(enc[0]), jnp.asarray(enc[1]),
                         jnp.asarray(enc[2]), *arrs)
                jax.block_until_ready(res.counts)
            except Exception:
                # a concurrent apply donated the snapshot away: the
                # compile simply happens at the first dispatch instead
                # (the pre-existing no-cache contract)
                log.debug("EP capacity warm exec lost the snapshot "
                          "race", exc_info=True)
        self._steps[key] = fn

    def plan_rebalance(self) -> int:
        """WORKER-THREAD step (the service's ``table.compact`` worker
        cadence): greedy hot-root reassignment off the popularity
        slab.  Moves the hottest improving root from the most- to the
        least-loaded shard, at most ``balance_budget`` times, and
        stages the result as a ``root → shard`` override map that the
        NEXT ``rebuild()`` apply swaps in (aid spans remap during that
        restack).  Defers — stages nothing, returns 0 — while any
        shard is dead or rebuilding: roots never remap onto a dead
        owner, and the readmit canary must judge the placement it was
        built against.  An injected ``ep.rebalance`` fault raises
        BEFORE anything is staged (kill mid-rebalance = no-op).
        Returns the number of roots moved."""
        if not self.ep_autotune or self.tp < 2 or self.balance_budget <= 0:
            return 0
        if _fi._injector is not None:
            act = _fi._injector.act("ep.rebalance")
            if act == "raise":
                raise _fi.InjectedFault("ep.rebalance")
            if act == "delay":
                import time

                time.sleep(_fi._injector.last_delay)
        if self._dead:
            return 0
        with self._lock:
            load = self._root_load.copy()
            placement = dict(self._placement)
            vocab_items = list(self.vocab.items())
        self._root_load *= 0.5   # age: EWMA at compaction cadence
        cand = [(w, wid) for w, wid in vocab_items
                if 0 < wid < len(load) and load[wid] > 0.0]
        if not cand:
            return 0
        owners: Dict[str, int] = {}
        loads: Dict[str, float] = {}
        for w, wid in cand:
            o = placement.get(w)
            if o is None:
                o = zlib.crc32(w.encode("utf-8")) % self.tp
            owners[w] = int(o)
            loads[w] = float(load[wid])
        from .prefix_ep import greedy_balance

        owners, moved = greedy_balance(
            loads, owners, self.tp, self.balance_budget)
        # the override map keeps only roots off their crc32 default;
        # overrides for roots with no observed load this round persist
        # (their filters still live on the overridden shard)
        new_place = {
            w: o for w, o in owners.items()
            if o != zlib.crc32(w.encode("utf-8")) % self.tp}
        for w, o in placement.items():
            if w not in owners:
                new_place.setdefault(w, o)
        if new_place == placement:
            return 0
        with self._lock:
            self._placement_next = new_place
        self.ep_rebalances += 1
        self.moved_roots = moved
        if self.metrics is not None:
            self.metrics.inc("tpu.match.ep_rebalances")
            self.metrics.set("tpu.match.ep_moved_roots", moved)
        log.info("EP balance pass staged %d root move(s) (%d "
                 "override(s) total); the next rebuild applies",
                 moved, len(new_place))
        return moved

    # ------------------------------------------------------------------
    # online shard rebuild + canary re-admit (degraded mesh, ISSUE 18)
    # ------------------------------------------------------------------

    def canary_topics(self, t: int, cap: int = 64) -> List[str]:
        """Concrete topics derived from shard ``t``'s own filter set
        (each wildcard level degraded to a literal token), so the
        re-admit canary batch exercises exactly the rebuilt subtable."""
        out = []
        for flt in list(self._filters[int(t)])[:cap]:
            out.append("/".join(
                w if w not in ("+", "#") else "c" for w in T.words(flt)))
        return out

    def canary_rows(self, topics: Sequence[str], batch: int,
                    readmit: int) -> Tuple[List[List[int]], List[int]]:
        """Dispatch a canary batch with shard ``readmit`` treated LIVE
        (any OTHER dead shard stays masked/diverted) — the bit-parity
        probe that gates re-admission.  Serving counters and the
        failure ladder are untouched; gates are bypassed on purpose
        (the probe must run while the shard is still marked dead)."""
        enc = self.encode(topics, batch=batch)
        words, lens, is_sys = enc
        b, d = int(words.shape[0]), int(words.shape[1])
        routed = self._routed_for(b)
        dead = frozenset(int(x) for x in self._dead
                         if int(x) != int(readmit))
        owner = 0
        dead_rows: List[int] = []
        if dead:
            if routed:
                dead_rows = self._dead_row_indices(words, lens, d, dead)
            else:
                owner = min(x for x in range(self.tp) if x not in dead)
        step = self._step_for((b, d), routed=routed, micro_owner=owner,
                              block_compile=True)
        with self._lock:
            if self._arrs is None:
                raise RuntimeError("multichip mirror not synced yet")
            res = step(jnp.asarray(words), jnp.asarray(lens),
                       jnp.asarray(is_sys), *self._arrs)
        ids, counts, ao, mo = jax.device_get(
            (res.ids, res.counts, res.active_overflow,
             res.match_overflow))
        if dead and not routed and counts.shape[1] == self.tp:
            counts = np.array(counts)
            counts[:, sorted(dead)] = 0
        cap_row = ids.shape[1] // counts.shape[1]
        n = len(topics)
        rows = decode_compact_rows(ids, counts, cap_row)[:n]
        out = [[int(a) for a in row if a >= 0] for row in rows]
        sp = (ao > 0) | (mo > 0)
        spilled = set(np.flatnonzero(sp[:n]).tolist())
        spilled.update(r for r in dead_rows if r < n)
        return out, sorted(spilled)

    def rebuild_shard(self, t: int, pairs: List[Tuple[str, int]],
                      segments_dir: Optional[str] = None,
                      expect_epoch: Optional[int] = None) -> float:
        """WORKER-THREAD step (the supervised ``mesh.rebuild`` child's
        ``to_thread`` hop): reconstruct shard ``t``'s subtable — seeded
        from its epoch-guarded per-shard segment when one matches, then
        a delta-tail replay from the service-level ``pairs`` converges
        it on the live filter state — and restack/re-upload the stacked
        twin.  Does NOT re-admit: the caller runs the bit-parity canary
        first.  Returns the rebuild wall seconds; an injected
        ``mesh.rebuild`` fault raises (the supervised child restarts
        and retries)."""
        import time as _time

        if _fi._injector is not None:
            act = _fi._injector.act("mesh.rebuild")
            if act == "raise":
                raise _fi.InjectedFault("mesh.rebuild")
            if act == "delay":
                _time.sleep(_fi._injector.last_delay)
        t = int(t)
        t0 = _time.perf_counter()
        want = {flt: aid for flt, aid in pairs
                if not is_micro_filter(flt)
                and self.shard_of(flt) == t}
        with self._maint_lock:
            seeded = self._seg_seed_filters(t, segments_dir,
                                            expect_epoch)
            sub = self._new_sub()
            seed_flts = [f for f in (seeded or ())]
            if self.native:
                # replay the live shared vocab in id order first so the
                # fresh native table assigns identical word ids
                sub.bulk_intern(
                    [w for w, _i in sorted(self.vocab.items(),
                                           key=lambda kv: kv[1])])
                sub.bulk_add(seed_flts)
            else:
                for f in seed_flts:
                    sub.add(f)
            # delta-tail replay: adds since the snapshot, then removes
            # of filters the service no longer holds
            for f in want:
                if seeded is None or f not in seeded:
                    sub.add(f)
            for f in seed_flts:
                if f not in want:
                    sub.remove(f)
            if self.native:
                self._adopt_vocab_tail(sub)
            amap = np.full(max(64, sub.n_filters + 1), -1, np.int32)
            for flt, aid in want.items():
                laid = sub.aid_of(flt)
                if laid < 0:
                    raise RuntimeError(
                        f"rebuilt filter missing: {flt!r}")
                if laid >= len(amap):
                    grown = np.full(max(2 * len(amap), laid + 1), -1,
                                    np.int32)
                    grown[:len(amap)] = amap
                    amap = grown
                amap[laid] = aid
            self._subs[t] = sub
            self._aid_maps[t] = amap
            self._filters[t] = dict(want)
            self._restack()
        dt = _time.perf_counter() - t0
        self.rebuilds += 1
        if self.metrics is not None:
            self.metrics.set("tpu.mesh.rebuild_s", round(dt, 6))
        log.warning("mesh shard %d rebuilt (%d filters, %s seed) in "
                    "%.3fs — canary gates re-admission", t, len(want),
                    "segment" if seeded is not None else "full", dt)
        return dt

    def _adopt_vocab_tail(self, sub) -> None:
        """``bulk_add``'s warm probe may intern sentinel words past the
        replayed shared sequence: append them to the shared vocab and
        every OTHER table too (ids assign append-only from the same
        prefix, so all vocabs stay identical)."""
        extra = [(w, i) for w, i in sub.vocab.items()
                 if w not in self.vocab]
        for w, _i in sorted(extra, key=lambda kv: kv[1]):
            self.vocab[w] = len(self.vocab) + 1
            for tbl in self._all_tables():
                if tbl is not sub:
                    tbl.intern(w)

    def _seg_seed_filters(self, t: int, segments_dir: Optional[str],
                          expect_epoch: Optional[int],
                          ) -> Optional[Dict[str, int]]:
        """Shard ``t``'s persisted (filter → service aid) snapshot iff
        the manifest's epoch/shape/checksum still match — the rebuild
        seed.  None → the rebuild runs from the live pairs alone."""
        if segments_dir is None or expect_epoch is None:
            return None
        from ..storage.segments import load_segment

        d = self._seg_dir(segments_dir)
        try:
            with open(os.path.join(d, "manifest.json")) as f:
                meta = json.load(f)
            if (meta.get("version") != self.MANIFEST_VERSION
                    or meta.get("tp") != self.tp
                    or meta.get("depth") != self.depth
                    or meta.get("native") != bool(self.native)
                    or meta.get("epoch") != int(expect_epoch)):
                return None
            npz = np.load(os.path.join(d, "aid_maps.npz"))
            arrays = {k: npz[k] for k in npz.files}
            meta_core = {k: meta[k] for k in
                         ("version", "epoch", "tp", "depth", "native")}
            if meta.get("checksum") != self._manifest_checksum(
                    meta_core, arrays):
                return None
            seg = load_segment(os.path.join(d, f"shard{t}.seg.npz"))
            if seg.depth != self.depth:
                return None
            if seg.meta.get("placement_crc") != self._place_crc(
                    self._placement):
                # the segment was cut under a different placement: its
                # filter set is not this shard's under the LIVE map —
                # the full rebuild from service pairs serves instead
                return None
            if seg.kind == "filters":
                sa = np.asarray(arrays[f"sa{t}"], np.int32)
                if len(sa) != len(seg.filters):
                    return None
                return dict(zip(seg.filters, sa.tolist()))
            amap = np.asarray(arrays[f"m{t}"], np.int32)
            return {f: int(amap[aid]) for aid, f in
                    enumerate(seg.accept_filters or [])
                    if f is not None and aid < len(amap)
                    and amap[aid] >= 0}
        except Exception:
            log.warning("mesh rebuild segment seed unavailable; full "
                        "rebuild from service state", exc_info=True)
            return None

    # ------------------------------------------------------------------
    # per-shard segment persistence (opt-in via match.segments.enable)
    # ------------------------------------------------------------------

    @staticmethod
    def _seg_dir(segments_dir: str) -> str:
        return os.path.join(segments_dir, "multichip")

    @staticmethod
    def _place_crc(place: Dict[str, int]) -> int:
        """Canonical crc32 of a placement override map — stamped into
        every per-shard segment's (checksummed) meta so a shard file
        cut under a DIFFERENT placement than the manifest restores is
        rejected (a torn save can leave mixed generations; the epoch
        guard alone can't see a placement-only swap)."""
        return zlib.crc32(json.dumps(
            sorted(place.items()),
            separators=(",", ":")).encode("utf-8"))

    def save_segments(self, segments_dir: str, epoch: int) -> None:
        """WORKER-THREAD step: persist every shard subtable + the
        micro-table (native tables ride the NUL-framed "filters"
        segment kind, Python tables the full "state" kind) plus a
        checksummed manifest carrying the service-table epoch, the
        shared vocab in id order, per-filter service aids, and the
        local→service aid maps.  Cold start seeds from these iff the
        epoch still matches (the ``_seg_join_seed`` idiom)."""
        with self._maint_lock:
            self._save_segments_locked(segments_dir, epoch)

    def _save_segments_locked(self, segments_dir: str, epoch: int) -> None:
        from ..storage.segments import save_segment

        d = self._seg_dir(segments_dir)
        os.makedirs(d, exist_ok=True)
        pcrc = self._place_crc(self._placement)
        arrays: Dict[str, np.ndarray] = {}
        for t, sub in enumerate(self._subs):
            flts = list(self._filters[t])
            save_segment(os.path.join(d, f"shard{t}.seg.npz"), sub,
                         deep={}, routing_aids=set(), filters=flts,
                         extra_meta={"placement_crc": pcrc})
            arrays[f"m{t}"] = np.asarray(self._aid_maps[t], np.int32)
            arrays[f"sa{t}"] = np.asarray(
                [self._filters[t][f] for f in flts], np.int32)
        mflts = list(self._micro_filters)
        save_segment(os.path.join(d, "micro.seg.npz"), self._micro,
                     deep={}, routing_aids=set(), filters=mflts,
                     extra_meta={"placement_crc": pcrc})
        arrays["mm"] = np.asarray(self._micro_amap, np.int32)
        arrays["sam"] = np.asarray(
            [self._micro_filters[f] for f in mflts], np.int32)
        # the shared vocab in id order (NUL-framed: words may contain
        # '\n', never NUL) — the restore replays it FIRST so every
        # fresh native vocab assigns the same ids
        words = [w for w, _i in sorted(self.vocab.items(),
                                       key=lambda kv: kv[1])]
        arrays["vw"] = np.frombuffer(
            "\x00".join(words).encode("utf-8"), np.uint8).copy()
        # v3: the popularity placement override map (NUL-framed roots
        # + parallel int32 owners, deterministic order) — cold start
        # restores placement BEFORE the restack, so the restored
        # partition and the shard_of it will serve under agree
        proots = sorted(self._placement)
        arrays["pr"] = (np.frombuffer(
            "\x00".join(proots).encode("utf-8"), np.uint8).copy()
            if proots else np.zeros(0, np.uint8))
        arrays["ps"] = np.asarray(
            [self._placement[w] for w in proots], np.int32)
        meta = {"version": self.MANIFEST_VERSION, "epoch": int(epoch),
                "tp": self.tp, "depth": self.depth,
                "native": bool(self.native)}
        digest = self._manifest_checksum(meta, arrays)
        np.savez(os.path.join(d, "aid_maps.npz"), **arrays)
        # the manifest lands LAST (atomic replace = the commit point):
        # a crash mid-save leaves either the old manifest or none
        tmp = os.path.join(d, "manifest.json.tmp")
        with open(tmp, "w") as f:
            json.dump({**meta, "checksum": digest}, f, sort_keys=True)
        os.replace(tmp, os.path.join(d, "manifest.json"))
        self._persist_due = False

    @staticmethod
    def _manifest_checksum(meta: dict, maps: Dict[str, np.ndarray]) -> str:
        import hashlib

        h = hashlib.sha1(json.dumps(meta, sort_keys=True).encode())
        for k in sorted(maps):
            h.update(k.encode())
            h.update(np.ascontiguousarray(maps[k]).tobytes())
        return h.hexdigest()

    def _restore_sub(self, seg, arrays, sa_key: str):
        """One subtable + its (filter → service aid) dict from a
        segment: native replays the NUL/newline filter blob through
        ``bulk_add`` and rebuilds aids via ``aid_of`` (robust to
        bulk-order drift); Python restores the full state."""
        from ..storage.segments import restore_incremental

        if seg.kind == "filters":
            if not self.native:
                raise ValueError("filters-kind segment without native")
            sub = self._new_sub()
            sub.bulk_intern(self._restored_words)
            flts = list(seg.filters)
            sub.bulk_add(flts)
            sa = np.asarray(arrays[sa_key], np.int32)
            if len(sa) != len(flts):
                raise ValueError("service-aid array length mismatch")
            amap = np.full(max(64, sub.n_filters + 1), -1, np.int32)
            fdict: Dict[str, int] = {}
            for f, service_aid in zip(flts, sa.tolist()):
                laid = sub.aid_of(f)
                if laid < 0:
                    raise ValueError(f"restored filter missing: {f!r}")
                if laid >= len(amap):
                    grown = np.full(
                        max(2 * len(amap), laid + 1), -1, np.int32)
                    grown[:len(amap)] = amap
                    amap = grown
                amap[laid] = service_aid
                fdict[f] = service_aid
            return sub, amap, fdict
        if seg.kind != "state" or self.native:
            raise ValueError(f"unexpected segment kind {seg.kind!r}")
        sub = restore_incremental(seg)
        amap_key = "m" + sa_key[2:] if sa_key.startswith("sa") else "mm"
        amap = np.asarray(arrays[amap_key], np.int32)
        fdict = {}
        for f in sub.filters():
            laid = sub.aid_of(f)
            if 0 <= laid < len(amap) and amap[laid] >= 0:
                fdict[f] = int(amap[laid])
        return sub, amap, fdict

    def load_segments(self, segments_dir: str, expect_epoch: int) -> bool:
        """Cold start: restore the shard partition from the persisted
        per-shard segments iff the manifest's service epoch matches the
        just-restored main table (no drift since the save) — else the
        caller rebuilds the partition from the live service state.
        Returns True when seeded."""
        from ..storage.segments import load_segment

        d = self._seg_dir(segments_dir)
        try:
            with open(os.path.join(d, "manifest.json")) as f:
                meta = json.load(f)
            if meta.get("version") != self.MANIFEST_VERSION \
                    or meta.get("tp") != self.tp \
                    or meta.get("depth") != self.depth \
                    or meta.get("native") != bool(self.native) \
                    or meta.get("epoch") != int(expect_epoch):
                return False
            npz = np.load(os.path.join(d, "aid_maps.npz"))
            arrays = {k: npz[k] for k in npz.files}
            want = meta.get("checksum")
            meta_core = {k: meta[k] for k in
                         ("version", "epoch", "tp", "depth", "native")}
            if want != self._manifest_checksum(meta_core, arrays):
                log.warning("multichip manifest checksum mismatch; "
                            "repartition serves")
                return False
            self._restored_words = (
                bytes(np.asarray(arrays["vw"], np.uint8))
                .decode("utf-8").split("\x00")
                if len(arrays.get("vw", ())) else [])
            place: Dict[str, int] = {}
            if len(arrays.get("pr", ())):
                proots = (bytes(np.asarray(arrays["pr"], np.uint8))
                          .decode("utf-8").split("\x00"))
                powners = np.asarray(arrays["ps"], np.int32).tolist()
                if len(proots) != len(powners) or any(
                        not 0 <= o < self.tp for o in powners):
                    log.warning("multichip placement map malformed; "
                                "repartition serves")
                    return False
                place = dict(zip(proots, powners))
            pcrc = self._place_crc(place)
            subs, amaps, fdicts = [], [], []
            for t in range(self.tp):
                seg = load_segment(os.path.join(d, f"shard{t}.seg.npz"))
                if seg.depth != self.depth:
                    return False
                if seg.meta.get("placement_crc") != pcrc:
                    # a torn save left this shard file cut under a
                    # different placement than the manifest restores
                    log.warning("multichip shard %d segment placement "
                                "skew; repartition serves", t)
                    return False
                sub, amap, fdict = self._restore_sub(
                    seg, arrays, f"sa{t}")
                subs.append(sub)
                amaps.append(amap)
                fdicts.append(fdict)
            mseg = load_segment(os.path.join(d, "micro.seg.npz"))
            if mseg.depth != self.depth:
                return False
            if mseg.meta.get("placement_crc") != pcrc:
                log.warning("multichip micro segment placement skew; "
                            "repartition serves")
                return False
            micro, micro_amap, micro_fdict = self._restore_sub(
                mseg, arrays, "sam")
        except FileNotFoundError:
            return False
        except Exception:
            log.warning("multichip segment load failed; repartition "
                        "serves", exc_info=True)
            return False
        if self.native:
            # bulk_add's warm probe interns a few sentinel words past
            # the persisted list; every table replayed the identical
            # sequence, so adopt one table's (refreshed) vocab as the
            # shared encode vocab and guard that they all agree —
            # otherwise the next live intern would assign drifting ids
            vocab = dict(subs[0].vocab)
            for tbl in [*subs[1:], micro]:
                if tbl.vocab != vocab:
                    log.warning("multichip shard vocabs diverged; "
                                "repartition serves")
                    return False
        else:
            # every shard persisted the SAME shared vocab — rebind
            # them to one dict instance so future interning stays
            # consistent
            vocab = subs[0].vocab
            for tbl in [*subs[1:], micro]:
                if tbl.vocab != vocab:
                    log.warning("multichip shard vocabs diverged; "
                                "repartition serves")
                    return False
                tbl.vocab = vocab
        with self._lock:
            self.vocab = vocab
            self._subs = subs
            self._aid_maps = amaps
            self._filters = fdicts
            self._micro = micro
            self._micro_amap = micro_amap
            self._micro_filters = micro_fdict
            # placement restores FIRST relative to the word_owner
            # resync the pending restack performs — the restored
            # partition was saved under exactly this map
            self._placement = place
            self._placement_next = None
            self._word_owner = np.zeros(1024, np.int32)
            self._word_owner_n = 0
            self._pending = []
            self._rebuild_pairs = None
            self._restack_due = True
            self._arrs = None
        self.seeded_from_segments = True
        return True

    def info(self) -> dict:
        return {
            "devices": self.n_devices,
            "mesh": {"dp": self.dp, "tp": self.tp},
            "ready": self.ready,
            "native": self.native,
            "ep": self.ep,
            "ep_compact": self.ep_compact,
            "gen": self.gen,
            "dispatches": self.dispatches,
            "ep_dispatches": self.ep_dispatches,
            "failovers": self.failovers,
            "applies": self.applies,
            "restacks": self.restacks,
            "dead_shards": sorted(self._dead),
            "shard_filters": [sub.n_filters for sub in self._subs],
            "micro_filters": len(self._micro_filters),
            "seeded_from_segments": self.seeded_from_segments,
            "degraded": self.degraded,
            "mesh_state": ("healthy", "degraded",
                           "cpu-only")[self.mesh_state()],
            "fail_counts": {str(t): c for t, c in
                            sorted(self._fail_counts.items())},
            "degraded_batches": self.degraded_batches,
            "cpu_filled_rows": self.cpu_filled_rows,
            "rebuilds": self.rebuilds,
            "readmit_canary_fails": self.readmit_canary_fails,
            "ep_overflow_ewma": round(self._ov_ewma, 6),
            "ep_autotune": self.ep_autotune,
            "ep_cap_class": self._cap_class,
            "ep_resizes": self.ep_resizes,
            "ep_rebalances": self.ep_rebalances,
            "placement_overrides": len(self._placement),
        }
