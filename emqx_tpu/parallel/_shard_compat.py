"""shard_map across jax versions.

jax moved ``shard_map`` from ``jax.experimental.shard_map`` to the top
level and renamed ``check_rep`` to ``check_vma`` along the way.  The
parallel modules are written against the current spelling; this wrapper
keeps them importable (and runnable) on the older API.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # jax < 0.5 ships it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = set(inspect.signature(_shard_map).parameters)

__all__ = ["shard_map"]


def shard_map(*args, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(*args, **kwargs)
