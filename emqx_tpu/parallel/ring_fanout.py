"""Config-5 device stage: ring-tiled accept-bitmap OR-reduction.

When the accept→subscriber bitmap outgrows one chip's HBM (BASELINE
config 5: 100k retained × 1M wildcard subs ⇒ multi-GB of bitmap rows),
its ROWS (accept ids) shard over a ``ring`` mesh axis.  Every shard OR-
assembles the contribution of the accept ids it owns, then partial
per-topic bitmaps rotate around the ring with ``ppermute`` accumulating
bitwise-OR — the ring-attention blockwise schedule with OR in place of
softmax-weighted sums (SURVEY.md §2.5 "Ring/blockwise bitmap tiles",
§5.7).  After ``ring-1`` hops every shard holds the full reduction, so
the result leaves the mesh dp-sharded and ring-replicated with no
all-gather.

Comms cost per batch: (ring-1) hops × (B/dp × W) words over ICI —
bandwidth-optimal for a reduction whose operand never fits one chip.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ._shard_compat import shard_map

__all__ = ["build_ring_fanout", "build_ring_fanout_compact",
           "shard_bitmap_rows"]


def shard_bitmap_rows(bitmap: np.ndarray, ring: int) -> np.ndarray:
    """Pad the (F+1, W) accept bitmap so ``ring`` divides the row count
    (pad rows are all-zero ⇒ OR-inert).  The LAST row must stay the
    all-zero invalid-slot row within its shard — instead of relying on
    position we simply require callers to index invalid slots to the
    global padded last row, which is zero by construction."""
    rows, w = bitmap.shape
    pad = (-rows) % ring
    if pad:
        bitmap = np.concatenate(
            [bitmap, np.zeros((pad, w), bitmap.dtype)], axis=0
        )
    return bitmap


def build_ring_fanout(mesh: Mesh, active_slots: int = 16,
                      max_matches: int = 32):
    """Returns jitted ``step(words, lens, is_sys, node, edge, seeds,
    bitmap_rows) -> (B, W) uint32`` with:

    * batch arrays sharded ``(dp,)`` and replicated over ``ring``;
    * NFA arrays replicated (the match runs identically on every ring
      shard — cheaper than broadcasting matches, and the tables are the
      small operand in config 5);
    * ``bitmap_rows`` (F_pad, W) sharded ``(ring, None)`` — the operand
      that doesn't fit one chip.
    """
    from ..ops.match_kernel import nfa_match

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P("dp", None), P("dp"), P("dp"),
            P(), P(), P(),
            P("ring", None),
        ),
        out_specs=P("dp", None),
        check_vma=False,
    )
    def step(words, lens, is_sys, node_tab, edge_tab, seeds, rows_local):
        res = nfa_match(
            words, lens, is_sys, node_tab, edge_tab, seeds,
            active_slots=active_slots, max_matches=max_matches,
        )
        ring_idx = jax.lax.axis_index("ring")
        f_local = rows_local.shape[0]
        lo = ring_idx * f_local
        m = res.matches                                    # (Bl, K) global aids
        local = m - lo
        valid = (m >= 0) & (local >= 0) & (local < f_local)
        safe = jnp.where(valid, local, 0)
        gathered = rows_local[safe]                        # (Bl, K, W)
        gathered = jnp.where(valid[:, :, None], gathered, jnp.uint32(0))
        partial_or = jax.lax.reduce(
            gathered, np.uint32(0), jax.lax.bitwise_or, (1,)
        )                                                  # (Bl, W)

        # ring accumulate: rotate partials, OR as they come around
        nring = mesh.shape["ring"]
        perm = [(j, (j + 1) % nring) for j in range(nring)]
        acc = partial_or
        chunk = partial_or
        for _ in range(nring - 1):
            chunk = jax.lax.ppermute(chunk, "ring", perm)
            acc = acc | chunk
        return acc

    return jax.jit(step)


def build_ring_fanout_compact(mesh: Mesh, cap_row: int = 64,
                              active_slots: int = 16,
                              max_matches: int = 32):
    """Dense-id ring: same contract as :func:`build_ring_fanout`
    (returns the fully-reduced ``(B, W) uint32`` bitmap, plus a
    ``(B,) int32`` truncation flag), but what ROTATES on the ring is
    each shard's compacted per-topic subscriber-id list — (Bl, cap_row)
    ints per hop instead of the (Bl, W) bitmap tile, so ICI traffic is
    proportional to matches, not table width (W words/topic at config-5
    scale vs tens of matches).  Each hop scatters the incoming dense
    ids back into the local accumulator bitmap (scatter-add into a
    zero tile, then OR — ids are unique within a row, so add ≡ OR),
    which also dedups subscribers reached via filters owned by
    different ring shards.  A row whose LOCAL partial popcount exceeds
    ``cap_row`` is flagged truncated (psum over the ring) — the
    fail-open set callers re-run on the host."""
    from ..ops.match_kernel import nfa_match
    from .sharded_match import compact_bitmap_ids

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P("dp", None), P("dp"), P("dp"),
            P(), P(), P(),
            P("ring", None),
        ),
        out_specs=(P("dp", None), P("dp")),
        check_vma=False,
    )
    def step(words, lens, is_sys, node_tab, edge_tab, seeds, rows_local):
        res = nfa_match(
            words, lens, is_sys, node_tab, edge_tab, seeds,
            active_slots=active_slots, max_matches=max_matches,
        )
        ring_idx = jax.lax.axis_index("ring")
        f_local = rows_local.shape[0]
        lo = ring_idx * f_local
        m = res.matches
        local = m - lo
        valid = (m >= 0) & (local >= 0) & (local < f_local)
        safe = jnp.where(valid, local, 0)
        gathered = rows_local[safe]
        gathered = jnp.where(valid[:, :, None], gathered, jnp.uint32(0))
        partial_or = jax.lax.reduce(
            gathered, np.uint32(0), jax.lax.bitwise_or, (1,)
        )                                                  # (Bl, W)
        Bl, W = partial_or.shape
        ids, n, over = compact_bitmap_ids(partial_or, cap_row)

        def bits_of(chunk_ids):
            """Dense (Bl, cap_row) ids → (Bl, W) bitmap tile: scatter
            1<<bit into a zero tile (unique bits per row ⇒ add ≡ OR);
            -1 pads drop via an out-of-bounds word index."""
            ok = chunk_ids >= 0
            word = jnp.where(ok, chunk_ids >> 5, W)
            bit = jnp.where(
                ok,
                jnp.uint32(1) << (chunk_ids & 31).astype(jnp.uint32),
                jnp.uint32(0))
            rows = jnp.broadcast_to(
                jnp.arange(Bl)[:, None], chunk_ids.shape)
            z = jnp.zeros((Bl, W), jnp.uint32)
            return z.at[rows, word].add(bit, mode="drop")

        # ring accumulate: rotate the DENSE id lists, re-expand each
        # incoming chunk into the local accumulator
        nring = mesh.shape["ring"]
        perm = [(j, (j + 1) % nring) for j in range(nring)]
        acc = partial_or
        chunk = ids
        for _ in range(nring - 1):
            chunk = jax.lax.ppermute(chunk, "ring", perm)
            acc = acc | bits_of(chunk)
        truncated = jax.lax.psum(over, "ring")
        return acc, truncated

    return jax.jit(step)
