"""Expert-parallel trie partition with all-to-all topic routing.

SURVEY.md §2.5's last two rows: the reference partitions routes by
owning node ("EP" analog) and our mandated counterpart shards the TRIE
by top-level topic word, routing each topic of the batch to the shard
owning its root prefix with a **ragged all-to-all** (the Ulysses-style
ingest→dispatch reshard).  Worth it when one chip's HBM can't hold the
whole table, or hot prefixes need isolation.

Pipeline (one `shard_map` over an ``ep`` axis):

1. ingest: topics arrive sharded arbitrarily over ``ep`` (B/E each);
2. each shard buckets its topics by owner (= root word id % E —
   device-computable and identical to the host partition rule) into an
   (E, C) capacity grid via the cumsum-compaction trick; bucket
   overflow is COUNTED and those topics fail open to the host trie;
3. ``all_to_all`` flips source↔owner: each shard now holds every topic
   it owns;
4. the local (per-partition) NFA matches them — root-level ``+``/``#``
   filters are replicated into every partition, so single-shard
   answers are complete;
5. results ``all_to_all`` back and scatter into ingest order.

Tables are built per partition with SHARED shapes and a SHARED vocab
(so one encode serves all shards) by :func:`build_partitions`.

This module remains the standalone dryrun (bench ``prefix_ep``,
MULTICHIP_r03+: parts=4, overflow=0).  The SERVING implementation of
the same router lives in :mod:`.multichip_serve` (ISSUE 16,
``match.multichip.ep.enable``): there the bucket/route step rides the
serve backend's dp×tp mesh, the owner merges a replicated
wildcard-root micro-table into its answer segment instead of
replicating root wildcards into every partition, and overflow joins
the serve plane's CPU-trie fail-open set.

:func:`greedy_balance` is the partition-balancing core the serving
plane's popularity-aware placement (ISSUE 20,
``match.multichip.ep.autotune.enable``) runs at compaction cadence: a
pure strict-improvement greedy over observed per-root loads, so the
same function is unit-testable here and auditable against the dryrun's
uniform ``owner_of`` rule it overrides.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ._shard_compat import shard_map

from .. import topic as T
from ..ops.incremental import IncrementalNfa

__all__ = ["EpTables", "build_partitions", "build_ep_matcher",
           "owner_of", "greedy_balance"]


def owner_of(flt_or_topic: str, vocab: Dict[str, int], n_parts: int) -> int:
    """Partition rule: root word's vocab id mod E (UNKNOWN → 0)."""
    root = flt_or_topic.split("/", 1)[0]
    return vocab.get(root, 0) % n_parts


def greedy_balance(loads: Dict[str, float], owners: Dict[str, int],
                   n_parts: int, budget: int,
                   ) -> Tuple[Dict[str, int], int]:
    """Greedy hot-root reassignment: repeatedly move the hottest
    strictly-improving root from the most- to the least-loaded
    partition, at most ``budget`` times.  A root heavier than the
    hi−lo gap never moves (it would only swap which partition is hot),
    so every move shrinks the spread and the loop terminates early
    when no improving move remains.  Pure: returns ``(new owners,
    moves made)`` without touching the inputs."""
    owners = dict(owners)
    shard_load = np.zeros(max(1, n_parts), np.float64)
    for w, o in owners.items():
        shard_load[o] += loads.get(w, 0.0)
    moved = 0
    for _ in range(max(0, budget)):
        hi = int(np.argmax(shard_load))
        lo = int(np.argmin(shard_load))
        gap = float(shard_load[hi] - shard_load[lo])
        best = None
        best_load = 0.0
        for w, o in owners.items():
            lw = loads.get(w, 0.0)
            if o == hi and 0.0 < lw < gap and lw > best_load:
                best, best_load = w, lw
        if best is None:
            break
        owners[best] = lo
        shard_load[hi] -= best_load
        shard_load[lo] += best_load
        moved += 1
    return owners, moved


class EpTables(NamedTuple):
    node_tabs: np.ndarray     # (E, S, 4) int32
    edge_tabs: np.ndarray     # (E, Hb, 16) int32
    seeds: np.ndarray         # (E, 2) int32
    vocab: Dict[str, int]     # SHARED across partitions
    accept_filters: List[List[str]]  # per-partition aid -> filter
    depth: int

    @property
    def n_parts(self) -> int:
        return int(self.node_tabs.shape[0])


def build_partitions(filters: Sequence[str], n_parts: int,
                     depth: int = 8) -> EpTables:
    """Partition ``filters`` by root word into ``n_parts`` NFA tables
    with uniform shapes + one shared vocab.  Root-level wildcards
    (``+``/``#`` first word) replicate into every partition."""
    # shared vocab: intern every literal word once, in a stable order
    vocab: Dict[str, int] = {}
    for f in sorted(set(filters)):
        for w in T.words(f):
            if w not in ("+", "#") and w not in vocab:
                vocab[w] = len(vocab) + 1

    parts: List[List[str]] = [[] for _ in range(n_parts)]
    for f in sorted(set(filters)):
        root = f.split("/", 1)[0]
        if root in ("+", "#"):
            for p in parts:
                p.append(f)
        else:
            parts[owner_of(f, vocab, n_parts)].append(f)

    incs = []
    for p in parts:
        inc = IncrementalNfa(depth=depth)
        inc.vocab = vocab  # shared interning (append-only, single thread)
        for f in p:
            inc.add(f)
        incs.append(inc)
    S = max(inc.S for inc in incs)
    Hb = max(inc.Hb for inc in incs)
    # re-home any undersized tables onto the common shapes
    rebuilt = []
    for inc, p in zip(incs, parts):
        if inc.S != S or inc.Hb != Hb:
            fresh = IncrementalNfa(depth=depth, state_bucket=S,
                                   edge_bucket=Hb)
            fresh.vocab = vocab
            for f in p:
                fresh.add(f)
            assert fresh.S == S and fresh.Hb == Hb, "table grew past max"
            inc = fresh
        rebuilt.append(inc)
    return EpTables(
        node_tabs=np.stack([i.node_tab for i in rebuilt]),
        edge_tabs=np.stack([i.edge_tab for i in rebuilt]),
        seeds=np.stack([i.seeds for i in rebuilt]),
        vocab=vocab,
        accept_filters=[list(i.accept_filters) for i in rebuilt],
        depth=depth,
    )


class EpResult(NamedTuple):
    matches: jax.Array      # (B, K) int32 PER-PARTITION accept ids
    owners: jax.Array       # (B,) int32 owning partition of each topic
    n_matches: jax.Array    # (B,) int32
    overflow: jax.Array     # (B,) int32 1 = bucket overflowed (host re-run)


def build_ep_matcher(mesh: Mesh, capacity: int, active_slots: int = 16,
                     max_matches: int = 32):
    """Jitted ``step(words, lens, is_sys, node_tabs, edge_tabs, seeds)
    -> EpResult`` over the ``ep`` axis.  ``capacity`` is the per-
    (source, owner) bucket size; overflowing topics are flagged for the
    host path (fail open, same discipline as kernel spills)."""
    from ..ops.match_kernel import nfa_match

    E = mesh.shape["ep"]
    C = capacity

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("ep", None), P("ep"), P("ep"),
                  P("ep", None, None), P("ep", None, None), P("ep", None)),
        out_specs=EpResult(P("ep", None), P("ep"), P("ep"), P("ep")),
        check_vma=False,
    )
    def step(words, lens, is_sys, node_tab, edge_tab, seeds):
        Bl, D = words.shape
        # one table per shard, or the device routing rule (% E) and the
        # host partition rule (% n_parts) silently disagree
        assert node_tab.shape[0] == 1, (
            f"tables built for {node_tab.shape[0] * E} partitions but the "
            f"mesh has ep={E}; build_partitions(n_parts) must match"
        )
        node_tab = node_tab[0]
        edge_tab = edge_tab[0]
        seeds = seeds[0]
        owner = words[:, 0] % E                             # (Bl,)
        # bucket my topics by owner: rank within each owner group
        onehot_owner = owner[:, None] == jnp.arange(E)[None, :]  # (Bl, E)
        rank = jnp.cumsum(onehot_owner, axis=0) - 1         # (Bl, E)
        my_rank = jnp.take_along_axis(
            rank, owner[:, None], axis=1)[:, 0]             # (Bl,)
        overflow = (my_rank >= C).astype(jnp.int32)
        keep = overflow == 0
        # overflowed rows must scatter NOWHERE (an in-range dummy slot
        # would clobber a legitimate topic): route them out of range and
        # let mode="drop" discard the write
        owner_idx = jnp.where(keep, owner, E)
        slot = jnp.where(keep, my_rank, 0)
        # scatter topics into the (E, C) grid
        grid_w = jnp.zeros((E, C, D), jnp.int32)
        grid_l = jnp.full((E, C), D + 2, jnp.int32)         # inert pad
        grid_s = jnp.ones((E, C), bool)
        src = jnp.arange(Bl)
        grid_w = grid_w.at[owner_idx, slot].set(words, mode="drop")
        grid_l = grid_l.at[owner_idx, slot].set(lens, mode="drop")
        grid_s = grid_s.at[owner_idx, slot].set(is_sys, mode="drop")
        # remember which source row filled each bucket slot
        grid_src = jnp.full((E, C), -1, jnp.int32).at[owner_idx, slot].set(
            src, mode="drop")

        # ragged all-to-all: (owner, C, ...) leaves, (source, C, ...) lands
        w2 = jax.lax.all_to_all(grid_w, "ep", 0, 0, tiled=False)
        l2 = jax.lax.all_to_all(grid_l, "ep", 0, 0, tiled=False)
        s2 = jax.lax.all_to_all(grid_s, "ep", 0, 0, tiled=False)

        res = nfa_match(
            w2.reshape(E * C, D), l2.reshape(E * C), s2.reshape(E * C),
            node_tab, edge_tab, seeds,
            active_slots=active_slots, max_matches=max_matches,
        )
        K = res.matches.shape[1]
        m_back = jax.lax.all_to_all(
            res.matches.reshape(E, C, K), "ep", 0, 0)       # (E, C, K)
        n_back = jax.lax.all_to_all(
            res.n_matches.reshape(E, C), "ep", 0, 0)        # (E, C)

        # scatter results into ingest order via the remembered sources
        out_m = jnp.full((Bl, K), -1, jnp.int32)
        out_n = jnp.zeros((Bl,), jnp.int32)
        flat_src = grid_src.reshape(E * C)
        safe = jnp.where(flat_src >= 0, flat_src, Bl)       # Bl = dropped
        out_m = out_m.at[safe].set(m_back.reshape(E * C, K), mode="drop")
        out_n = out_n.at[safe].set(n_back.reshape(E * C), mode="drop")
        return EpResult(out_m, owner, out_n, overflow)

    return jax.jit(step)
