"""Multi-chip publish step: DP-sharded NFA match + TP-sharded subscriber
bitmaps with ICI reductions.

This is the TPU-native counterpart of the reference's cluster fan-out
(``emqx_broker:publish`` → route → ``gen_rpc`` forward → per-node dispatch,
SURVEY.md §3.4), restructured for a device mesh (§2.5):

* the NFA tables are **replicated** on every chip (they are the "model");
* the topic batch is sharded over ``dp`` — each chip matches its rows with
  zero communication;
* the accept→subscriber bitmap matrix is sharded **column-wise** over
  ``tp`` — each chip OR-assembles its slice of every matched row locally,
  and per-topic totals (e.g. shared-group member counts) are ``psum``'d
  over ``tp`` (BASELINE config 4's "$share fan-out with subscriber-bitmap
  reduction").

Everything runs inside one ``shard_map`` so XLA sees the whole step and
schedules the collectives on ICI.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._shard_compat import shard_map

from ..ops.compiler import NfaTable
from ..ops.match_kernel import nfa_match

__all__ = ["CompactFanoutResult", "FanoutResult",
           "build_sharded_matcher", "build_sharded_matcher_compact",
           "compact_bitmap_ids", "decode_compact_rows",
           "make_accept_bitmap", "or_accept_rows"]


class FanoutResult(NamedTuple):
    bitmap: jax.Array       # (B, W) uint32 — per-topic subscriber bitmap
    n_subscribers: jax.Array  # (B,) int32 — popcount over the full row
    n_matches: jax.Array    # (B,) int32 — matched filter count
    active_overflow: jax.Array  # (B,) int32 per-row spills (fail-open set)
    match_overflow: jax.Array   # (B,) int32 per-row 1 where count > K


class CompactFanoutResult(NamedTuple):
    """Dense-id fan-out (shard-locally compacted): what leaves the mesh
    is proportional to MATCHES, not table width.  ``ids`` holds GLOBAL
    subscriber ids (-1 padded) — each tp shard compacts its own bitmap
    columns with the same popcount + prefix-scan gather the match
    kernel's flat epilogue uses, and tp shards own disjoint subscriber
    ranges, so the per-row union across tp segments is a plain
    concatenation (no dedup pass)."""

    ids: jax.Array          # (B, tp·cap_row) int32, ascending per segment
    counts: jax.Array       # (B, tp) int32 — ids per tp segment
    overflow: jax.Array     # (B, tp) int32 — 1 where a segment truncated
    n_matches: jax.Array    # (B,) int32
    active_overflow: jax.Array  # (B,) int32 (fail-open set)
    match_overflow: jax.Array   # (B,) int32


def make_accept_bitmap(
    table: NfaTable, subscribers_of, n_subs: int, tp: int = 1
) -> np.ndarray:
    """Build the accept-id → subscriber-bitmap matrix (F+1, W) uint32.

    ``subscribers_of(filter) -> iterable[int]`` maps each accept filter to
    subscriber ids in [0, n_subs).  Row F (last) is all-zero and is indexed
    by invalid match slots.  W is padded so tp divides it.
    """
    words = (n_subs + 31) // 32
    if words % tp:
        words += tp - (words % tp)
    F = table.n_accepts
    bm = np.zeros((F + 1, words), np.uint32)
    for aid, flt in enumerate(table.accept_filters):
        for sub in subscribers_of(flt):
            if not 0 <= sub < n_subs:
                raise ValueError(f"subscriber id {sub} out of range")
            bm[aid, sub >> 5] |= np.uint32(1) << np.uint32(sub & 31)
    return bm


def or_accept_rows(accept_bitmap: jax.Array, matches: jax.Array) -> jax.Array:
    """(F+1, W) accept bitmap × (B, K) match ids → (B, W) OR-assembled
    subscriber rows.  Invalid slots (-1) index the all-zero sentinel
    row F.  Shared by every fan-out layout (TP, ring, Ulysses)."""
    F = accept_bitmap.shape[0] - 1
    idx = jnp.where(matches >= 0, matches, F)        # (B, K)
    rows = accept_bitmap[idx]                        # (B, K, W)
    return jax.lax.reduce(
        rows, np.uint32(0), jax.lax.bitwise_or, (1,)
    )


def compact_bitmap_ids(bitmap: jax.Array, cap_row: int,
                       id_base=0) -> Tuple[jax.Array, jax.Array,
                                           jax.Array]:
    """Shard-local bitmap compaction: (B, W) uint32 → dense per-row
    subscriber-id lists, entirely on device.

    The same popcount + prefix-scan gather shape as the match kernel's
    flat epilogue: expand set bits, cumsum positions within the row,
    compare-scatter into a (B, cap_row) buffer (-1 padded, ascending).
    ``id_base`` offsets local bit positions into the GLOBAL subscriber
    id space (a tp shard passes its column offset).  Returns
    ``(ids, counts, overflow)`` with overflow = 1 where a row's
    popcount exceeded ``cap_row`` (fail-open set — the host re-runs
    those rows against the full bitmap)."""
    B, W = bitmap.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((bitmap[:, :, None] >> shifts) & jnp.uint32(1)) \
        .astype(jnp.int32).reshape(B, W * 32)               # (B, W·32)
    sub = id_base + jnp.arange(W * 32, dtype=jnp.int32)     # global ids
    n = jnp.sum(bits, axis=1)
    pos = jnp.cumsum(bits, axis=1) - 1
    pos = jnp.where(bits > 0, pos, cap_row)                 # OOB-drop
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], pos.shape)
    out = jnp.full((B, cap_row), -1, jnp.int32)
    ids = out.at[rows, pos].set(
        jnp.broadcast_to(sub[None, :], pos.shape), mode="drop")
    overflow = (n > cap_row).astype(jnp.int32)
    return ids, n, overflow


def decode_compact_rows(ids: np.ndarray, counts: np.ndarray,
                        cap_row: int):
    """Host decode of a :class:`CompactFanoutResult`: per-topic global
    subscriber-id arrays, tp segments concatenated.  ``ids`` is
    (B, tp·cap_row), ``counts`` (B, tp); segments are disjoint by
    construction so no dedup is needed.  Truncated segments (overflow)
    decode to their surviving prefix — callers re-run flagged rows."""
    B, tp = counts.shape
    out = []
    for r in range(B):
        segs = [ids[r, t * cap_row:t * cap_row
                    + min(int(counts[r, t]), cap_row)]
                for t in range(tp)]
        out.append(np.concatenate(segs) if segs else
                   np.empty(0, np.int32))
    return out


def build_sharded_matcher_compact(
    mesh: Mesh,
    cap_row: int = 64,
    active_slots: int = 16,
    max_matches: int = 32,
):
    """Dense-id twin of :func:`build_sharded_matcher`: each (dp, tp)
    shard OR-assembles its bitmap slice locally, then COMPACTS it on
    shard — the cross-chip output is per-topic dense global subscriber
    ids + counts (4·(tp·cap_row + tp) bytes/topic, matches-proportional
    with cap_row sized to the fan-out tail) instead of the full (B, W)
    bitmap tile (W words/topic ≈ 1.2 MB/topic at 10M filters).  The
    readback-side contract mirrors the serve plane's two-phase d2h:
    counts first, then the dense segments."""
    repl = P()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P("dp", None),  # words
            P("dp"),        # lens
            P("dp"),        # is_sys
            repl, repl, repl,  # NFA arrays
            P(None, "tp"),  # accept_bitmap columns
        ),
        out_specs=CompactFanoutResult(
            ids=P("dp", "tp"),
            counts=P("dp", "tp"),
            overflow=P("dp", "tp"),
            n_matches=P("dp"),
            active_overflow=P("dp"),
            match_overflow=P("dp"),
        ),
        check_vma=False,
    )
    def step(words, lens, is_sys, node_tab, edge_tab, seeds,
             accept_bitmap):
        res = nfa_match(
            words, lens, is_sys, node_tab, edge_tab, seeds,
            active_slots=active_slots, max_matches=max_matches,
        )
        bitmap = or_accept_rows(accept_bitmap, res.matches)  # (Bl, Wl)
        # local columns → global subscriber ids: tp shard t owns words
        # [t·Wl, (t+1)·Wl) of the padded bitmap row
        base = jax.lax.axis_index("tp") * bitmap.shape[1] * 32
        ids, n, over = compact_bitmap_ids(bitmap, cap_row, id_base=base)
        return CompactFanoutResult(
            ids=ids,
            counts=n[:, None],
            overflow=over[:, None],
            n_matches=res.n_matches,
            active_overflow=res.active_overflow,
            match_overflow=res.match_overflow,
        )

    return jax.jit(step)


def build_sharded_matcher(
    mesh: Mesh,
    active_slots: int = 16,   # keep in lockstep with nfa_match defaults so
    max_matches: int = 32,    # sharded/unsharded paths agree on truncation
):
    """Return a jitted ``step(words, lens, is_sys, *nfa_arrays, accept_bitmap)
    -> FanoutResult`` sharded over the mesh.

    Input layouts: batch arrays sharded over ``dp``; NFA arrays replicated;
    ``accept_bitmap`` (F+1, W) sharded over ``tp`` columns.  Output bitmap
    is (dp, tp)-sharded; counts are dp-sharded (psum'd over tp).
    """
    repl = P()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P("dp", None),  # words
            P("dp"),        # lens
            P("dp"),        # is_sys
            repl, repl, repl,  # NFA arrays (node_tab, edge_tab, seeds)
            P(None, "tp"),  # accept_bitmap columns
        ),
        out_specs=FanoutResult(
            bitmap=P("dp", "tp"),
            n_subscribers=P("dp"),
            n_matches=P("dp"),
            active_overflow=P("dp"),
            match_overflow=P("dp"),
        ),
        check_vma=False,
    )
    def step(words, lens, is_sys, node_tab, edge_tab, seeds, accept_bitmap):
        res = nfa_match(
            words, lens, is_sys, node_tab, edge_tab, seeds,
            active_slots=active_slots, max_matches=max_matches,
        )
        bitmap = or_accept_rows(accept_bitmap, res.matches)  # (Bl, Wl)
        # per-topic total subscribers: popcount local slice, psum over tp
        local = jnp.sum(
            jax.lax.population_count(bitmap).astype(jnp.int32), axis=1
        )
        total = jax.lax.psum(local, "tp")
        # per-row overflow rides the dp sharding like the other outputs —
        # the host re-runs exactly the spilled rows on the trie
        return FanoutResult(
            bitmap=bitmap,
            n_subscribers=total,
            n_matches=res.n_matches,
            active_overflow=res.active_overflow,
            match_overflow=res.match_overflow,
        )

    return jax.jit(step)
