"""Multi-chip parallelism: mesh construction and sharded match/fan-out."""

from .mesh import make_mesh, pick_shape
from .sharded_match import (
    FanoutResult,
    build_sharded_matcher,
    make_accept_bitmap,
)

__all__ = [
    "make_mesh",
    "pick_shape",
    "FanoutResult",
    "build_sharded_matcher",
    "make_accept_bitmap",
]
