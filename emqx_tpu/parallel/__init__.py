"""Multi-chip parallelism: mesh construction and sharded match/fan-out."""

from .mesh import make_mesh, pick_shape
from .multichip_serve import (
    MultichipMatcher,
    ShardDead,
    build_multichip_step,
    serve_mesh_shape,
    shard_of_filter,
)
from .multihost import MultihostRuntime, dcn_env, hybrid_mesh_from
from .prefix_ep import EpTables, build_ep_matcher, build_partitions, owner_of
from .ring_fanout import (
    build_ring_fanout,
    build_ring_fanout_compact,
    shard_bitmap_rows,
)
from .shared_group import build_shared_selector, host_pick, make_group_masks
from .sharded_match import (
    CompactFanoutResult,
    FanoutResult,
    build_sharded_matcher,
    build_sharded_matcher_compact,
    compact_bitmap_ids,
    decode_compact_rows,
    make_accept_bitmap,
)
from .ulysses import (
    UlyssesResult,
    build_reshard,
    build_ulysses_step,
    build_unreshard,
)

__all__ = [
    "make_mesh",
    "pick_shape",
    "MultichipMatcher",
    "ShardDead",
    "build_multichip_step",
    "serve_mesh_shape",
    "shard_of_filter",
    "MultihostRuntime",
    "dcn_env",
    "hybrid_mesh_from",
    "CompactFanoutResult",
    "FanoutResult",
    "build_sharded_matcher",
    "build_sharded_matcher_compact",
    "compact_bitmap_ids",
    "decode_compact_rows",
    "make_accept_bitmap",
    "build_shared_selector",
    "make_group_masks",
    "host_pick",
    "build_ring_fanout",
    "build_ring_fanout_compact",
    "shard_bitmap_rows",
    "EpTables",
    "build_partitions",
    "build_ep_matcher",
    "owner_of",
    "UlyssesResult",
    "build_reshard",
    "build_unreshard",
    "build_ulysses_step",
]
