"""Multi-host (DCN) distributed runtime — the gen_rpc/NCCL-backend
analog for scaling past one TPU slice.

Behavioral reference: the reference clusters brokers over ekka/gen_rpc
(SURVEY.md §2.2, §2.5 "collective backend"); its compute frameworks use
NCCL/MPI process groups.  The TPU-native counterpart is
``jax.distributed`` + a HYBRID mesh: inner axes map to ICI (fast
intra-slice interconnect), the outermost axis maps to DCN (the
data-center network between hosts/slices).  XLA then routes each
collective over the right fabric — ``psum`` over a ``dp``-outer axis
becomes a hierarchical reduce (ICI first, one DCN hop per slice), which
is exactly the layout the scaling playbook prescribes (data-parallel
between slices, model/bitmap-parallel inside).

Single-process usage is a no-op passthrough, so the same node code runs
a laptop test, a one-host TPU, and a multi-host fleet:

    rt = MultihostRuntime.from_env()      # env/flags → initialize()
    mesh = rt.hybrid_mesh({"tp": 4}, dcn_axis="dp")
    ... pjit over mesh as usual ...

The matching broker-side responsibility split (who owns which router
shard, takeover on host loss) stays in ``cluster/`` — this module only
owns process bootstrap + mesh construction + the collective fabric.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger(__name__)

__all__ = ["MultihostRuntime", "hybrid_mesh_from", "dcn_env"]


def dcn_env() -> Dict[str, Optional[str]]:
    """The bootstrap triplet, from the environment (the same contract as
    torchrun/MPI launchers: every process gets coordinator + rank +
    world size)."""
    return {
        "coordinator": os.environ.get("EMQX_TPU_COORDINATOR"),
        "process_id": os.environ.get("EMQX_TPU_PROCESS_ID"),
        "num_processes": os.environ.get("EMQX_TPU_NUM_PROCESSES"),
    }


@dataclass
class MultihostRuntime:
    """Process-level distributed state (one per Python process)."""

    num_processes: int = 1
    process_id: Optional[int] = 0
    initialized: bool = False

    @classmethod
    def from_env(cls, coordinator: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None) -> "MultihostRuntime":
        """Initialize ``jax.distributed`` when a coordinator is
        configured; single-process passthrough otherwise."""
        env = dcn_env()
        coordinator = coordinator or env["coordinator"]
        if num_processes is None and env["num_processes"]:
            num_processes = int(env["num_processes"])
        if process_id is None and env["process_id"]:
            process_id = int(env["process_id"])
        if not coordinator or not num_processes or num_processes <= 1:
            return cls()
        # process_id None passes through: JAX auto-detects rank on
        # TPU/GKE launchers — coercing to 0 would make every host claim
        # rank 0 and hang the bootstrap barrier
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        pid = process_id if process_id is not None \
            else getattr(jax, "process_index", lambda: 0)()
        rt = cls(num_processes=num_processes,
                 process_id=pid, initialized=True)
        log.info("jax.distributed up: process %s/%d via %s",
                 rt.process_id, num_processes, coordinator)
        return rt

    # -- mesh construction --------------------------------------------------

    def hybrid_mesh(self, ici_shape: Dict[str, int],
                    dcn_axis: str = "dp",
                    devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        return hybrid_mesh_from(ici_shape, dcn_axis, devices,
                                num_hosts=max(1, self.num_processes))

    def local_devices(self):
        return jax.local_devices()

    def is_coordinator(self) -> bool:
        return self.process_id == 0


def hybrid_mesh_from(ici_shape: Dict[str, int], dcn_axis: str = "dp",
                     devices: Optional[Sequence[jax.Device]] = None,
                     num_hosts: Optional[int] = None) -> Mesh:
    """Build a mesh whose OUTERMOST axis spans hosts (DCN) and whose
    inner axes tile each host's devices (ICI).

    ``ici_shape`` maps inner axis names to sizes and must factor each
    host's device count; ``dcn_axis`` names the cross-host axis.  Device
    order groups each host's devices contiguously (``jax.devices()``
    orders by process), so XLA sees the outer axis as the slow fabric —
    collectives over inner axes never cross DCN.

    On one host this degenerates to an ordinary mesh with a size-1 (or
    host-count-free) outer axis — shardings and pjit code are unchanged
    between the laptop test and the fleet.
    """
    devs = list(devices if devices is not None else jax.devices())
    if num_hosts is None:
        num_hosts = max(1, getattr(jax, "process_count", lambda: 1)())
    if len(devs) % num_hosts:
        raise ValueError(
            f"{len(devs)} devices do not split over {num_hosts} hosts")
    per_host = len(devs) // num_hosts
    inner = int(np.prod(list(ici_shape.values()))) if ici_shape else 1
    if per_host % inner:
        raise ValueError(
            f"ici shape {ici_shape} ({inner}) does not divide the "
            f"per-host device count {per_host}")
    ici_shape = dict(ici_shape)
    leftover = per_host // inner
    # fold any per-host leftover into the dcn axis rows so the full
    # device count is used: outer axis = hosts × leftover
    outer = num_hosts * leftover
    if dcn_axis in ici_shape:
        raise ValueError(f"dcn axis {dcn_axis!r} also in ici_shape")
    shape = {dcn_axis: outer, **ici_shape}
    arr = np.array(devs).reshape(list(shape.values()))
    return Mesh(arr, tuple(shape.keys()))
