"""Device-mesh helpers for the multi-chip match pipeline.

The reference scales by BEAM process scheduling + mria replication
(SURVEY.md §2.5); our counterpart is a ``jax.sharding.Mesh`` with named
axes:

* ``dp`` — publish-batch rows (pure fan-out, no comms until reduction);
* ``tp`` — subscriber-bitmap columns (accept sets sharded; group
  reductions ``psum`` over ICI);
* ``ep`` — trie prefix partition (stage 12; topics ``all_to_all``-routed
  to the shard owning their root word).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "pick_shape"]


def pick_shape(n_devices: int, tp: Optional[int] = None) -> Dict[str, int]:
    """Default mesh factorization: widest power-of-two tp ≤ 4 that divides
    the device count, rest dp."""
    if tp is None:
        tp = 1
        for cand in (4, 2):
            if n_devices % cand == 0:
                tp = cand
                break
    if n_devices % tp:
        raise ValueError(f"tp={tp} does not divide {n_devices} devices")
    return {"dp": n_devices // tp, "tp": tp}


def make_mesh(
    shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = pick_shape(len(devs))
    sizes = list(shape.values())
    n = int(np.prod(sizes))
    if n > len(devs):
        raise ValueError(f"mesh {shape} needs {n} devices, have {len(devs)}")
    arr = np.array(devs[:n]).reshape(sizes)
    return Mesh(arr, tuple(shape.keys()))
