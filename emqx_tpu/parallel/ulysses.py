"""Ulysses-style ingest→dispatch reshard: one ``all_to_all`` flipping
the sharded axis between the two natural layouts of the publish step.

SURVEY.md §2.5 mandates this row explicitly: the reference has no
sequence parallelism, but its per-node dispatch (`emqx_broker:dispatch`
after `gen_rpc` forwarding, SURVEY.md §3.4 [U]) is the role this
collective fills on a mesh.  The two layouts:

* **ingest layout** — the topic BATCH axis is sharded (each device
  matches B/U topics end-to-end and assembles full-width subscriber
  bitmap rows for them).  This is where publishes arrive: whichever
  device's host fed the batch owns those rows.
* **dispatch layout** — the SUBSCRIBER axis is sharded (each device
  owns a column slice of the bitmap over the WHOLE batch).  This is
  what delivery wants: a device (≙ broker node) owns a range of
  sessions and must see every message destined to them.

Ulysses in sequence-parallel attention flips seq-sharded ↔ head-sharded
with one ``all_to_all`` per layer; here the same single collective flips
batch-sharded ↔ subscriber-sharded per publish batch:

    (B/U, W) per device  --all_to_all(split cols, concat rows)-->  (B, W/U)

versus the TP fan-out in :mod:`sharded_match` (which keeps rows sharded
and psums counts), this moves each message's bits to the device that
will deliver them — the collective IS the cluster forward hop, riding
ICI instead of gen_rpc.

The inverse reshard (dispatch→ingest) carries per-subscriber delivery
outcomes (acks, inflight counts) back to the ingest owners.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ._shard_compat import shard_map

from ..ops.match_kernel import nfa_match
from .sharded_match import or_accept_rows

__all__ = [
    "UlyssesResult",
    "build_reshard",
    "build_unreshard",
    "build_ulysses_step",
]


class UlyssesResult(NamedTuple):
    dispatch_bitmap: jax.Array   # (B, W) — column ("u")-sharded: each
    #                              device holds its subscriber slice of
    #                              EVERY message in the batch
    sub_deliveries: jax.Array    # (W*32,) int32 — per-subscriber message
    #                              counts, sharded over "u" like the cols
    n_matches: jax.Array         # (B,) int32 — ingest ("u")-row sharded
    active_overflow: jax.Array   # (B,) int32 — fail-open rows (ingest)


def build_reshard(mesh: Mesh, axis: str = "u"):
    """Jitted ingest→dispatch reshard: rows sharded over ``axis`` in,
    columns sharded over ``axis`` out.  One tiled ``all_to_all``."""

    @partial(shard_map, mesh=mesh,
             in_specs=P(axis, None), out_specs=P(None, axis))
    def reshard(block):            # (B/U, W) local
        return jax.lax.all_to_all(
            block, axis, split_axis=1, concat_axis=0, tiled=True)

    return jax.jit(reshard)


def build_unreshard(mesh: Mesh, axis: str = "u"):
    """Inverse (dispatch→ingest): columns sharded in, rows sharded out —
    the ack/backpressure return path."""

    @partial(shard_map, mesh=mesh,
             in_specs=P(None, axis), out_specs=P(axis, None))
    def unreshard(block):          # (B, W/U) local
        return jax.lax.all_to_all(
            block, axis, split_axis=0, concat_axis=1, tiled=True)

    return jax.jit(unreshard)


def build_ulysses_step(mesh: Mesh, axis: str = "u",
                       active_slots: int = 16, max_matches: int = 32):
    """Full ingest→match→reshard→dispatch step as ONE jitted program.

    ``step(words, lens, is_sys, node_tab, edge_tab, seeds, accept_bitmap)
    -> UlyssesResult``.  Batch arrays arrive row-sharded over ``axis``;
    NFA tables and the accept bitmap are replicated (the ingest side
    assembles full-width rows — that replication is what the single
    all_to_all then amortizes, exactly the Ulysses trade).  The dispatch
    side computes per-subscriber delivery counts for its slice: the
    device-resident work list a delivering node consumes.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis),
                  P(), P(), P(), P()),
        out_specs=UlyssesResult(
            dispatch_bitmap=P(None, axis),
            sub_deliveries=P(axis),
            n_matches=P(axis),
            active_overflow=P(axis),
        ),
        check_vma=False,
    )
    def step(words, lens, is_sys, node_tab, edge_tab, seeds, accept_bitmap):
        res = nfa_match(
            words, lens, is_sys, node_tab, edge_tab, seeds,
            active_slots=active_slots, max_matches=max_matches,
        )
        ingest_bm = or_accept_rows(accept_bitmap, res.matches)  # (Bl, W)
        # THE reshard: batch-sharded full rows → subscriber-sharded
        # full batch, one tiled all_to_all on the wire
        disp = jax.lax.all_to_all(
            ingest_bm, axis, split_axis=1, concat_axis=0, tiled=True)
        # dispatch-side work list: how many messages hit each of MY
        # subscribers (bit b of word w = subscriber w*32+b)
        bits = (disp[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)) \
            & jnp.uint32(1)                                  # (B, Wl, 32)
        per_sub = jnp.sum(bits.astype(jnp.int32), axis=0).reshape(-1)
        return UlyssesResult(
            dispatch_bitmap=disp,
            sub_deliveries=per_sub,
            n_matches=res.n_matches,
            active_overflow=res.active_overflow,
        )

    return jax.jit(step)
