"""MQTT topic algebra: split/validate/join and the wildcard-match oracle.

Behavioral reference: ``apps/emqx/src/emqx_topic.erl`` [U] (reference mount
was empty this round — see SURVEY.md provenance header; semantics follow the
MQTT v3.1.1 / v5.0 specifications and upstream module behavior:
``words/1``, ``match/2``, ``validate/1``, ``wildcard/1``, share parsing).

This module is the **semantics oracle**: every device kernel (the flattened
NFA matcher in ``emqx_tpu.ops``) is property-tested against :func:`match`.
It is deliberately pure Python with no JAX imports.

Key semantics implemented (MQTT spec + emqx behavior):

* Topic levels are separated by ``/``; empty levels are allowed and
  significant (``"a//b"`` has three levels ``['a', '', 'b']``).
* ``+`` matches exactly one level; it must occupy a whole level.
* ``#`` matches zero or more levels; it must be the last level and occupy a
  whole level.  ``"sport/#"`` matches ``"sport"``.
* Topics whose **first** level begins with ``$`` (e.g. ``$SYS/...``) are not
  matched by filters starting with ``+`` or ``#`` (deeper levels are not
  protected: ``$SYS/#`` matches ``$SYS/broker``).
* ``$share/<group>/<real-filter>`` denotes a shared subscription; matching
  operates on the real filter.  ``$queue/<topic>`` is the legacy alias for
  ``$share/$queue/<topic>``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

MAX_TOPIC_LEN = 65535  # bytes, per MQTT spec (emqx ?MAX_TOPIC_LEN)

SHARE_PREFIX = "$share"
QUEUE_PREFIX = "$queue"

__all__ = [
    "TopicError",
    "words",
    "join",
    "levels",
    "wildcard",
    "validate",
    "is_valid",
    "match",
    "match_share",
    "is_sys",
    "is_shared",
    "parse_share",
    "strip_share",
    "make_share",
    "feed_var",
]


class TopicError(ValueError):
    """Raised for malformed topics / filters."""


def words(topic: str) -> List[str]:
    """Split a topic into its levels.  ``"a//b"`` → ``['a', '', 'b']``."""
    return topic.split("/")


def join(ws: Sequence[str]) -> str:
    """Inverse of :func:`words`."""
    return "/".join(ws)


def levels(topic: str) -> int:
    return len(words(topic))


def wildcard(topic_or_words) -> bool:
    """True if the filter contains ``+`` or ``#`` at any level."""
    ws = words(topic_or_words) if isinstance(topic_or_words, str) else topic_or_words
    return any(w in ("+", "#") for w in ws)


def is_sys(topic: str) -> bool:
    """True for ``$``-prefixed topics (``$SYS/...``, ``$queue/...``, ...)."""
    return topic.startswith("$")


# ---------------------------------------------------------------------------
# Validation
# ---------------------------------------------------------------------------

def validate(topic: str, kind: str = "filter") -> None:
    """Validate a topic name (``kind='name'``) or filter (``kind='filter'``).

    Raises :class:`TopicError` on violation.  Mirrors emqx_topic:validate/2:
    non-empty, ≤65535 bytes, no NUL; names admit no wildcards; filters admit
    ``+``/``#`` only as whole levels with ``#`` last; ``$share`` filters
    need a non-empty wildcard-free group and a valid non-empty real filter.
    """
    if kind not in ("name", "filter"):
        raise ValueError(f"bad kind: {kind!r}")
    if topic == "":
        raise TopicError("empty topic")
    if len(topic.encode("utf-8")) > MAX_TOPIC_LEN:
        raise TopicError("topic too long")
    if "\x00" in topic:
        raise TopicError("NUL character in topic")

    if kind == "filter":
        share = parse_share(topic)
        if share is not None:
            group, real = share
            if group == "" or "+" in group or "#" in group:
                raise TopicError(f"invalid $share group: {group!r}")
            if real == "":
                raise TopicError("empty $share real filter")
            if parse_share(real) is not None:
                raise TopicError(f"nested $share filter: {topic!r}")
            return validate(real, "filter")

    ws = words(topic)
    for i, w in enumerate(ws):
        if kind == "name":
            if "+" in w or "#" in w:
                raise TopicError(f"wildcard in topic name: {topic!r}")
        else:
            if w == "#":
                if i != len(ws) - 1:
                    raise TopicError(f"'#' not at last level: {topic!r}")
            elif "#" in w:
                raise TopicError(f"'#' must occupy a whole level: {topic!r}")
            elif w != "+" and "+" in w:
                raise TopicError(f"'+' must occupy a whole level: {topic!r}")


def is_valid(topic: str, kind: str = "filter") -> bool:
    try:
        validate(topic, kind)
        return True
    except TopicError:
        return False


# ---------------------------------------------------------------------------
# Share-subscription parsing
# ---------------------------------------------------------------------------

def parse_share(flt: str) -> Optional[Tuple[str, str]]:
    """``"$share/g/a/b"`` → ``("g", "a/b")``; ``"$queue/t"`` → ``("$queue", "t")``;
    anything else → None."""
    if flt.startswith(SHARE_PREFIX + "/"):
        rest = flt[len(SHARE_PREFIX) + 1 :]
        group, sep, real = rest.partition("/")
        if not sep:
            return (group, "")
        return (group, real)
    if flt.startswith(QUEUE_PREFIX + "/"):
        return (QUEUE_PREFIX, flt[len(QUEUE_PREFIX) + 1 :])
    return None


def is_shared(flt: str) -> bool:
    return parse_share(flt) is not None


def strip_share(flt: str) -> str:
    """Return the real filter, share prefix removed (identity otherwise)."""
    share = parse_share(flt)
    return share[1] if share is not None else flt


def make_share(group: str, real: str) -> str:
    return f"{SHARE_PREFIX}/{group}/{real}"


# ---------------------------------------------------------------------------
# The match oracle
# ---------------------------------------------------------------------------

def match(name, flt) -> bool:
    """Does concrete topic ``name`` match topic filter ``flt``?

    Both arguments may be strings or pre-split word lists.  ``name`` must be
    wildcard-free (a published topic); ``flt`` may contain ``+``/``#``.
    Share prefixes are **not** stripped here — see :func:`match_share`.
    """
    nw = words(name) if isinstance(name, str) else list(name)
    fw = words(flt) if isinstance(flt, str) else list(flt)
    if not nw or not fw:
        return False
    # $-topics are not matched by filters starting with a wildcard.
    if nw[0].startswith("$") and fw[0] in ("+", "#"):
        return False
    i = 0
    for fword in fw:
        if fword == "#":
            return True  # zero or more remaining levels
        if i >= len(nw):
            return False
        if fword == "+" or fword == nw[i]:
            i += 1
            continue
        return False
    return i == len(nw)


def match_share(name, flt) -> bool:
    """Like :func:`match` but strips a ``$share``/``$queue`` prefix first."""
    f = flt if isinstance(flt, str) else join(flt)
    return match(name, strip_share(f))


# ---------------------------------------------------------------------------
# Variable substitution (emqx_topic:feed_var/3)
# ---------------------------------------------------------------------------

def feed_var(var: str, value: str, topic: str) -> str:
    """Substitute a placeholder level (e.g. ``%c``, ``%u``) with ``value``."""
    return join([value if w == var else w for w in words(topic)])
