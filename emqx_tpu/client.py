"""Asyncio MQTT client — the `emqtt` analog (SURVEY.md §2.3: client lib +
load generator used as the baseline driver).

Full v3.1.1/v5 client over TCP or WebSocket: CONNECT negotiation,
QoS 0/1/2 publish flows with inflight tracking, SUBSCRIBE/UNSUBSCRIBE,
keepalive PINGREQ, auto reason-code surfacing.  Incoming PUBLISHes land in
an asyncio queue (or a callback), with the full QoS2 receiver FSM.
"""

from __future__ import annotations

import asyncio
import struct
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .mqtt import frame as F
from .mqtt import packet as P

__all__ = ["Client", "MqttError", "InboundMessage"]


class MqttError(Exception):
    pass


@dataclass
class InboundMessage:
    topic: str
    payload: bytes
    qos: int = 0
    retain: bool = False
    dup: bool = False
    properties: Dict[str, Any] = field(default_factory=dict)


class Client:
    def __init__(
        self,
        clientid: str = "",
        host: str = "127.0.0.1",
        port: int = 1883,
        proto_ver: int = 4,
        clean_start: bool = True,
        keepalive: int = 60,
        username: Optional[str] = None,
        password: Optional[bytes] = None,
        will: Optional[P.Will] = None,
        properties: Optional[Dict[str, Any]] = None,
        on_message: Optional[Callable[[InboundMessage], None]] = None,
        max_packet_size: int = F.MAX_REMAINING_LEN,
        on_auth: Optional[Callable[[bytes], bytes]] = None,
    ) -> None:
        self.clientid = clientid
        self.host, self.port = host, port
        self.proto_ver = proto_ver
        self.clean_start = clean_start
        self.keepalive = keepalive
        self.username, self.password = username, password
        self.will = will
        self.conn_properties = properties or {}
        self.on_message = on_message
        self.on_auth = on_auth  # enhanced auth: challenge bytes -> response
        self.messages: "asyncio.Queue[InboundMessage]" = asyncio.Queue()
        self.connack: Optional[P.Connack] = None
        self.connected = False
        self._parser = F.Parser(max_packet_size=max_packet_size)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pid_counter = 0
        self._pending: Dict[Tuple[int, int], asyncio.Future] = {}
        self._pids: set = set()   # pids awaiting an ack (O(1) alloc)
        # (topic, payload_len) → serialized v4 QoS1 PUBLISH head: a
        # pipelined publisher re-sending one topic patches 2 pid bytes
        # instead of paying a serializer pass per message (bytes
        # identical to frame.serialize; v5/props/retain use the
        # serializer as before)
        self._pub_heads: Dict[Tuple[str, int], bytes] = {}
        # while a feed batch is being handled, outbound pid-only acks
        # (PUBACK/PUBREC/PUBCOMP) collect here and flush as ONE write
        # per TCP read — the consumer-side analog of the broker's
        # coalesced ack writes
        self._ack_buf: Optional[bytearray] = None
        self._rel_pending: Dict[int, P.Publish] = {}  # QoS2 rx, awaiting REL
        self._tasks: List[asyncio.Task] = []
        self._closed = asyncio.Event()
        self.disconnect_reason: Optional[int] = None
        self.reauth_result: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------

    async def connect(self, timeout: float = 10.0) -> P.Connack:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        from .transport.connection import set_nodelay

        set_nodelay(self._writer.get_extra_info("socket"))
        # inbound packets parse with the version we offer (the server's
        # parser learns it from our CONNECT; ours must be pre-pinned)
        self._parser.proto_ver = self.proto_ver
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[(P.CONNACK, 0)] = fut
        self._tasks.append(asyncio.ensure_future(self._read_loop()))
        self._send(
            P.Connect(
                proto_ver=self.proto_ver,
                clientid=self.clientid,
                clean_start=self.clean_start,
                keepalive=self.keepalive,
                username=self.username,
                password=self.password,
                will=self.will,
                properties=dict(self.conn_properties),
            )
        )
        try:
            self.connack = await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, TimeoutError, MqttError):
            await self.close()  # no socket/task leak on a dead broker
            raise
        rc = self.connack.reason_code
        if rc != 0:
            await self.close()
            raise MqttError(f"CONNACK refused rc={rc}")
        if "Assigned-Client-Identifier" in self.connack.properties:
            self.clientid = self.connack.properties[
                "Assigned-Client-Identifier"
            ]
        self.connected = True
        if self.keepalive:
            self._tasks.append(asyncio.ensure_future(self._ping_loop()))
        return self.connack

    async def subscribe(
        self,
        filters,
        qos: int = 0,
        timeout: float = 10.0,
        **opts,
    ) -> List[int]:
        """filters: str or [(filter, qos)] / [filter]. Returns SUBACK codes."""
        if isinstance(filters, str):
            filters = [(filters, qos)]
        topics = [
            (x, {"qos": qos, **opts}) if isinstance(x, str)
            else (x[0], {"qos": x[1], **opts})
            for x in filters
        ]
        pid = self._next_pid()
        ack = await self._request(
            P.Subscribe(packet_id=pid, topic_filters=topics),
            (P.SUBACK, pid),
            timeout,
        )
        return list(ack.reason_codes)

    async def unsubscribe(self, filters, timeout: float = 10.0) -> List[int]:
        if isinstance(filters, str):
            filters = [filters]
        pid = self._next_pid()
        ack = await self._request(
            P.Unsubscribe(packet_id=pid, topic_filters=list(filters)),
            (P.UNSUBACK, pid),
            timeout,
        )
        return list(getattr(ack, "reason_codes", []) or [])

    async def publish(
        self,
        topic: str,
        payload: bytes = b"",
        qos: int = 0,
        retain: bool = False,
        properties: Optional[Dict[str, Any]] = None,
        timeout: float = 10.0,
    ) -> Optional[int]:
        """QoS0: fire-and-forget.  QoS1: await PUBACK.  QoS2: full
        PUBREC/PUBREL/PUBCOMP handshake.  Returns the ack reason code."""
        pkt = P.Publish(
            qos=qos, retain=retain, topic=topic, payload=payload,
            properties=properties or {},
        )
        if qos == 0:
            self._send(pkt)
            return None
        pid = pkt.packet_id = self._next_pid()
        if qos == 1:
            ack = await self._request(pkt, (P.PUBACK, pid), timeout)
            return getattr(ack, "reason_code", 0)
        return await self._publish_qos2(pkt, pid, timeout)

    def publish_start(
        self,
        topic: str,
        payload: bytes = b"",
        retain: bool = False,
        properties: Optional[Dict[str, Any]] = None,
    ):
        """Pipelined QoS1 publish: send now, return the PUBACK future —
        the emqtt_bench async-publish mode.  The caller bounds its own
        inflight window by awaiting futures."""
        pid = self._next_pid()
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        key = (P.PUBACK, pid)
        self._pending[key] = fut
        self._pids.add(pid)
        fut.add_done_callback(
            lambda _f: (self._pending.pop(key, None),
                        self._pids.discard(pid)))
        if self.proto_ver < 5 and not retain and not properties:
            # template fast path: head cached per (topic, len), only
            # the 2 pid bytes differ between repeats — identical bytes
            # to the serializer
            hkey = (topic, len(payload))
            head = self._pub_heads.get(hkey)
            if head is None:
                tb = topic.encode("utf-8")
                rl = 2 + len(tb) + 2 + len(payload)
                head = (bytes((0x32,)) + F._enc_varint(rl)
                        + struct.pack(">H", len(tb)) + tb)
                self._pub_heads[hkey] = head
            if self._writer is None:
                raise MqttError("not connected")
            self._writer.write(
                head + struct.pack(">H", pid) + payload)
            return fut
        pkt = P.Publish(
            qos=1, retain=retain, topic=topic, payload=payload,
            properties=properties or {}, packet_id=pid,
        )
        self._send(pkt)
        return fut

    async def _publish_qos2(self, pkt, pid: int, timeout: float):
        rec = await self._request(pkt, (P.PUBREC, pid), timeout)
        rc = getattr(rec, "reason_code", 0)
        if rc >= 0x80:
            return rc
        comp = await self._request(
            P.PubAck(P.PUBREL, pid), (P.PUBCOMP, pid), timeout
        )
        return getattr(comp, "reason_code", 0)

    async def recv(self, timeout: float = 10.0) -> "InboundMessage":
        if not self.messages.empty():
            # fast path: no timer arm/disarm per already-queued message
            return self.messages.get_nowait()
        return await asyncio.wait_for(self.messages.get(), timeout)

    async def recv_many(self, timeout: float = 10.0,
                        max_n: int = 0) -> List["InboundMessage"]:
        """Wait for at least one message, then drain everything already
        queued (up to ``max_n``; 0 = unbounded).  One await per burst
        instead of one per message — the consumer-side analog of the
        broker's batched fanout flush."""
        q = self.messages
        out: List[InboundMessage] = []
        if q.empty():
            out.append(await asyncio.wait_for(q.get(), timeout))
        while not q.empty() and (not max_n or len(out) < max_n):
            out.append(q.get_nowait())
        return out

    async def disconnect(self, reason_code: int = 0) -> None:
        if self._writer is not None and not self._writer.is_closing():
            try:
                self._send(P.Disconnect(reason_code=reason_code))
                await self._writer.drain()
            except ConnectionError:
                pass
        await self.close()

    async def close(self) -> None:
        self.connected = False
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:
                pass
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        self._closed.set()

    async def wait_closed(self) -> None:
        await self._closed.wait()


    # ------------------------------------------------------------------

    def _next_pid(self) -> int:
        """1..65535 with wraparound, skipping ids still awaiting an ack
        (MQTT §2.2.1 packet identifiers are 16-bit).  O(1) via the
        in-use pid set (the old per-call scan of ``_pending`` was
        O(window) per publish — measurable at bench windows)."""
        in_use = self._pids
        for _ in range(65535):
            self._pid_counter = (self._pid_counter % 65535) + 1
            pid = self._pid_counter
            if pid not in in_use:
                return pid
        raise MqttError("no free packet id")

    def _send(self, pkt: Any) -> None:
        if self._writer is None:
            raise MqttError("not connected")
        self._writer.write(F.serialize(pkt, ver=self.proto_ver))

    async def _request(self, pkt: Any, key: Tuple[int, int], timeout: float):
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[key] = fut
        self._pids.add(key[1])
        self._send(pkt)
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(key, None)
            self._pids.discard(key[1])

    async def _ping_loop(self) -> None:
        while True:
            await asyncio.sleep(max(self.keepalive * 0.75, 1.0))
            try:
                self._send(P.PingReq())
            except (MqttError, ConnectionError):
                return

    async def _read_loop(self) -> None:
        try:
            while True:
                data = await self._reader.read(65536)
                if not data:
                    break
                pkts = self._parser.feed(data)
                self._ack_buf = ab = bytearray()
                try:
                    for pkt in pkts:
                        self._handle(pkt)
                finally:
                    self._ack_buf = None
                    if ab and self._writer is not None:
                        # every pid-only ack for this TCP read in ONE
                        # write (bytes identical to per-packet sends)
                        self._writer.write(bytes(ab))
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self.connected = False
            self._closed.set()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(MqttError("connection closed"))

    def _handle(self, pkt: Any) -> None:
        t = pkt.type
        if t == P.CONNACK:
            self._resolve((P.CONNACK, 0), pkt)
        elif t in (P.SUBACK, P.UNSUBACK, P.PUBACK, P.PUBCOMP, P.PUBREC):
            self._resolve((t, pkt.packet_id), pkt)
        elif t == P.PUBLISH:
            self._handle_publish(pkt)
        elif t == P.PUBREL:
            held = self._rel_pending.pop(pkt.packet_id, None)
            if held is not None:
                self._emit(held)
            self._ack(0x70, pkt.packet_id)  # PUBCOMP
        elif t == P.DISCONNECT:
            self.disconnect_reason = getattr(pkt, "reason_code", 0)
        elif t == P.AUTH and pkt.reason_code != P.RC.CONTINUE_AUTHENTICATION:
            # AUTH rc=0x00: server-side completion of a re-auth — expose
            # the final data (server signature) for caller verification
            self.reauth_result = dict(pkt.properties)
        elif t == P.AUTH and self.on_auth is None:
            # fail fast instead of hanging until the connect timeout
            self._resolve((P.CONNACK, 0), MqttError(
                "AUTH challenge received but no on_auth handler"))
        elif t == P.AUTH:
            # enhanced-auth challenge: compute + send the response leg
            try:
                data = self.on_auth(
                    pkt.properties.get("Authentication-Data", b""))
                self._send(P.Auth(
                    reason_code=P.RC.CONTINUE_AUTHENTICATION,
                    properties={
                        "Authentication-Method":
                            self.conn_properties.get(
                                "Authentication-Method", ""),
                        "Authentication-Data": data,
                    },
                ))
            except Exception as e:
                self._resolve((P.CONNACK, 0), MqttError(f"auth failed: {e}"))
        # PINGRESP: nothing to do

    def _ack(self, head: int, pid: int) -> None:
        """Send a pid-only ack (rc 0 — 4 bytes in every version):
        coalesced into one write per TCP read while a feed batch is
        open, identical bytes to a per-packet serialize+send."""
        if self._ack_buf is not None:
            self._ack_buf += bytes((head, 2, pid >> 8, pid & 0xFF))
        else:
            self._send(P.PubAck(head >> 4, pid))

    def _handle_publish(self, pkt: P.Publish) -> None:
        if pkt.qos == 0:
            self._emit(pkt)
        elif pkt.qos == 1:
            self._emit(pkt)
            self._ack(0x40, pkt.packet_id)  # PUBACK
        else:  # QoS2 receiver: hold until PUBREL (exactly-once)
            if pkt.packet_id not in self._rel_pending:
                self._rel_pending[pkt.packet_id] = pkt
            self._ack(0x50, pkt.packet_id)  # PUBREC

    def _emit(self, pkt: P.Publish) -> None:
        msg = InboundMessage(
            topic=pkt.topic, payload=pkt.payload, qos=pkt.qos,
            retain=pkt.retain, dup=pkt.dup, properties=dict(pkt.properties),
        )
        if self.on_message is not None:
            self.on_message(msg)
        else:
            self.messages.put_nowait(msg)

    def _resolve(self, key: Tuple[int, int], pkt: Any) -> None:
        fut = self._pending.get(key)
        if fut is not None and not fut.done():
            if isinstance(pkt, Exception):
                fut.set_exception(pkt)
            else:
                fut.set_result(pkt)
