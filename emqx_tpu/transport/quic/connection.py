"""QUIC v1 sans-IO connections + the UDP endpoint/MQTT bridge.

One client-initiated bidirectional stream (id 0) carries the MQTT byte
stream — the same mapping the reference runs over quicer streams
(``emqx_quic_stream.erl`` [U]).  The endpoint hands each accepted
connection's stream to the node's ordinary ``handle_stream`` via a
stream adapter, so the full Channel/session machinery is shared with
TCP/WS/TLS listeners.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from . import frames as FR
from .crypto import LevelKeys, initial_keys, traffic_keys
from .packet import (
    PKT_1RTT, PKT_HANDSHAKE, PKT_INITIAL, PlainPacket, protect, unprotect,
)
from .tls13 import LEVEL_APP, LEVEL_HANDSHAKE, LEVEL_INITIAL, Tls13

log = logging.getLogger(__name__)

__all__ = ["QuicClient", "QuicEndpoint", "QuicServerConnection",
           "QuicStream"]

_LEVEL_OF_PKT = {PKT_INITIAL: LEVEL_INITIAL, PKT_HANDSHAKE: LEVEL_HANDSHAKE,
                 PKT_1RTT: LEVEL_APP}
_PKT_OF_LEVEL = {v: k for k, v in _LEVEL_OF_PKT.items()}


def _retransmittable(frame: bytes) -> bool:
    """Frames worth re-sending on loss: CRYPTO, STREAM, HANDSHAKE_DONE,
    PING.  ACKs regenerate from _recv_pns, PADDING/CLOSE never
    retransmit."""
    t = frame[0]
    return (t == FR.CRYPTO or 0x08 <= t <= 0x0F
            or t == FR.HANDSHAKE_DONE or t == FR.PING)

# generous static transport parameters (flow control is not enforced
# beyond these; see package docstring scope cuts)
_TP_DEFAULTS = (
    (0x04, 1 << 24),   # initial_max_data
    (0x05, 1 << 22),   # initial_max_stream_data_bidi_local
    (0x06, 1 << 22),   # ..bidi_remote
    (0x07, 1 << 22),   # ..uni
    (0x08, 64),        # initial_max_streams_bidi
    (0x09, 64),        # ..uni
)


def _encode_tp(scid: bytes, odcid: Optional[bytes]) -> bytes:
    from .packet import encode_varint as ev

    out = bytearray()
    for pid, val in _TP_DEFAULTS:
        body = ev(val)
        out += ev(pid) + ev(len(body)) + body
    out += ev(0x0F) + ev(len(scid)) + scid          # initial_scid
    if odcid is not None:
        out += ev(0x00) + ev(len(odcid)) + odcid    # original_dcid
    return bytes(out)


class _Assembler:
    """Offset-based byte-stream reassembly (CRYPTO and stream 0)."""

    def __init__(self) -> None:
        self.pos = 0
        self.segs: Dict[int, bytes] = {}

    def add(self, offset: int, data: bytes) -> bytes:
        if data:
            self.segs[offset] = max(self.segs.get(offset, b""), data,
                                    key=len)
        out = bytearray()
        while True:
            for off, seg in list(self.segs.items()):
                if off <= self.pos < off + len(seg) or off == self.pos:
                    out += seg[self.pos - off:]
                    self.pos = off + len(seg)
                    del self.segs[off]
                    break
                if off + len(seg) <= self.pos:
                    del self.segs[off]
                    break
            else:
                break
        return bytes(out)


class _Conn:
    """Shared machinery for both roles."""

    def __init__(self, role: str, tls: Tls13, scid: bytes,
                 initial: LevelKeys, mtu_discovery: bool = True) -> None:
        self.role = role
        self.tls = tls
        self.scid = scid
        self.remote_cid = b""
        self._keys: Dict[str, LevelKeys] = {LEVEL_INITIAL: initial}
        self._next_pn: Dict[str, int] = {
            LEVEL_INITIAL: 0, LEVEL_HANDSHAKE: 0, LEVEL_APP: 0}
        self._largest: Dict[str, int] = {
            LEVEL_INITIAL: -1, LEVEL_HANDSHAKE: -1, LEVEL_APP: -1}
        self._recv_pns: Dict[str, List[int]] = {
            LEVEL_INITIAL: [], LEVEL_HANDSHAKE: [], LEVEL_APP: []}
        self._ack_due: Dict[str, bool] = {
            LEVEL_INITIAL: False, LEVEL_HANDSHAKE: False, LEVEL_APP: False}
        self._crypto_rx = {lv: _Assembler()
                           for lv in (LEVEL_INITIAL, LEVEL_HANDSHAKE,
                                      LEVEL_APP)}
        self._crypto_tx_off: Dict[str, int] = {
            LEVEL_INITIAL: 0, LEVEL_HANDSHAKE: 0, LEVEL_APP: 0}
        self.stream_rx = _Assembler()
        self._stream_tx_off = 0
        self._stream_in = bytearray()
        self.stream_fin = False
        self.handshake_done = False
        self.closed = False
        self.close_reason = ""
        self._out_datagrams: List[bytes] = []
        self._pending_frames: Dict[str, List[bytes]] = {
            LEVEL_INITIAL: [], LEVEL_HANDSHAKE: [], LEVEL_APP: []}
        # 1-RTT packets that arrived before app recv keys derived (a
        # peer may coalesce its first stream data with its Finished);
        # replayed after derivation — bounded
        self._undecryptable: List[bytes] = []
        # loss recovery (RFC 9002): ack-eliciting frames of each sent
        # packet, kept until acked; _detect_lost() re-queues on ack
        # evidence (packet/time threshold), on_timer() re-queues
        # anything older than the (backed-off) PTO as the backstop
        self._sent: Dict[str, Dict[int, Tuple[float, List[bytes]]]] = {
            LEVEL_INITIAL: {}, LEVEL_HANDSHAKE: {}, LEVEL_APP: {}}
        self._pto_base = 0.4      # pre-measurement default
        self._pto_count = 0
        # RFC 9002 §6.1 ack-based loss detection state: packets more
        # than kPacketThreshold (3) below the largest acked — or older
        # than 9/8·srtt with a later ack present — are declared lost at
        # ACK receipt and retransmitted immediately, no PTO wait
        self._largest_acked: Dict[str, int] = {
            LEVEL_INITIAL: -1, LEVEL_HANDSHAKE: -1, LEVEL_APP: -1}
        # RFC 9002 §7 NewReno congestion controller in PACKET units
        # (every STREAM packet is MTU-sized by construction, so packets
        # ≈ bytes/1200): slow start to _ssthresh, then +1/cwnd per ack;
        # halved once per round trip on a loss event; collapsed to the
        # minimum window on persistent congestion (2 consecutive PTOs)
        self._cwnd = 10.0
        self._ssthresh = float("inf")
        self._recovery_until: Dict[str, int] = {
            LEVEL_INITIAL: -1, LEVEL_HANDSHAKE: -1, LEVEL_APP: -1}
        self.fast_retransmits = 0
        # RFC 6298-style smoothed RTT from ack round trips (our ACKs
        # carry ack_delay 0, so the sample is the pure path RTT)
        self._srtt: Optional[float] = None
        self._rttvar = 0.0
        self.retransmits = 0
        # send window: stream chunks wait here until in-flight packet
        # count allows them (a multi-MB write must not blow past the
        # _sent tracking cap — evicted entries would be retransmit
        # holes); drained on ACK receipt and on the PTO timer
        self._stream_txq: deque = deque()
        self._tx_window = 512
        # packet pacing (RFC 9002 §7.7): a token bucket bounds how many
        # stream packets one _service() releases, refilled at
        # 1.25 × cwnd/srtt.  On LAN RTTs the rate is effectively
        # unbounded; on lossy WAN paths it stops a full-window burst
        # from flooding a shallow queue and re-triggering loss.  Before
        # an RTT sample exists the bucket refills to the burst cap.
        self._pace_tokens = 16.0
        self._pace_last = time.monotonic()
        # DPLPMTUD (RFC 8899 / RFC 9000 §14.3): after the handshake,
        # PING+PADDING probe datagrams walk the ladder; an acked probe
        # raises the datagram budget, a lost one (after one retry)
        # freezes it — probe loss is NOT congestion evidence.
        # mtu_validated is the PUBLIC operator-facing view (listener
        # stats); the rest is internal probe state.
        self._mtu_chunk = self._MTU_STREAM_CHUNK
        self.mtu_validated = 1252
        self._mtu_probe: Optional[Tuple[int, int]] = None   # (pn, size)
        self._mtu_ladder: List[int] = (
            [1452, 4096, 9000, 16000, 32000, 63000] if mtu_discovery
            else [])
        self._mtu_fails: Dict[int, int] = {}
        self.mtu_probes_sent = 0
        # black-hole detection state (RFC 8899 §4.3): consecutive losses
        # of packets LARGER than the base PLPMTU, tracked independently
        # of _pto_count (which resets on every ack — on a mixed-traffic
        # path whose MTU shrank, small packets keep flowing and would
        # keep resetting it, so the fallback would never fire)
        self._big_loss_streak = 0
        self.last_seen = time.monotonic()

    # -- key plumbing --------------------------------------------------

    def _maybe_derive_keys(self) -> None:
        if LEVEL_HANDSHAKE not in self._keys and self.tls.hs_secrets:
            c, s = self.tls.hs_secrets
            self._keys[LEVEL_HANDSHAKE] = LevelKeys(
                client=traffic_keys(c), server=traffic_keys(s))
        if LEVEL_APP not in self._keys and self.tls.app_secrets:
            c, s = self.tls.app_secrets
            self._keys[LEVEL_APP] = LevelKeys(
                client=traffic_keys(c), server=traffic_keys(s))

    def _send_keys(self, level: str):
        ks = self._keys.get(level)
        if ks is None:
            return None
        return ks.server if self.role == "server" else ks.client

    def _recv_keys(self, level: str):
        ks = self._keys.get(level)
        if ks is None:
            return None
        return ks.client if self.role == "server" else ks.server

    # -- receive -------------------------------------------------------

    def receive(self, datagram: bytes) -> None:
        if self.closed:
            return
        self.last_seen = time.monotonic()
        self._receive_segments(datagram)
        if self._undecryptable and \
                self._recv_keys(LEVEL_APP) is not None:
            pend, self._undecryptable = self._undecryptable, []
            for seg in pend:
                self._receive_segments(seg)
        self._service()

    def _receive_segments(self, datagram: bytes) -> None:
        off = 0
        while off < len(datagram):
            if not (datagram[off] & 0x80) \
                    and self._recv_keys(LEVEL_APP) is None:
                # short-header packet before app keys: park the tail
                # (short headers run to the end of the datagram)
                if len(self._undecryptable) < 32:
                    self._undecryptable.append(datagram[off:])
                return
            pkt, off = unprotect(
                datagram, off,
                lambda kind: self._recv_keys(_LEVEL_OF_PKT[kind]),
                lambda kind: self._largest[_LEVEL_OF_PKT[kind]],
                local_cid_len=len(self.scid),
            )
            if pkt is None:
                continue
            self._on_packet(pkt)

    def _on_packet(self, pkt: PlainPacket) -> None:
        level = _LEVEL_OF_PKT[pkt.kind]
        self._largest[level] = max(self._largest[level], pkt.pn)
        self._recv_pns[level].append(pkt.pn)
        self._recv_pns[level] = self._recv_pns[level][-64:]
        if pkt.kind != PKT_1RTT and pkt.scid:
            self.remote_cid = pkt.scid
        for fr in FR.parse_frames(pkt.payload):
            if isinstance(fr, FR.CryptoFrame):
                self._ack_due[level] = True
                data = self._crypto_rx[level].add(fr.offset, fr.data)
                if data:
                    self.tls.feed(level, data)
                    self._maybe_derive_keys()
            elif isinstance(fr, FR.StreamFrame):
                self._ack_due[level] = True
                if fr.stream_id == 0:
                    got = self.stream_rx.add(fr.offset, fr.data)
                    if got:
                        self._stream_in += got
                    if fr.fin:
                        self.stream_fin = True
                # non-zero streams: accepted and ignored (scope cut)
            elif fr is FR.PING:
                self._ack_due[level] = True
            elif fr is FR.HANDSHAKE_DONE:
                self._ack_due[level] = True
                self.handshake_done = True
                # RFC 9001 §4.9: Initial/Handshake PN spaces retire with
                # the handshake — their in-flight state goes too
                self._sent[LEVEL_INITIAL].clear()
                self._sent[LEVEL_HANDSHAKE].clear()
            elif isinstance(fr, FR.CloseFrame):
                self.closed = True
                self.close_reason = fr.reason
            elif isinstance(fr, FR.AckFrame):
                sent = self._sent[level]
                if sent:
                    # iterate OUR bounded in-flight set, not the peer's
                    # ranges (a hostile ACK can claim 2^62-wide ranges)
                    rngs = fr.ranges[:64]
                    acked = [pn for pn in sent
                             if any(lo <= pn <= hi for lo, hi in rngs)]
                    now = time.monotonic()
                    probe_pn = (self._mtu_probe[0]
                                if level == LEVEL_APP
                                and self._mtu_probe is not None else None)
                    for pn in acked:
                        t_sent, frs = sent.pop(pn)
                        if pn == fr.largest:    # RFC 9002 §5: sample on
                            self._rtt_sample(now - t_sent)  # largest
                        if pn == probe_pn:
                            # DPLPMTUD probe: discovery traffic, not
                            # congestion feedback — no cwnd growth
                            continue
                        if self._frames_len(frs) > self._MTU_STREAM_CHUNK:
                            # a full-size packet got through: the path
                            # carries the validated MTU (RFC 8899 §4.3)
                            self._big_loss_streak = 0
                        # congestion window growth, per acked packet
                        if self._cwnd < self._ssthresh:
                            self._cwnd += 1.0           # slow start
                        else:
                            self._cwnd += 1.0 / self._cwnd
                    if acked:
                        self._pto_count = 0     # backoff resets on ack
                        self._largest_acked[level] = max(
                            self._largest_acked[level], max(acked))
                        if (level == LEVEL_APP
                                and self._mtu_probe is not None
                                and self._mtu_probe[0] in acked):
                            self._mtu_probe_result(True)
                        self._detect_lost(level, now)

    # -- send ----------------------------------------------------------

    def _flush_level(self, level: str) -> List[bytes]:
        keys = self._send_keys(level)
        if keys is None:
            # keys not derived yet (e.g. app data queued mid-handshake):
            # leave the frames AND the ack-due flag queued — they flush
            # on the next _service() after key derivation, instead of
            # being silently discarded
            return []
        if level == LEVEL_APP:
            # re-segment at FLUSH time, not only at the black-hole
            # transition: stream frames requeued from _sent on a later
            # PTO tick (or queued before the MTU shrank) must never
            # leave oversized again
            for fr in self._pending_frames[level]:
                if len(fr) > self._mtu_chunk and 0x08 <= fr[0] <= 0x0F:
                    self._resegment_app_frames()
                    break
        frames = self._pending_frames[level]
        if self._ack_due[level] and self._recv_pns[level]:
            frames.insert(0, FR.encode_ack(self._recv_pns[level]))
            self._ack_due[level] = False
        if not frames:
            return []
        self._pending_frames[level] = []
        # greedy frame grouping under the MTU payload budget: frames
        # queued while keys were absent must NOT merge into one
        # oversized packet (send_stream's segmentation would be undone)
        groups: List[List[bytes]] = [[]]
        size = 0
        for fr in frames:
            if groups[-1] and size + len(fr) > self._mtu_chunk:
                groups.append([])
                size = 0
            groups[-1].append(fr)
            size += len(fr)
        kind = _PKT_OF_LEVEL[level]
        out = []
        now = time.monotonic()
        for group in groups:
            pn = self._next_pn[level]
            self._next_pn[level] += 1
            out.append(protect(kind, keys, pn, b"".join(group),
                               dcid=self.remote_cid, scid=self.scid))
            rtx = [fr for fr in group if _retransmittable(fr)]
            if rtx:
                sent = self._sent[level]
                if len(sent) >= 1024:       # bounded: evict the oldest
                    sent.pop(next(iter(sent)))
                sent[pn] = (now, rtx)
        return out

    def _service(self) -> None:
        """Drain TLS output + pending frames into coalesced datagrams."""
        self._drain_stream_txq()
        for level, msg in self.tls.take_outgoing():
            off = self._crypto_tx_off[level]
            self._pending_frames[level].append(FR.encode_crypto(off, msg))
            self._crypto_tx_off[level] = off + len(msg)
        self._maybe_derive_keys()
        if self.role == "server" and self.tls.complete \
                and not self.handshake_done:
            self._pending_frames[LEVEL_APP].append(
                bytes([FR.HANDSHAKE_DONE]))
            self.handshake_done = True
            # mirror of the receive path: the peer discards Initial/
            # Handshake keys now (RFC 9001 §4.9), so unacked CRYPTO in
            # those PN spaces can never be acknowledged — dropping it
            # stops futile ~1200-byte PTO retransmits for the lifetime
            # of the connection
            self._sent[LEVEL_INITIAL].clear()
            self._sent[LEVEL_HANDSHAKE].clear()
        parts: List[bytes] = []
        extra_dgrams: List[bytes] = []
        app_pkt: Optional[bytes] = None
        has_initial = bool(self._pending_frames[LEVEL_INITIAL]) \
            or self._ack_due[LEVEL_INITIAL]
        for level in (LEVEL_INITIAL, LEVEL_HANDSHAKE):
            pkts = self._flush_level(level)
            if pkts:
                parts.append(pkts[0])
                for p in pkts[1:]:              # each under the MTU
                    if level == LEVEL_INITIAL and len(p) < 1200:
                        # RFC 9000 §14.1 applies to EVERY datagram
                        # carrying an Initial — overflow Initials must
                        # pad too or strict peers (incl. our own
                        # endpoint) drop them
                        p = p + self._make_padding(1200 - len(p),
                                                   allow_short=False)
                    extra_dgrams.append(p)
        app_pkts = self._flush_level(LEVEL_APP)
        if app_pkts:
            app_pkt = app_pkts[0]   # short header: MUST stay last in a
            extra_dgrams.extend(app_pkts[1:])   # datagram (no length
        if not parts and app_pkt is None:       # field) — spares ride
            self._out_datagrams.extend(extra_dgrams)    # solo
            self._maybe_send_mtu_probe()
            return
        total = sum(map(len, parts)) + (len(app_pkt) if app_pkt else 0)
        if has_initial and total < 1200:
            # RFC 9000 §14.1: datagrams carrying Initial packets expand
            # to 1200 (client anti-amplification / server validation).
            # The pad packet goes BEFORE any short-header packet: a
            # second short-header packet in one datagram would swallow
            # it into the first one's AEAD body and break decryption.
            pad = self._make_padding(1200 - total,
                                     allow_short=app_pkt is None)
            if pad:
                parts.append(pad)
        if app_pkt is not None:
            parts.append(app_pkt)
        self._out_datagrams.append(b"".join(parts))
        self._out_datagrams.extend(extra_dgrams)
        self._maybe_send_mtu_probe()

    def _make_padding(self, n: int, allow_short: bool = True) -> bytes:
        """A PADDING-only packet bringing the datagram to the 1200-byte
        floor (raw zero bytes after a packet are illegal — padding must
        live INSIDE a protected packet).  Long-header levels first:
        their explicit length lets another packet follow; the 1-RTT
        short-header form is only legal as the datagram's LAST packet
        (``allow_short``)."""
        levels = (LEVEL_HANDSHAKE, LEVEL_INITIAL) + (
            (LEVEL_APP,) if allow_short else ())
        for level in levels:
            keys = self._send_keys(level)
            if keys is None:
                continue
            pn = self._next_pn[level]
            kind = _PKT_OF_LEVEL[level]
            # probe: per-level overhead (header + AEAD tag) so the pad
            # lands on the floor.  The probe's 1-byte payload encodes a
            # 1-byte length varint; the real pad's length field can need
            # 2 bytes (length > 63), overshooting by one — converge on
            # the exact size below.  When the budget n is SMALLER than a
            # minimal pad packet (~overhead bytes), the floor wins over
            # exactness: the datagram lands a few bytes past 1200 but
            # stays well under the ~1252 safe MTU.  Only the final
            # ciphertext leaves the host, so reusing pn for the probes
            # discloses nothing.
            overhead = len(protect(kind, keys, pn, b"\x00",
                                   dcid=self.remote_cid,
                                   scid=self.scid)) - 1
            self._next_pn[level] += 1
            payload = b"\x00" * max(1, n - overhead)
            pkt = protect(kind, keys, pn, payload,
                          dcid=self.remote_cid, scid=self.scid)
            for _ in range(3):      # varint-boundary convergence
                delta = len(pkt) - n
                if delta == 0 or len(payload) - delta < 1:
                    break
                payload = b"\x00" * (len(payload) - delta)
                pkt = protect(kind, keys, pn, payload,
                              dcid=self.remote_cid, scid=self.scid)
            return pkt
        return b""

    def take_outgoing(self) -> List[bytes]:
        out, self._out_datagrams = self._out_datagrams, []
        return out

    # -- loss recovery (RFC 9002) --------------------------------------

    def _detect_lost(self, level: str, now: float) -> None:
        """Ack-based loss detection (RFC 9002 §6.1): with a later ack
        on record, unacked packets ≥ kPacketThreshold (3) below it, or
        older than the 9/8·srtt time threshold, are lost — their
        frames re-queue immediately (the caller's _service() flushes
        them) and the congestion window halves once per round trip."""
        sent = self._sent[level]
        la = self._largest_acked[level]
        time_limit = now - 9 / 8 * self._srtt if self._srtt else None
        lost = [pn for pn, (t, _) in sent.items()
                if pn <= la - 3
                or (time_limit is not None and pn < la
                    and t <= time_limit)]
        if (level == LEVEL_APP and self._mtu_probe is not None
                and self._mtu_probe[0] in lost):
            # a lost MTU probe means the path can't carry that size —
            # expected during discovery, NOT congestion (RFC 8899 §3):
            # no retransmit, no window halving for the probe itself
            lost.remove(self._mtu_probe[0])
            sent.pop(self._mtu_probe[0], None)
            self._mtu_probe_result(False)
        if not lost:
            return
        for pn in sorted(lost):         # original send order
            _, frames = sent.pop(pn)
            if level == LEVEL_APP \
                    and self._frames_len(frames) > self._MTU_STREAM_CHUNK:
                self._big_loss_streak += 1
            self._pending_frames[level].extend(frames)
        if level == LEVEL_APP:
            self._maybe_mtu_black_hole()
        self.fast_retransmits += 1
        if max(lost) >= self._recovery_until[level]:
            # first loss of this round trip: one multiplicative
            # decrease, then a recovery period until the current
            # send edge is acked (further losses in the same flight
            # must not halve again)
            self._ssthresh = max(2.0, self._cwnd / 2)
            self._cwnd = self._ssthresh
            self._recovery_until[level] = self._next_pn[level]

    # -- DPLPMTUD (RFC 8899 / RFC 9000 §14.3) --------------------------

    @staticmethod
    def _frames_len(frames: List[bytes]) -> int:
        return sum(len(f) for f in frames)

    # consecutive larger-than-base-PLPMTU losses before the black-hole
    # fallback fires (RFC 8899 §4.3's MAX_PROBES analog)
    BLACK_HOLE_STREAK = 3

    def _maybe_mtu_black_hole(self) -> None:
        """Fire the PLPMTU black-hole fallback on a streak of big-packet
        losses — independent of the ack-reset PTO counter, so a path
        whose MTU shrank under mixed traffic (small packets still
        flowing, acks resetting ``_pto_count``) still falls back."""
        if (self._big_loss_streak < self.BLACK_HOLE_STREAK
                or self.mtu_validated <= 1252):
            return
        self._mtu_black_hole_fallback()

    def _mtu_black_hole_fallback(self) -> None:
        """Persistent loss of full-size packets after an MTU was
        validated usually means the path shrank (route change under a
        DF socket) — fall back to the base PLPMTU and re-segment
        anything queued at the old size.  The ladder stays retired: a
        shrinking path has proven itself unstable."""
        self.mtu_validated = 1252
        self._mtu_chunk = self._MTU_STREAM_CHUNK
        self._mtu_ladder = []
        self._mtu_probe = None
        self._big_loss_streak = 0
        self._resegment_app_frames()

    def _maybe_send_mtu_probe(self) -> None:
        """One PING+PADDING probe datagram at the next ladder size;
        at most one in flight.  An acked probe raises the validated
        datagram budget (and the stream chunk size with it); a lost
        one retries once, then freezes the ladder at the last
        validated size."""
        if (self._mtu_probe is not None or not self._mtu_ladder
                or not self.handshake_done or self.closed):
            return
        if self._largest_acked[LEVEL_APP] < self._recovery_until[LEVEL_APP]:
            # in recovery (RFC 9002 §7.3.2): discovery probes would
            # compete with retransmissions for a shrunken window — wait
            # until the loss edge is acked
            return
        keys = self._send_keys(LEVEL_APP)
        if keys is None:
            return
        size = self._mtu_ladder[0]
        pn = self._next_pn[LEVEL_APP]
        self._next_pn[LEVEL_APP] += 1
        payload = b"\x01"                       # PING, ack-eliciting
        pkt = protect(PKT_1RTT, keys, pn, payload,
                      dcid=self.remote_cid, scid=self.scid)
        payload = b"\x01" + b"\x00" * max(0, size - len(pkt))
        pkt = protect(PKT_1RTT, keys, pn, payload,
                      dcid=self.remote_cid, scid=self.scid)
        for _ in range(3):                      # varint convergence
            delta = len(pkt) - size
            if delta == 0 or len(payload) - delta < 1:
                break
            payload = payload[:len(payload) - delta]
            pkt = protect(PKT_1RTT, keys, pn, payload,
                          dcid=self.remote_cid, scid=self.scid)
        self._sent[LEVEL_APP][pn] = (time.monotonic(), [])
        self._mtu_probe = (pn, len(pkt))
        self.mtu_probes_sent += 1
        self._out_datagrams.append(pkt)         # rides alone: probing
                                                # THIS datagram size

    def _mtu_probe_result(self, ok: bool) -> None:
        pn, size = self._mtu_probe              # type: ignore[misc]
        self._mtu_probe = None
        if ok:
            self.mtu_validated = size
            # short header + AEAD tag + STREAM frame header margin
            self._mtu_chunk = size - 70
            self._mtu_ladder = [s for s in self._mtu_ladder if s > size]
        else:
            fails = self._mtu_fails.get(size, 0) + 1
            self._mtu_fails[size] = fails
            if fails >= 2:                      # one retry per size,
                self._mtu_ladder = []           # then freeze

    def _resegment_app_frames(self) -> None:
        """Split pending STREAM frames built at a larger validated MTU
        back into base-MTU chunks (offsets preserved, FIN kept on the
        final piece) — without this the black-hole fallback would keep
        re-sending the same undeliverable jumbo frames."""
        out: List[bytes] = []
        for fr in self._pending_frames[LEVEL_APP]:
            if 0x08 <= fr[0] <= 0x0F and len(fr) > self._mtu_chunk:
                sf = next(iter(FR.parse_frames(fr)))
                step = self._mtu_chunk
                for i in range(0, len(sf.data) or 1, step):
                    piece = sf.data[i:i + step]
                    out.append(FR.encode_stream(
                        sf.stream_id, sf.offset + i, piece,
                        fin=sf.fin and i + step >= len(sf.data)))
            else:
                out.append(fr)
        self._pending_frames[LEVEL_APP] = out

    def _rtt_sample(self, rtt: float) -> None:
        if rtt < 0:
            return
        if self._srtt is None:
            self._srtt = rtt
            self._rttvar = rtt / 2
        else:
            self._rttvar = 0.75 * self._rttvar + 0.25 * abs(
                self._srtt - rtt)
            self._srtt = 0.875 * self._srtt + 0.125 * rtt

    def pto(self) -> float:
        # srtt + 4·rttvar once measured (20 ms minimum — LAN RTTs
        # would otherwise set sub-millisecond timers), the conservative
        # default before; exponential backoff on top
        base = (self._pto_base if self._srtt is None
                else max(0.02, self._srtt + 4 * self._rttvar))
        return min(8.0, base * (1 << min(self._pto_count, 4)))

    def on_timer(self, now: Optional[float] = None) -> bool:
        """Re-queue ack-eliciting frames unacked past the PTO; returns
        True when a retransmission was produced (caller flushes the
        resulting datagrams).  CRYPTO/STREAM retransmission is
        idempotent — frames carry offsets and the receive assemblers
        drop duplicates."""
        if self.closed:
            return False
        now = time.monotonic() if now is None else now
        deadline = now - self.pto()
        fired = False
        for level, sent in self._sent.items():
            late = [pn for pn, (t, _) in sent.items() if t <= deadline]
            if (level == LEVEL_APP and self._mtu_probe is not None
                    and self._mtu_probe[0] in late):
                # probe timeout = discovery failure, not congestion:
                # no backoff, no retransmit counter for the probe alone
                late.remove(self._mtu_probe[0])
                sent.pop(self._mtu_probe[0], None)
                self._mtu_probe_result(False)
            if not late:
                continue
            fired = True
            for pn in sorted(late):     # original send order
                _, frames = sent.pop(pn)
                if level == LEVEL_APP \
                        and self._frames_len(frames) > self._MTU_STREAM_CHUNK:
                    self._big_loss_streak += 1
                self._pending_frames[level].extend(frames)
        if not fired and (self._stream_txq or
                          (self.handshake_done and self._mtu_ladder
                           and self._mtu_probe is None)):
            # nothing timed out, but pacing may have withheld stream
            # chunks (tokens refill with elapsed time) or an MTU probe
            # slot opened — release them on the timer tick.  Returns
            # False: these are not retransmissions; callers flush
            # take_outgoing() either way.
            self._service()
            return False
        if fired:
            self.retransmits += 1
            self._pto_count += 1        # exponential backoff
            # black-hole detection: the streak counter (big-packet
            # losses, ack-independent — see _maybe_mtu_black_hole) is
            # the primary trigger; two consecutive PTOs with a raised
            # MTU stay as the belt-and-braces backstop
            self._maybe_mtu_black_hole()
            if self._pto_count == 2 and self.mtu_validated > 1252:
                self._mtu_black_hole_fallback()
            if self._pto_count == 2:
                # persistent congestion (RFC 9002 §7.6, PTO proxy):
                # two consecutive timeouts with no ack in between —
                # collapse to the minimum window and re-probe.  ONLY on
                # the transition: later PTOs of the same outage must
                # not clobber ssthresh down to the floor, or post-
                # outage slow start has nothing to climb back toward
                self._ssthresh = max(2.0, self._cwnd / 2)
                self._cwnd = 2.0
            self._service()
        return fired

    # -- app surface ---------------------------------------------------

    # RFC 9000 §14: never send datagrams above the 1200-byte minimum
    # path MTU until probing validates more.  STREAM payload per packet
    # leaves room for the short header + AEAD tag + frame header; the
    # instance's _mtu_chunk grows as DPLPMTUD validates larger sizes.
    _MTU_STREAM_CHUNK = 1130

    def send_stream(self, data: bytes, fin: bool = False) -> None:
        # segment into path-MTU-sized packets: one oversized datagram
        # would be IP-fragmented and silently dropped on frag-hostile
        # paths
        step = self._mtu_chunk
        chunks = [data[i:i + step]
                  for i in range(0, len(data), step)] or [b""]
        for j, chunk in enumerate(chunks):
            self._stream_txq.append((chunk, fin and j == len(chunks) - 1))
        self._service()

    def _drain_stream_txq(self) -> None:
        """Window-limited release of queued stream chunks into frames:
        at most _tx_window packets in flight, so the _sent tracker
        never overflows and every unacked chunk stays retransmittable.
        More drains happen on ACK receipt and on the timer tick (both
        call _service).  The release rate is governed by the
        congestion window — min(tracker cap, cwnd) packets in flight —
        AND by the pacing bucket: tokens refill at 1.25 × cwnd/srtt
        with a max(16, cwnd/2) burst cap, so a full window never
        leaves as one line-rate burst (RFC 9002 §7.7)."""
        now = time.monotonic()
        burst = max(16.0, self._cwnd / 2)
        if self._srtt:
            rate = 1.25 * self._cwnd / max(self._srtt, 1e-4)
            self._pace_tokens = min(
                burst, self._pace_tokens + (now - self._pace_last) * rate)
        else:
            self._pace_tokens = burst       # pre-measurement: no pacing
        self._pace_last = now
        room = (min(self._tx_window, max(2, int(self._cwnd)))
                - len(self._sent[LEVEL_APP])
                - len(self._pending_frames[LEVEL_APP]))
        while self._stream_txq and room > 0 and self._pace_tokens >= 1.0:
            chunk, fin = self._stream_txq.popleft()
            self._pending_frames[LEVEL_APP].append(
                FR.encode_stream(0, self._stream_tx_off, chunk, fin=fin))
            self._stream_tx_off += len(chunk)
            room -= 1
            self._pace_tokens -= 1.0

    def pop_stream_data(self) -> bytes:
        out = bytes(self._stream_in)
        self._stream_in.clear()
        return out

    def close(self, code: int = 0, reason: str = "") -> None:
        if self.closed:
            return
        level = LEVEL_APP if self._send_keys(LEVEL_APP) is not None \
            else LEVEL_INITIAL
        self._pending_frames[level].append(FR.encode_close(code, reason))
        self._service()
        self.closed = True
        self.close_reason = reason


class QuicServerConnection(_Conn):
    def __init__(self, first_dcid: bytes, cert_pem: bytes, key_pem: bytes,
                 alpn: str = "mqtt", mtu_discovery: bool = True) -> None:
        scid = os.urandom(8)
        tls = Tls13("server", cert_pem=cert_pem, key_pem=key_pem,
                    alpn=alpn, tp=_encode_tp(scid, first_dcid))
        super().__init__("server", tls, scid, initial_keys(first_dcid),
                         mtu_discovery=mtu_discovery)

    @property
    def established(self) -> bool:
        return self.tls.complete


class QuicClient(_Conn):
    def __init__(self, alpn: str = "mqtt", server_name: str = "",
                 verify_cert: bool = False,
                 ca_pem: Optional[bytes] = None,
                 mtu_discovery: bool = True) -> None:
        odcid = os.urandom(8)
        scid = os.urandom(8)
        tls = Tls13("client", alpn=alpn, server_name=server_name,
                    verify_cert=verify_cert, ca_pem=ca_pem,
                    tp=_encode_tp(scid, None))
        super().__init__("client", tls, scid, initial_keys(odcid),
                         mtu_discovery=mtu_discovery)
        self.remote_cid = odcid
        self._service()     # first flight: Initial(CRYPTO(ClientHello))

    @property
    def established(self) -> bool:
        return self.tls.complete and self.handshake_done


class QuicStream:
    """asyncio adapter with the TcpStream surface, so QUIC connections
    ride the node's ordinary ``handle_stream`` path."""

    def __init__(self, conn: _Conn, flush: Callable[[], None]) -> None:
        self.conn = conn
        self._flush = flush
        self._rx: asyncio.Queue = asyncio.Queue()
        self._buf = bytearray()
        self._eof = False

    def feed(self, data: bytes) -> None:
        if data:
            self._rx.put_nowait(data)

    def feed_eof(self) -> None:
        self._eof = True
        self._rx.put_nowait(b"")

    async def read(self, n: int) -> bytes:
        if not self._buf:
            if self._eof and self._rx.empty():
                return b""
            chunk = await self._rx.get()
            if not chunk:
                return b""
            self._buf += chunk
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def write(self, data: bytes) -> None:
        self.conn.send_stream(data)
        self._flush()

    async def drain(self) -> None:
        return None

    def close(self) -> None:
        if not self.conn.closed:
            self.conn.close(0, "closed")
            self._flush()
        self.feed_eof()

    async def wait_closed(self) -> None:
        return None

    def peername(self):
        return getattr(self.conn, "peer_addr", None)


class QuicEndpoint:
    """Server-side UDP demultiplexer (the quicer listener analog).

    ``on_connection(stream, conninfo_dict)`` is scheduled once per new
    connection as soon as the handshake completes — the node passes its
    ``handle_stream``."""

    def __init__(self, transport, cert_pem: bytes, key_pem: bytes,
                 on_connection, alpn: str = "mqtt",
                 idle_timeout: float = 120.0,
                 max_connections: int = 4096,
                 mtu_discovery: bool = True,
                 supervisor=None) -> None:
        self.transport = transport
        self.cert_pem = cert_pem
        self.key_pem = key_pem
        self.on_connection = on_connection
        self.alpn = alpn
        self.idle_timeout = idle_timeout
        # hard cap on live connection state: Initial keys derive from
        # the public DCID, so well-formed Initials are spoofable and
        # each costs an RSA server-flight sign — past the cap new
        # Initials are DROPPED until the idle sweep frees slots (a
        # retry-token round would authenticate source addresses; out of
        # scope, and the cap bounds the damage either way)
        self.max_connections = max_connections
        self.mtu_discovery = mtu_discovery
        self.by_cid: Dict[bytes, QuicServerConnection] = {}
        self.streams: Dict[QuicServerConnection, QuicStream] = {}
        self.handshakes = 0
        self.dropped_initials = 0
        self.retransmits = 0        # endpoint-lifetime (survives drops)
        self.retransmit_tick = 0.2
        # node's supervision tree (when embedded): the retransmission
        # timer registers as a transient child there, so a crashed tick
        # loop restarts instead of silently freezing every handshake PTO
        self.supervisor = supervisor
        self._timer_task = None     # asyncio.Task or supervise.Child


    def live_conns(self) -> list:
        """Unique live connections (by_cid holds 2 entries per conn)."""
        return list({id(c): c for c in self.by_cid.values()}.values())

    def _ensure_timer(self) -> None:
        """Retransmission timer: one endpoint-wide ~200 ms tick driving
        every connection's PTO (RFC 9002 analog; the 1 s node
        housekeeping is too coarse for handshake recovery).  Transient
        supervised child when a supervisor is attached — the loop ends
        normally when the last connection sweeps out and re-registers on
        the next Initial; a crash restarts it with backoff."""
        if self._timer_task is None or self._timer_task.done():
            sup = self.supervisor
            if sup is not None:
                self._timer_task = sup.start_child(
                    "quic.timer", self._timer_loop, restart="transient")
            else:
                try:
                    self._timer_task = \
                        asyncio.get_running_loop().create_task(
                            self._timer_loop())
                except RuntimeError:    # sans-io use (tests): no loop
                    pass

    async def _timer_loop(self) -> None:
        while self.by_cid:
            await asyncio.sleep(self.retransmit_tick)
            now = time.monotonic()
            for conn in self.live_conns():
                try:
                    if conn.on_timer(now):
                        self.retransmits += 1
                    self._flush(conn)   # retransmits AND paced/probe
                except Exception:       # datagrams ride the same tick
                    log.debug("quic retransmit", exc_info=True)
                    self._drop(conn)
        self._timer_task = None

    def datagram_received(self, data: bytes, addr) -> None:
        if len(data) < 7:
            return
        conn = self._route(data)
        if conn is None:
            if not (data[0] & 0x80):
                return                      # short header for unknown cid
            # new connection: accept ONLY a well-formed v1 Initial
            # (long-header type 0) at the 1200-byte anti-amplification
            # floor.  Anything else (stale Handshake retransmits after a
            # sweep, scanners, garbage versions) must not allocate state:
            # each spoofed-source datagram would otherwise grow by_cid
            # until the idle sweep.
            if (data[0] & 0x30) != 0x00:    # long-header type != Initial
                return
            if data[1:5] != b"\x00\x00\x00\x01":       # QUIC v1 only
                return
            if len(data) < 1200:            # RFC 9000 §14.1 client floor
                return
            p = 5
            dcil = data[p]; p += 1
            if dcil < 8 or p + dcil > len(data):
                return                      # our clients send >=8-byte cids
            dcid = data[p:p + dcil]
            if len(self.by_cid) >= 2 * self.max_connections:
                self.dropped_initials += 1      # 2 cid entries per conn
                return
            conn = QuicServerConnection(dcid, self.cert_pem, self.key_pem,
                                        alpn=self.alpn,
                                        mtu_discovery=self.mtu_discovery)
            conn.peer_addr = addr
            self.by_cid[dcid] = conn
            self.by_cid[conn.scid] = conn
            self._ensure_timer()
        conn.peer_addr = addr
        was_up = conn.established
        try:
            conn.receive(data)
        except Exception:
            log.debug("quic: dropping connection", exc_info=True)
            self._drop(conn)
            return
        self._flush(conn)
        if conn.established and not was_up:
            self.handshakes += 1
            stream = QuicStream(conn, lambda c=conn: self._flush(c))
            self.streams[conn] = stream
            info = {"listener": "quic:default", "peername": addr}
            asyncio.ensure_future(self.on_connection(stream, info))
        s = self.streams.get(conn)
        if s is not None:
            s.feed(conn.pop_stream_data())
            if conn.stream_fin or conn.closed:
                s.feed_eof()
        if conn.closed:
            self._drop(conn)

    def _route(self, data: bytes) -> Optional[QuicServerConnection]:
        if data[0] & 0x80:
            dcil = data[5]
            return self.by_cid.get(data[6:6 + dcil])
        return self.by_cid.get(data[1:9])

    def _flush(self, conn: _Conn) -> None:
        for dg in conn.take_outgoing():
            self.transport.sendto(dg, conn.peer_addr)

    def _drop(self, conn: QuicServerConnection) -> None:
        s = self.streams.pop(conn, None)
        if s is not None:
            s.feed_eof()
        for cid in [c for c, v in self.by_cid.items() if v is conn]:
            del self.by_cid[cid]

    def sweep(self, now: Optional[float] = None) -> int:
        now = now if now is not None else time.monotonic()
        stale = [c for c in self.live_conns()
                 if now - c.last_seen > self.idle_timeout]
        for c in stale:
            self._drop(c)
        return len(stale)

    def close(self) -> None:
        if self._timer_task is not None:
            self._timer_task.cancel()
            self._timer_task = None
        for conn in self.live_conns():
            conn.close(0, "server shutdown")
            self._flush(conn)
            s = self.streams.pop(conn, None)
            if s is not None:
                s.feed_eof()
        self.by_cid.clear()
        self.transport.close()
