"""MQTT-over-QUIC transport (RFC 9000/9001, v1).

Behavioral reference: ``emqx_quic_connection.erl`` + the ``quicer``
MsQuic NIF [U] (SURVEY.md §2.1 QUIC connection, §2.4).  No QUIC stack
exists in this environment (no MsQuic, and CPython's ``ssl`` exposes
neither DTLS nor the TLS-1.3-secrets API QUIC needs), so — the same
posture as the hand-rolled DTLS/Kafka/MySQL wire layers — the protocol
is implemented directly:

* :mod:`.crypto`  — packet protection: initial-secret derivation,
  per-level AEAD (AES-128-GCM) + header protection (AES-ECB mask),
  validated against the RFC 9001 Appendix A test vectors;
* :mod:`.tls13`   — the embedded TLS 1.3 handshake (x25519,
  TLS_AES_128_GCM_SHA256, rsa_pss_rsae_sha256 certificates,
  quic_transport_parameters extension), both roles;
* :mod:`.packet`  — long/short headers, varints, packet numbers;
* :mod:`.frames`  — CRYPTO/ACK/STREAM/HANDSHAKE_DONE/CONNECTION_CLOSE;
* :mod:`.connection` — sans-IO connection state machines + the
  :class:`~emqx_tpu.transport.quic.connection.QuicEndpoint` UDP
  demultiplexer that feeds MQTT bytes from stream 0 into the broker's
  ordinary channel machinery.

Deliberate scope cuts, recorded: no loss-recovery timers or
retransmission (flights fit loopback datagrams; a lost flight restarts
the connection), no connection migration, no 0-RTT, no flow-control
enforcement beyond generous static limits, single client-initiated
bidirectional stream (the MQTT byte stream — exactly how the reference
maps MQTT onto quicer streams).
"""

from .connection import QuicClient, QuicEndpoint, QuicServerConnection

__all__ = ["QuicClient", "QuicEndpoint", "QuicServerConnection"]
