"""QUIC v1 wire format: varints, long/short headers, packet numbers,
and the protect/unprotect pipeline (RFC 9000 §16–17, RFC 9001 §5.4).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

from .crypto import DirectionKeys

__all__ = [
    "PKT_INITIAL", "PKT_HANDSHAKE", "PKT_1RTT",
    "PlainPacket", "decode_varint", "encode_varint",
    "protect", "unprotect", "decode_pn",
]

QUIC_V1 = 1

PKT_INITIAL = "initial"
PKT_HANDSHAKE = "handshake"
PKT_1RTT = "1rtt"

_LONG_TYPE = {0: PKT_INITIAL, 2: PKT_HANDSHAKE}   # 1=0RTT, 3=Retry unused
_TYPE_BITS = {PKT_INITIAL: 0, PKT_HANDSHAKE: 2}


def encode_varint(v: int) -> bytes:
    if v < 0x40:
        return bytes([v])
    if v < 0x4000:
        return (v | 0x4000).to_bytes(2, "big")
    if v < 0x4000_0000:
        return (v | 0x8000_0000).to_bytes(4, "big")
    return (v | 0xC000_0000_0000_0000).to_bytes(8, "big")


def decode_varint(buf: bytes, off: int) -> Tuple[int, int]:
    """-> (value, new_offset)."""
    first = buf[off]
    ln = 1 << (first >> 6)
    v = int.from_bytes(buf[off:off + ln], "big") & ((1 << (8 * ln - 2)) - 1)
    return v, off + ln


def decode_pn(truncated: int, pn_len: int, largest: int) -> int:
    """Reconstruct a full packet number (RFC 9000 §A.3)."""
    expected = largest + 1
    win = 1 << (8 * pn_len)
    half = win // 2
    cand = (expected & ~(win - 1)) | truncated
    if cand <= expected - half and cand < (1 << 62) - win:
        return cand + win
    if cand > expected + half and cand >= win:
        return cand - win
    return cand


class PlainPacket(NamedTuple):
    kind: str          # initial | handshake | 1rtt
    dcid: bytes
    scid: bytes        # b"" for 1rtt
    pn: int
    payload: bytes     # decrypted frames
    token: bytes = b""


def protect(kind: str, keys: DirectionKeys, pn: int, payload: bytes,
            dcid: bytes, scid: bytes = b"", token: bytes = b"",
            pn_len: int = 4) -> bytes:
    """Build + encrypt one packet (AEAD then header protection)."""
    pn_bytes = pn.to_bytes(pn_len, "big")[-pn_len:]
    if kind == PKT_1RTT:
        first = 0x40 | (pn_len - 1)            # fixed bit, key phase 0
        header = bytes([first]) + dcid + pn_bytes
        pn_off = 1 + len(dcid)
    else:
        first = 0xC0 | (_TYPE_BITS[kind] << 4) | (pn_len - 1)
        hdr = bytearray([first])
        hdr += QUIC_V1.to_bytes(4, "big")
        hdr += bytes([len(dcid)]) + dcid
        hdr += bytes([len(scid)]) + scid
        if kind == PKT_INITIAL:
            hdr += encode_varint(len(token)) + token
        length = pn_len + len(payload) + 16    # + AEAD tag
        hdr += encode_varint(length)
        pn_off = len(hdr)
        hdr += pn_bytes
        header = bytes(hdr)
    sealed = keys.seal(pn, header, payload)
    pkt = bytearray(header + sealed)
    # header protection: sample starts 4 bytes after the pn offset
    sample = bytes(pkt[pn_off + 4:pn_off + 20])
    mask = keys.hp_mask(sample)
    if kind == PKT_1RTT:
        pkt[0] ^= mask[0] & 0x1F
    else:
        pkt[0] ^= mask[0] & 0x0F
    for i in range(pn_len):
        pkt[pn_off + i] ^= mask[1 + i]
    return bytes(pkt)


def unprotect(datagram: bytes, off: int, keys_for, largest_pn,
              local_cid_len: int = 8) -> Tuple[Optional[PlainPacket], int]:
    """Unprotect ONE packet starting at ``off``; -> (packet|None, next_off).

    ``keys_for(kind) -> DirectionKeys|None`` supplies the peer's send
    keys per level (None ⇒ skip: not yet available).  ``largest_pn(kind)``
    supplies the largest received pn for reconstruction.  Undecryptable
    or unknown packets skip to the end of the datagram (coalescing only
    matters for long-header packets, which carry explicit lengths).
    """
    first = datagram[off]
    if first & 0x80:                            # long header
        ver = int.from_bytes(datagram[off + 1:off + 5], "big")
        p = off + 5
        dcil = datagram[p]; p += 1
        dcid = datagram[p:p + dcil]; p += dcil
        scil = datagram[p]; p += 1
        scid = datagram[p:p + scil]; p += scil
        if ver != QUIC_V1:
            return None, len(datagram)
        kind = _LONG_TYPE.get((first >> 4) & 0x3)
        token = b""
        if kind == PKT_INITIAL:
            tlen, p = decode_varint(datagram, p)
            token = datagram[p:p + tlen]; p += tlen
        elif kind is None:
            return None, len(datagram)
        length, p = decode_varint(datagram, p)
        end = p + length
        pn_off = p
    else:                                       # short header (1-RTT)
        kind = PKT_1RTT
        dcid = datagram[off + 1:off + 1 + local_cid_len]
        scid = b""
        token = b""
        pn_off = off + 1 + local_cid_len
        end = len(datagram)
    keys = keys_for(kind)
    if keys is None or pn_off + 20 > len(datagram):
        return None, end
    sample = datagram[pn_off + 4:pn_off + 20]
    mask = keys.hp_mask(sample)
    buf = bytearray(datagram[off:end])
    rel_pn = pn_off - off
    if kind == PKT_1RTT:
        buf[0] ^= mask[0] & 0x1F
    else:
        buf[0] ^= mask[0] & 0x0F
    pn_len = (buf[0] & 0x03) + 1
    for i in range(pn_len):
        buf[rel_pn + i] ^= mask[1 + i]
    trunc = int.from_bytes(buf[rel_pn:rel_pn + pn_len], "big")
    pn = decode_pn(trunc, pn_len, largest_pn(kind))
    header = bytes(buf[:rel_pn + pn_len])
    body = bytes(buf[rel_pn + pn_len:])
    try:
        payload = keys.open(pn, header, body)
    except Exception:
        return None, end
    return PlainPacket(kind, dcid, scid, pn, payload, token), end
