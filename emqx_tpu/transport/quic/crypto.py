"""QUIC v1 packet protection (RFC 9001) — keys, AEAD, header masks.

Validated against RFC 9001 Appendix A: the initial-secret derivation,
client-initial encryption, and header-protection mask tests live in
``tests/test_quic.py`` and pin this module to the published vectors.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import NamedTuple

from cryptography.hazmat.primitives.ciphers import (
    Cipher, algorithms, modes,
)
from cryptography.hazmat.primitives.ciphers.aead import AESGCM

__all__ = ["DirectionKeys", "LevelKeys", "initial_keys", "hkdf_expand_label",
           "traffic_keys", "INITIAL_SALT_V1"]

INITIAL_SALT_V1 = bytes.fromhex("38762cf7f55934b34d179ae6a4c80cadccbb7f0a")


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def hkdf_expand_label(secret: bytes, label: bytes, context: bytes,
                      length: int) -> bytes:
    """TLS 1.3 HKDF-Expand-Label (RFC 8446 §7.1)."""
    full = b"tls13 " + label
    info = (length.to_bytes(2, "big") + bytes([len(full)]) + full
            + bytes([len(context)]) + context)
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac.new(secret, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


class DirectionKeys(NamedTuple):
    key: bytes   # 16 B AEAD key
    iv: bytes    # 12 B
    hp: bytes    # 16 B header-protection key

    def seal(self, pn: int, header: bytes, payload: bytes) -> bytes:
        nonce = (int.from_bytes(self.iv, "big") ^ pn).to_bytes(12, "big")
        return AESGCM(self.key).encrypt(nonce, payload, header)

    def open(self, pn: int, header: bytes, payload: bytes) -> bytes:
        nonce = (int.from_bytes(self.iv, "big") ^ pn).to_bytes(12, "big")
        return AESGCM(self.key).decrypt(nonce, payload, header)

    def hp_mask(self, sample: bytes) -> bytes:
        """AES-ECB(hp_key, sample)[:5] (RFC 9001 §5.4.3)."""
        enc = Cipher(algorithms.AES(self.hp), modes.ECB()).encryptor()
        return (enc.update(sample) + enc.finalize())[:5]


def traffic_keys(secret: bytes) -> DirectionKeys:
    return DirectionKeys(
        key=hkdf_expand_label(secret, b"quic key", b"", 16),
        iv=hkdf_expand_label(secret, b"quic iv", b"", 12),
        hp=hkdf_expand_label(secret, b"quic hp", b"", 16),
    )


class LevelKeys(NamedTuple):
    client: DirectionKeys
    server: DirectionKeys


def initial_keys(dcid: bytes) -> LevelKeys:
    """Initial-level keys from the client's first DCID (RFC 9001 §5.2)."""
    initial = _hkdf_extract(INITIAL_SALT_V1, dcid)
    cs = hkdf_expand_label(initial, b"client in", b"", 32)
    ss = hkdf_expand_label(initial, b"server in", b"", 32)
    return LevelKeys(client=traffic_keys(cs), server=traffic_keys(ss))
