"""QUIC v1 frame encode/parse (RFC 9000 §19) — the subset the
handshake + a single MQTT byte stream need."""

from __future__ import annotations

from typing import Iterator, List, NamedTuple, Tuple

from .packet import decode_varint, encode_varint

__all__ = [
    "AckFrame", "CloseFrame", "CryptoFrame", "StreamFrame",
    "encode_ack", "encode_crypto", "encode_stream", "encode_close",
    "parse_frames", "HANDSHAKE_DONE", "PING",
]

PADDING = 0x00
PING = 0x01
ACK = 0x02
CRYPTO = 0x06
NEW_TOKEN = 0x07
STREAM_BASE = 0x08       # 0x08..0x0f: OFF=0x04 LEN=0x02 FIN=0x01
MAX_DATA = 0x10
MAX_STREAM_DATA = 0x11
MAX_STREAMS_BIDI = 0x12
MAX_STREAMS_UNI = 0x13
DATA_BLOCKED = 0x14
STREAM_DATA_BLOCKED = 0x15
STREAMS_BLOCKED_BIDI = 0x16
STREAMS_BLOCKED_UNI = 0x17
NEW_CONNECTION_ID = 0x18
RETIRE_CONNECTION_ID = 0x19
CONNECTION_CLOSE_QUIC = 0x1C
CONNECTION_CLOSE_APP = 0x1D
HANDSHAKE_DONE = 0x1E


class CryptoFrame(NamedTuple):
    offset: int
    data: bytes


class StreamFrame(NamedTuple):
    stream_id: int
    offset: int
    data: bytes
    fin: bool


class AckFrame(NamedTuple):
    largest: int
    ranges: List[Tuple[int, int]]   # [(lo, hi)] descending


class CloseFrame(NamedTuple):
    error_code: int
    reason: str
    app: bool


def encode_crypto(offset: int, data: bytes) -> bytes:
    return (bytes([CRYPTO]) + encode_varint(offset)
            + encode_varint(len(data)) + data)


def encode_stream(stream_id: int, offset: int, data: bytes,
                  fin: bool = False) -> bytes:
    t = STREAM_BASE | 0x04 | 0x02 | (0x01 if fin else 0)
    return (bytes([t]) + encode_varint(stream_id) + encode_varint(offset)
            + encode_varint(len(data)) + data)


def encode_ack(pns: List[int]) -> bytes:
    """ACK frame over a received-pn list (collapsed into ranges)."""
    s = sorted(set(pns), reverse=True)
    ranges: List[Tuple[int, int]] = []
    hi = lo = s[0]
    for pn in s[1:]:
        if pn == lo - 1:
            lo = pn
        else:
            ranges.append((lo, hi))
            hi = lo = pn
    ranges.append((lo, hi))
    out = bytearray([ACK])
    out += encode_varint(ranges[0][1])            # largest acked
    out += encode_varint(0)                       # ack delay
    out += encode_varint(len(ranges) - 1)
    out += encode_varint(ranges[0][1] - ranges[0][0])
    prev_lo = ranges[0][0]
    for lo, hi in ranges[1:]:
        out += encode_varint(prev_lo - hi - 2)    # gap
        out += encode_varint(hi - lo)             # range length
        prev_lo = lo
    return bytes(out)


def encode_close(error_code: int, reason: str = "",
                 app: bool = True) -> bytes:
    r = reason.encode()
    t = CONNECTION_CLOSE_APP if app else CONNECTION_CLOSE_QUIC
    out = bytes([t]) + encode_varint(error_code)
    if not app:
        out += encode_varint(0)                   # offending frame type
    return out + encode_varint(len(r)) + r


def parse_frames(payload: bytes) -> Iterator[object]:
    """Yield parsed frames; unknown-but-skippable frames are consumed
    silently, unskippable ones raise."""
    off = 0
    n = len(payload)
    while off < n:
        t = payload[off]
        if t == PADDING:
            off += 1
            continue
        if t == PING:
            off += 1
            yield PING          # ack-eliciting: receiver must ack (a
            continue            # PING-only packet is how MTU probes
                                # and keepalives get acknowledged)
        if t in (ACK, ACK + 1):
            off += 1
            largest, off = decode_varint(payload, off)
            _delay, off = decode_varint(payload, off)
            count, off = decode_varint(payload, off)
            first, off = decode_varint(payload, off)
            ranges = [(largest - first, largest)]
            lo = largest - first
            for _ in range(count):
                gap, off = decode_varint(payload, off)
                rlen, off = decode_varint(payload, off)
                hi = lo - gap - 2
                lo = hi - rlen
                ranges.append((lo, hi))
            if t == ACK + 1:                      # ECN counts
                for _ in range(3):
                    _, off = decode_varint(payload, off)
            yield AckFrame(largest, ranges)
            continue
        if t == CRYPTO:
            off += 1
            o, off = decode_varint(payload, off)
            ln, off = decode_varint(payload, off)
            yield CryptoFrame(o, payload[off:off + ln])
            off += ln
            continue
        if STREAM_BASE <= t <= STREAM_BASE + 0x07:
            off += 1
            sid, off = decode_varint(payload, off)
            o = 0
            if t & 0x04:
                o, off = decode_varint(payload, off)
            if t & 0x02:
                ln, off = decode_varint(payload, off)
            else:
                ln = n - off
            yield StreamFrame(sid, o, payload[off:off + ln],
                              bool(t & 0x01))
            off += ln
            continue
        if t in (CONNECTION_CLOSE_QUIC, CONNECTION_CLOSE_APP):
            off += 1
            code, off = decode_varint(payload, off)
            if t == CONNECTION_CLOSE_QUIC:
                _ft, off = decode_varint(payload, off)
            rlen, off = decode_varint(payload, off)
            yield CloseFrame(code, payload[off:off + rlen].decode(
                "utf-8", "replace"), t == CONNECTION_CLOSE_APP)
            off += rlen
            continue
        if t == HANDSHAKE_DONE:
            off += 1
            yield HANDSHAKE_DONE
            continue
        if t == NEW_TOKEN:
            off += 1
            ln, off = decode_varint(payload, off)
            off += ln
            continue
        if t in (MAX_DATA, MAX_STREAMS_BIDI, MAX_STREAMS_UNI,
                 DATA_BLOCKED, STREAMS_BLOCKED_BIDI, STREAMS_BLOCKED_UNI,
                 RETIRE_CONNECTION_ID):
            off += 1
            _, off = decode_varint(payload, off)
            continue
        if t in (MAX_STREAM_DATA, STREAM_DATA_BLOCKED):
            off += 1
            _, off = decode_varint(payload, off)
            _, off = decode_varint(payload, off)
            continue
        if t == NEW_CONNECTION_ID:
            off += 1
            _seq, off = decode_varint(payload, off)
            _ret, off = decode_varint(payload, off)
            cl = payload[off]
            off += 1 + cl + 16                    # cid + reset token
            continue
        raise ValueError(f"unhandled frame type {t:#x}")
