"""Minimal TLS 1.3 handshake embedded in QUIC CRYPTO streams.

RFC 8446 restricted to what QUIC v1 needs and one ciphersuite:
``TLS_AES_128_GCM_SHA256`` + x25519 + ``rsa_pss_rsae_sha256``
certificates, ALPN, and the ``quic_transport_parameters`` extension
(RFC 9001 §8.2).  Both roles, sans-IO:

    tls = Tls13(role="server", cert_pem=..., key_pem=..., tp=params)
    tls.feed(LEVEL_INITIAL, crypto_bytes)   # reassembled CRYPTO data
    for level, msg in tls.take_outgoing(): ...
    tls.hs_secrets / tls.app_secrets        # -> (client, server) or None

The QUIC packet layer derives its per-level keys from the secrets via
:func:`~emqx_tpu.transport.quic.crypto.traffic_keys`.

Scope cuts, recorded: no PSK/resumption/0-RTT, no HelloRetryRequest
(x25519 is mandatory for our own client), no client certificates, no
KeyUpdate.  NewSessionTicket from a peer is parsed and ignored.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Dict, List, Optional, Tuple

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import padding
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey, X25519PublicKey,
)

from .crypto import hkdf_expand_label

__all__ = ["Tls13", "TlsError", "LEVEL_INITIAL", "LEVEL_HANDSHAKE",
           "LEVEL_APP"]

LEVEL_INITIAL = "initial"
LEVEL_HANDSHAKE = "handshake"
LEVEL_APP = "1rtt"

HT_CLIENT_HELLO = 1
HT_SERVER_HELLO = 2
HT_NEW_SESSION_TICKET = 4
HT_ENCRYPTED_EXTENSIONS = 8
HT_CERTIFICATE = 11
HT_CERTIFICATE_VERIFY = 15
HT_FINISHED = 20

SUITE_AES128_GCM_SHA256 = 0x1301
GROUP_X25519 = 0x001D
SIG_RSA_PSS_SHA256 = 0x0804

EXT_SERVER_NAME = 0
EXT_SUPPORTED_GROUPS = 10
EXT_SIG_ALGS = 13
EXT_ALPN = 16
EXT_SUPPORTED_VERSIONS = 43
EXT_KEY_SHARE = 51
EXT_QUIC_TP = 0x39


class TlsError(Exception):
    pass


def _u8(b: bytes) -> bytes:
    return bytes([len(b)]) + b


def _u16(b: bytes) -> bytes:
    return len(b).to_bytes(2, "big") + b


def _u24(b: bytes) -> bytes:
    return len(b).to_bytes(3, "big") + b


def _ext(t: int, body: bytes) -> bytes:
    return t.to_bytes(2, "big") + _u16(body)


def _hs_msg(t: int, body: bytes) -> bytes:
    return bytes([t]) + _u24(body)


def _parse_exts(buf: bytes) -> Dict[int, bytes]:
    out: Dict[int, bytes] = {}
    off = 0
    while off + 4 <= len(buf):
        t = int.from_bytes(buf[off:off + 2], "big")
        ln = int.from_bytes(buf[off + 2:off + 4], "big")
        out[t] = buf[off + 4:off + 4 + ln]
        off += 4 + ln
    return out


def _derive_secret(secret: bytes, label: bytes, transcript: bytes) -> bytes:
    return hkdf_expand_label(secret, label, transcript, 32)


_CV_CONTEXT = {
    "server": b"\x20" * 64 + b"TLS 1.3, server CertificateVerify\x00",
    "client": b"\x20" * 64 + b"TLS 1.3, client CertificateVerify\x00",
}


class Tls13:
    def __init__(self, role: str, *, tp: bytes,
                 cert_pem: Optional[bytes] = None,
                 key_pem: Optional[bytes] = None,
                 alpn: str = "mqtt",
                 server_name: str = "",
                 verify_cert: bool = False,
                 ca_pem: Optional[bytes] = None) -> None:
        assert role in ("client", "server")
        self.role = role
        self.alpn = alpn
        self.tp = tp                      # local quic_transport_parameters
        self.peer_tp: Optional[bytes] = None
        self.server_name = server_name
        self.verify_cert = verify_cert
        self.ca_pem = ca_pem
        self.cert_pem = cert_pem
        self.key_pem = key_pem
        self.complete = False
        self.peer_cert_der: Optional[bytes] = None
        self.hs_secrets: Optional[Tuple[bytes, bytes]] = None  # (c, s)
        self.app_secrets: Optional[Tuple[bytes, bytes]] = None
        self._ecdh = X25519PrivateKey.generate()
        self._transcript = hashlib.sha256()
        self._out: List[Tuple[str, bytes]] = []
        self._bufs: Dict[str, bytearray] = {
            LEVEL_INITIAL: bytearray(), LEVEL_HANDSHAKE: bytearray(),
            LEVEL_APP: bytearray(),
        }
        self._hs_secret = b""
        self._master = b""
        self._server_hs_transcript = b""
        if role == "client":
            self._send_client_hello()

    # -- transcript helpers --------------------------------------------

    def _absorb(self, msg: bytes) -> None:
        self._transcript.update(msg)

    def _th(self) -> bytes:
        return self._transcript.copy().digest()

    def take_outgoing(self) -> List[Tuple[str, bytes]]:
        out, self._out = self._out, []
        return out

    # -- key schedule --------------------------------------------------

    def _derive_handshake(self, shared: bytes) -> None:
        early = hmac.new(b"\x00" * 32, b"\x00" * 32, hashlib.sha256).digest()
        derived = _derive_secret(early, b"derived",
                                 hashlib.sha256(b"").digest())
        self._hs_secret = hmac.new(derived, shared, hashlib.sha256).digest()
        th = self._th()     # CH..SH
        self.hs_secrets = (
            _derive_secret(self._hs_secret, b"c hs traffic", th),
            _derive_secret(self._hs_secret, b"s hs traffic", th),
        )
        derived2 = _derive_secret(self._hs_secret, b"derived",
                                  hashlib.sha256(b"").digest())
        self._master = hmac.new(derived2, b"\x00" * 32,
                                hashlib.sha256).digest()

    def _derive_app(self, th: bytes) -> None:
        self.app_secrets = (
            _derive_secret(self._master, b"c ap traffic", th),
            _derive_secret(self._master, b"s ap traffic", th),
        )

    @staticmethod
    def _finished(secret: bytes, th: bytes) -> bytes:
        fk = hkdf_expand_label(secret, b"finished", b"", 32)
        return hmac.new(fk, th, hashlib.sha256).digest()

    # -- message construction ------------------------------------------

    def _send_client_hello(self) -> None:
        pub = self._ecdh.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        exts = b"".join([
            _ext(EXT_SUPPORTED_VERSIONS, b"\x02\x03\x04"),
            _ext(EXT_SUPPORTED_GROUPS, _u16(GROUP_X25519.to_bytes(2, "big"))),
            _ext(EXT_SIG_ALGS, _u16(SIG_RSA_PSS_SHA256.to_bytes(2, "big"))),
            _ext(EXT_KEY_SHARE, _u16(
                GROUP_X25519.to_bytes(2, "big") + _u16(pub))),
            _ext(EXT_ALPN, _u16(_u8(self.alpn.encode()))),
            _ext(EXT_QUIC_TP, self.tp),
        ] + ([_ext(EXT_SERVER_NAME, _u16(
            b"\x00" + _u16(self.server_name.encode())))]
            if self.server_name else []))
        body = (b"\x03\x03" + os.urandom(32) + _u8(b"")
                + _u16(SUITE_AES128_GCM_SHA256.to_bytes(2, "big"))
                + _u8(b"\x00") + _u16(exts))
        msg = _hs_msg(HT_CLIENT_HELLO, body)
        self._absorb(msg)
        self._out.append((LEVEL_INITIAL, msg))

    def _server_flight(self, client_pub: bytes) -> None:
        # ServerHello
        pub = self._ecdh.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        sh_exts = b"".join([
            _ext(EXT_SUPPORTED_VERSIONS, b"\x03\x04"),
            _ext(EXT_KEY_SHARE, GROUP_X25519.to_bytes(2, "big") + _u16(pub)),
        ])
        sh = _hs_msg(HT_SERVER_HELLO,
                     b"\x03\x03" + os.urandom(32) + _u8(b"")
                     + SUITE_AES128_GCM_SHA256.to_bytes(2, "big") + b"\x00"
                     + _u16(sh_exts))
        self._absorb(sh)
        self._out.append((LEVEL_INITIAL, sh))
        shared = self._ecdh.exchange(
            X25519PublicKey.from_public_bytes(client_pub))
        self._derive_handshake(shared)

        # EncryptedExtensions
        ee = _hs_msg(HT_ENCRYPTED_EXTENSIONS, _u16(b"".join([
            _ext(EXT_ALPN, _u16(_u8(self.alpn.encode()))),
            _ext(EXT_QUIC_TP, self.tp),
        ])))
        self._absorb(ee)
        # Certificate
        from cryptography import x509

        cert = x509.load_pem_x509_certificate(self.cert_pem)
        der = cert.public_bytes(serialization.Encoding.DER)
        cert_msg = _hs_msg(HT_CERTIFICATE,
                           _u8(b"") + _u24(_u24(der) + _u16(b"")))
        self._absorb(cert_msg)
        # CertificateVerify over the transcript so far
        key = serialization.load_pem_private_key(self.key_pem, None)
        sig = key.sign(
            _CV_CONTEXT["server"] + self._th(),
            padding.PSS(mgf=padding.MGF1(hashes.SHA256()),
                        salt_length=hashes.SHA256().digest_size),
            hashes.SHA256())
        cv = _hs_msg(HT_CERTIFICATE_VERIFY,
                     SIG_RSA_PSS_SHA256.to_bytes(2, "big") + _u16(sig))
        self._absorb(cv)
        # server Finished
        fin = _hs_msg(HT_FINISHED,
                      self._finished(self.hs_secrets[1], self._th()))
        self._absorb(fin)
        self._server_hs_transcript = self._th()   # CH..server Finished
        self._derive_app(self._server_hs_transcript)
        for m in (ee, cert_msg, cv, fin):
            self._out.append((LEVEL_HANDSHAKE, m))

    # -- incoming ------------------------------------------------------

    def feed(self, level: str, data: bytes) -> None:
        buf = self._bufs[level]
        buf.extend(data)
        while len(buf) >= 4:
            ln = int.from_bytes(buf[1:4], "big")
            if len(buf) < 4 + ln:
                return
            msg = bytes(buf[:4 + ln])
            del buf[:4 + ln]
            self._handle(level, msg[0], msg[4:], msg)

    def _handle(self, level: str, ht: int, body: bytes, raw: bytes) -> None:
        if self.role == "server":
            self._server_handle(level, ht, body, raw)
        else:
            self._client_handle(level, ht, body, raw)

    # .. server side ...................................................

    def _server_handle(self, level, ht, body, raw) -> None:
        if ht == HT_CLIENT_HELLO and level == LEVEL_INITIAL:
            if self.hs_secrets is not None:
                return                       # retransmit
            off = 2 + 32
            sid = body[off]
            off += 1 + sid
            n = int.from_bytes(body[off:off + 2], "big")
            suites = [int.from_bytes(body[off + 2 + i:off + 4 + i], "big")
                      for i in range(0, n, 2)]
            off += 2 + n
            comp = body[off]
            off += 1 + comp
            elen = int.from_bytes(body[off:off + 2], "big")
            exts = _parse_exts(body[off + 2:off + 2 + elen])
            if SUITE_AES128_GCM_SHA256 not in suites:
                raise TlsError("no shared cipher suite")
            ks = exts.get(EXT_KEY_SHARE)
            client_pub = None
            if ks is not None:
                p = 2
                while p + 4 <= len(ks):
                    grp = int.from_bytes(ks[p:p + 2], "big")
                    kl = int.from_bytes(ks[p + 2:p + 4], "big")
                    if grp == GROUP_X25519:
                        client_pub = ks[p + 4:p + 4 + kl]
                    p += 4 + kl
            if client_pub is None:
                raise TlsError("no x25519 key share (no HRR support)")
            if EXT_QUIC_TP in exts:
                self.peer_tp = exts[EXT_QUIC_TP]
            self._absorb(raw)
            self._server_flight(client_pub)
            return
        if ht == HT_FINISHED and level == LEVEL_HANDSHAKE:
            want = self._finished(self.hs_secrets[0], self._th())
            if not hmac.compare_digest(body, want):
                raise TlsError("bad client Finished")
            self._absorb(raw)
            self.complete = True
            return
        raise TlsError(f"unexpected handshake {ht} at {level} (server)")

    # .. client side ...................................................

    def _client_handle(self, level, ht, body, raw) -> None:
        if ht == HT_SERVER_HELLO and level == LEVEL_INITIAL:
            off = 2 + 32
            sid = body[off]
            off += 1 + sid
            suite = int.from_bytes(body[off:off + 2], "big")
            off += 3                        # suite + compression
            elen = int.from_bytes(body[off:off + 2], "big")
            exts = _parse_exts(body[off + 2:off + 2 + elen])
            if suite != SUITE_AES128_GCM_SHA256:
                raise TlsError(f"server chose {suite:#x}")
            ks = exts.get(EXT_KEY_SHARE)
            if ks is None or int.from_bytes(ks[:2], "big") != GROUP_X25519:
                raise TlsError("missing x25519 key share")
            kl = int.from_bytes(ks[2:4], "big")
            server_pub = ks[4:4 + kl]
            self._absorb(raw)
            shared = self._ecdh.exchange(
                X25519PublicKey.from_public_bytes(server_pub))
            self._derive_handshake(shared)
            return
        if level == LEVEL_HANDSHAKE and ht == HT_ENCRYPTED_EXTENSIONS:
            exts = _parse_exts(body[2:2 + int.from_bytes(body[:2], "big")])
            if EXT_QUIC_TP in exts:
                self.peer_tp = exts[EXT_QUIC_TP]
            self._absorb(raw)
            return
        if level == LEVEL_HANDSHAKE and ht == HT_CERTIFICATE:
            off = 1 + body[0]               # context
            total = int.from_bytes(body[off:off + 3], "big")
            p = off + 3
            if total:
                dl = int.from_bytes(body[p:p + 3], "big")
                self.peer_cert_der = body[p + 3:p + 3 + dl]
            self._absorb(raw)
            return
        if level == LEVEL_HANDSHAKE and ht == HT_CERTIFICATE_VERIFY:
            alg = int.from_bytes(body[:2], "big")
            sl = int.from_bytes(body[2:4], "big")
            sig = body[4:4 + sl]
            if self.verify_cert:
                if alg != SIG_RSA_PSS_SHA256 or self.peer_cert_der is None:
                    raise TlsError("unsupported certificate verify")
                from cryptography import x509

                cert = x509.load_der_x509_certificate(self.peer_cert_der)
                cert.public_key().verify(
                    sig, _CV_CONTEXT["server"] + self._th(),
                    padding.PSS(mgf=padding.MGF1(hashes.SHA256()),
                                salt_length=hashes.SHA256().digest_size),
                    hashes.SHA256())
                if self.ca_pem is not None:
                    from cryptography import x509 as _x

                    ca = _x.load_pem_x509_certificate(self.ca_pem)
                    cert.verify_directly_issued_by(ca)
            self._absorb(raw)
            return
        if level == LEVEL_HANDSHAKE and ht == HT_FINISHED:
            want = self._finished(self.hs_secrets[1], self._th())
            if not hmac.compare_digest(body, want):
                raise TlsError("bad server Finished")
            self._absorb(raw)
            self._derive_app(self._th())
            fin = _hs_msg(HT_FINISHED,
                          self._finished(self.hs_secrets[0], self._th()))
            # client Finished does NOT enter the app-secret transcript
            self._out.append((LEVEL_HANDSHAKE, fin))
            self.complete = True
            return
        if ht == HT_NEW_SESSION_TICKET:
            return                          # parsed-and-ignored
        raise TlsError(f"unexpected handshake {ht} at {level} (client)")
