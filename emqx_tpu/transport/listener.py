"""Listeners: acceptor endpoints feeding connections into the broker.

Behavioral reference: ``emqx_listeners.erl`` + esockd acceptor pools /
cowboy WS [U] (SURVEY.md §3.1 boot).  asyncio's event-loop accept path
replaces esockd's acceptor pool; per-listener connection caps and a
connect-rate token bucket implement esockd's ``max_connections`` /
``max_conn_rate``.
"""

from __future__ import annotations

import asyncio
import logging
import ssl as _ssl
from typing import Awaitable, Callable, Dict, List, Optional

from ..broker.limiter import TokenBucket
from .connection import ConnInfo, TcpStream, set_nodelay
from .ws import WsError, WsStream, server_handshake

log = logging.getLogger(__name__)

__all__ = ["Listener", "Listeners"]

# handler(stream, conninfo) -> runs the connection to completion
Handler = Callable[[object, ConnInfo], Awaitable[None]]


class _ShedProtocol(asyncio.Protocol):
    """Accept-and-close: the overload answer when the cap/rate trips."""

    def connection_made(self, transport) -> None:
        transport.close()


class Listener:
    def __init__(
        self,
        name: str,
        bind: str,
        handler: Handler,
        kind: str = "tcp",            # tcp | ws
        ssl_context: Optional[_ssl.SSLContext] = None,
        max_connections: int = 1 << 20,
        max_conn_rate: float = 0.0,   # conns/s, 0 = unlimited
        ws_path: str = "/mqtt",
        reuse_port: bool = False,
        proto_factory: Optional[Callable[[ConnInfo], object]] = None,
        shard_pool=None,
    ) -> None:
        self.name = name
        self.kind = kind
        host, _, port = bind.rpartition(":")
        self.host, self.port = host or "0.0.0.0", int(port)
        self.handler = handler
        self.ssl_context = ssl_context
        self.max_connections = max_connections
        self.ws_path = ws_path
        # SO_REUSEPORT: several broker PROCESSES bind the same port and
        # the kernel load-balances accepted connections across them —
        # the esockd-multi-acceptor analog for scaling the connection
        # plane past one core (peers cluster as usual; routes replicate)
        self.reuse_port = reuse_port
        # protocol-mode datapath (transport/proto_conn.py): zero
        # per-connection tasks; used for plain TCP when the node
        # provides a factory
        self.proto_factory = proto_factory
        # connection-plane sharding (transport/shards.py): when a pool
        # is attached, the pool's per-shard SO_REUSEPORT listeners do
        # the accepting (one per worker loop) and this listener object
        # is the aggregate view — counts, caps and info() roll up the
        # per-shard numbers
        self.shard_pool = shard_pool
        self._conn_rate = TokenBucket(max_conn_rate)
        self._server: Optional[asyncio.AbstractServer] = None
        self._main_conns = 0
        self.shed_count = 0

    @property
    def current_connections(self) -> int:
        """Live connections across the main-loop server AND every
        shard (each shard counts its own accepts on its own loop; the
        sum is a racy-but-monotonic-enough aggregate, exactly like
        esockd's per-acceptor counters)."""
        pool = self.shard_pool
        return self._main_conns + (pool.conn_count()
                                   if pool is not None else 0)

    @property
    def running(self) -> bool:
        if self._server is not None:
            return True
        pool = self.shard_pool
        return pool is not None and pool.running

    async def start(self) -> None:
        if self.shard_pool is not None and self.kind == "tcp" \
                and self.ssl_context is None \
                and self.proto_factory is not None:
            self.shard_pool.listener = self
            self.port = await self.shard_pool.start(self.host, self.port)
            log.info("listener %s (%s) sharded ×%d on %s:%d", self.name,
                     self.kind, self.shard_pool.n, self.host, self.port)
            return
        self.shard_pool = None  # pool unusable for this listener kind
        if self.proto_factory is not None and self.kind == "tcp" \
                and self.ssl_context is None:
            loop = asyncio.get_running_loop()
            self._server = await loop.create_server(
                self._make_protocol, self.host, self.port,
                reuse_port=self.reuse_port or None,
            )
        else:
            self._server = await asyncio.start_server(
                self._accept, self.host, self.port, ssl=self.ssl_context,
                reuse_port=self.reuse_port or None,
            )
        # resolve the real port for bind=":0" (tests)
        socks = self._server.sockets or []
        if socks and self.port == 0:
            self.port = socks[0].getsockname()[1]
        log.info("listener %s (%s) on %s:%d", self.name, self.kind,
                 self.host, self.port)

    async def stop(self) -> None:
        if self.shard_pool is not None:
            await self.shard_pool.stop()
        if self._server is not None:
            self._server.close()
            try:
                # 3.12: wait_closed() blocks until every connection handler
                # returns; a socket that never spoke MQTT (so was never
                # kicked by the node) would hang us here forever
                await asyncio.wait_for(self._server.wait_closed(), timeout=5.0)
            except asyncio.TimeoutError:
                log.warning(
                    "listener %s: connections still open at stop", self.name
                )
            self._server = None

    def _make_protocol(self):
        """Protocol-mode accept with esockd-style shedding BEFORE any
        protocol work: past the cap/rate, not even a Channel is built —
        a trivial closing protocol answers the flood."""
        ok, _ = self._conn_rate.consume(1.0)
        if not ok or self.current_connections >= self.max_connections:
            self.shed_count += 1
            return _ShedProtocol()
        info = ConnInfo(listener=f"{self.kind}:{self.name}",
                        tls=self.ssl_context is not None)
        proto = self.proto_factory(info)
        orig_made = proto.connection_made
        orig_lost = proto.connection_lost

        def made(transport):
            self._main_conns += 1
            proto._listener_counted = True
            orig_made(transport)

        def lost(exc):
            if getattr(proto, "_listener_counted", False):
                self._main_conns -= 1
            orig_lost(exc)

        proto.connection_made = made
        proto.connection_lost = lost
        return proto

    async def _accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        ok, _ = self._conn_rate.consume(1.0)
        if not ok or self.current_connections >= self.max_connections:
            # esockd sheds by closing the socket before any protocol work
            self.shed_count += 1
            writer.close()
            return
        self._main_conns += 1
        set_nodelay(writer.get_extra_info("socket"))
        info = ConnInfo(
            peername=writer.get_extra_info("peername"),
            sockname=writer.get_extra_info("sockname"),
            listener=f"{self.kind}:{self.name}",
            ws=self.kind == "ws",
            tls=self.ssl_context is not None,
        )
        try:
            if self.kind == "ws":
                try:
                    await server_handshake(reader, writer, path=self.ws_path)
                except (WsError, asyncio.IncompleteReadError, ConnectionError):
                    writer.close()
                    return
                stream = WsStream(reader, writer)
            else:
                stream = TcpStream(reader, writer)
            await self.handler(stream, info)
        except Exception:
            log.exception("listener %s: connection handler crashed", self.name)
            writer.close()
        finally:
            self._main_conns -= 1

    def info(self) -> dict:
        return {
            "id": f"{self.kind}:{self.name}",
            "type": self.kind,
            "bind": f"{self.host}:{self.port}",
            "running": self.running,
            "max_connections": self.max_connections,
            "current_connections": self.current_connections,
            "shed_count": self.shed_count,
            **({"shards": self.shard_pool.info()}
               if self.shard_pool is not None else {}),
        }


class Listeners:
    """Registry of named listeners (start/stop all, REST surface)."""

    def __init__(self) -> None:
        self._by_id: Dict[str, Listener] = {}

    def add(self, lst: Listener) -> Listener:
        self._by_id[f"{lst.kind}:{lst.name}"] = lst
        return lst

    def get(self, lid: str) -> Optional[Listener]:
        return self._by_id.get(lid)

    def all(self) -> List[Listener]:
        return list(self._by_id.values())

    async def start_all(self) -> None:
        for lst in self._by_id.values():
            if not lst.running:
                await lst.start()

    async def stop_all(self) -> None:
        for lst in self._by_id.values():
            await lst.stop()
