"""OCSP stapling cache for the TLS listener.

Behavioral reference: ``emqx_ocsp_cache.erl`` [U] (SURVEY.md §2.1 TLS
utils): the broker — not each client — asks the CA's OCSP responder
whether its OWN server certificate is still good, caches the DER
response, refreshes it ahead of expiry, and staples it into TLS
handshakes so clients get revocation proof without contacting the CA.

Scope note, recorded honestly: CPython's ``ssl`` module exposes no
server-side ``SSL_set_tlsext_status`` equivalent, so the final staple
hand-off is gated on runtime support (the same posture as TLS-PSK,
``node._build_ssl_context``).  Everything the reference's cache does is
here and tested against a mocked responder: request construction
(RFC 6960 via ``cryptography.x509.ocsp``), POST to the responder URL
from the certificate's AIA extension (or an override), response
validation (status, this/next update window), TTL'd caching with
stale-while-refresh semantics, periodic refresh, and fail-open vs
fail-closed reporting for the health surface.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

log = logging.getLogger(__name__)

__all__ = ["OcspCache", "OcspError"]


class OcspError(Exception):
    pass


class OcspCache:
    """Fetch + cache the stapled OCSP response for one server cert.

    ``fetch(url, der_request) -> der_response`` is injectable (tests use
    a mocked responder); the default POSTs over the in-repo HTTP client.
    """

    def __init__(
        self,
        cert_pem: bytes,
        issuer_pem: bytes,
        responder_url: Optional[str] = None,
        refresh_interval_s: float = 3600.0,
        refresh_http_timeout_s: float = 10.0,
        fetch: Optional[Callable] = None,
        supervisor: Optional[object] = None,
    ) -> None:
        from cryptography import x509

        # node's supervision tree (when embedded): the refresh loop
        # registers there so a crashed refresher restarts instead of
        # the staple silently going stale until node restart
        self.supervisor = supervisor

        self.cert = x509.load_pem_x509_certificate(cert_pem)
        self.issuer = x509.load_pem_x509_certificate(issuer_pem)
        self.responder_url = responder_url or self._aia_url()
        self.refresh_interval_s = refresh_interval_s
        self.refresh_http_timeout_s = refresh_http_timeout_s
        self._fetch = fetch or self._default_fetch
        self._response_der: Optional[bytes] = None
        self._status: Optional[str] = None
        self._next_update: Optional[float] = None
        self._fetched_at: Optional[float] = None
        self._task: Optional[asyncio.Task] = None
        self.refreshes = 0
        self.failures = 0

    # -- request construction ------------------------------------------

    def _aia_url(self) -> Optional[str]:
        from cryptography import x509
        from cryptography.x509.oid import (
            AuthorityInformationAccessOID, ExtensionOID,
        )

        try:
            aia = self.cert.extensions.get_extension_for_oid(
                ExtensionOID.AUTHORITY_INFORMATION_ACCESS).value
        except x509.ExtensionNotFound:
            # the cert simply has no AIA extension: OCSP not applicable
            return None
        for desc in aia:
            if desc.access_method == AuthorityInformationAccessOID.OCSP:
                return desc.access_location.value
        return None

    def build_request(self) -> bytes:
        """DER OCSP request for (cert, issuer) — RFC 6960 §4.1."""
        from cryptography.hazmat.primitives import hashes
        from cryptography.x509 import ocsp

        builder = ocsp.OCSPRequestBuilder().add_certificate(
            self.cert, self.issuer, hashes.SHA256())
        from cryptography.hazmat.primitives.serialization import Encoding

        return builder.build().public_bytes(Encoding.DER)

    async def _default_fetch(self, url: str, der: bytes) -> bytes:
        from ..bridge import httpc

        resp = await httpc.request(
            "POST", url, body=der,
            headers={"Content-Type": "application/ocsp-request"},
            timeout=self.refresh_http_timeout_s,
        )
        if resp.status != 200:
            raise OcspError(f"responder returned HTTP {resp.status}")
        return resp.body

    # -- refresh -------------------------------------------------------

    async def refresh(self) -> str:
        """One fetch+validate+install cycle; returns the cert status.
        On failure the previous response stays served until ITS
        next_update passes (stale-while-refresh, like the reference's
        cache keeping the last good staple)."""
        try:
            return await self._refresh()
        except Exception:
            # single counting point: transport errors, bad responder
            # status, and validation failures all tally once here
            self.failures += 1
            raise

    async def _refresh(self) -> str:
        if self.responder_url is None:
            raise OcspError("no responder URL (cert has no AIA OCSP entry)")
        from cryptography.x509 import ocsp

        der = await self._fetch(self.responder_url, self.build_request())
        resp = ocsp.load_der_ocsp_response(der)
        if resp.response_status != ocsp.OCSPResponseStatus.SUCCESSFUL:
            raise OcspError(f"responder status {resp.response_status}")
        # OCSP rides plain HTTP: the response itself must prove (a) it
        # answers for OUR certificate and (b) the ISSUER signed it — a
        # MITM'd 'good' must not reach the health surface or the staple
        if resp.serial_number != self.cert.serial_number:
            raise OcspError(
                f"response is for serial {resp.serial_number:#x}, "
                f"not ours ({self.cert.serial_number:#x})")
        self._verify_signature(resp)
        status = resp.certificate_status
        now = time.time()
        nu = resp.next_update_utc
        this_update = resp.this_update_utc
        if this_update is not None and this_update.timestamp() > now + 300:
            raise OcspError("response from the future (clock skew > 5m)")
        if nu is not None and nu.timestamp() <= now:
            raise OcspError("responder served an already-expired response")
        self._response_der = der
        self._status = status.name.lower()   # good | revoked | unknown
        self._next_update = nu.timestamp() if nu is not None else None
        self._fetched_at = now
        self.refreshes += 1
        if self._status != "good":
            log.warning("ocsp: server certificate status is %r", self._status)
        return self._status

    def _verify_signature(self, resp) -> None:
        """Responder signature check against the issuer key (delegated
        responder certificates are out of scope — a response our CA did
        not sign directly is rejected, fail-closed)."""
        from cryptography.hazmat.primitives.asymmetric import ec, padding

        pub = self.issuer.public_key()
        try:
            if hasattr(pub, "curve"):
                pub.verify(resp.signature, resp.tbs_response_bytes,
                           ec.ECDSA(resp.signature_hash_algorithm))
            else:
                pub.verify(resp.signature, resp.tbs_response_bytes,
                           padding.PKCS1v15(),
                           resp.signature_hash_algorithm)
        except Exception as e:
            raise OcspError(
                f"responder signature not verifiable by the issuer: {e}")

    async def _loop(self) -> None:
        while True:
            try:
                await self.refresh()
            except Exception as e:
                log.warning("ocsp refresh failed: %s", e)
            await asyncio.sleep(self._next_sleep())

    # refresh margin before the staple expires; floor against a
    # responder issuing pathologically short windows
    EXPIRY_MARGIN_S = 60.0
    MIN_SLEEP_S = 30.0

    def _next_sleep(self) -> float:
        """Refresh AHEAD of the response's own expiry: a responder
        issuing 10-minute windows must not leave the listener unstapled
        for the rest of a 1-hour interval."""
        sleep = self.refresh_interval_s
        if self._next_update is not None:
            sleep = min(sleep,
                        self._next_update - time.time()
                        - self.EXPIRY_MARGIN_S)
        return max(self.MIN_SLEEP_S, sleep)

    def start(self) -> None:
        if self._task is None:
            sup = self.supervisor
            if sup is not None:
                self._task = sup.start_child("transport.ocsp", self._loop)
            else:
                self._task = asyncio.ensure_future(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # -- staple surface ------------------------------------------------

    def current(self) -> Optional[bytes]:
        """The DER response to staple, or None when absent/expired —
        the TLS accept path calls this per handshake (and, on None,
        proceeds unstapled: fail-open, clients fall back to their own
        revocation checking)."""
        if self._response_der is None:
            return None
        if self._next_update is not None and time.time() >= self._next_update:
            return None   # expired staple is worse than none
        return self._response_der

    def info(self) -> dict:
        return {
            "responder_url": self.responder_url,
            "status": self._status,
            "stapled": self.current() is not None,
            "fetched_at": self._fetched_at,
            "next_update": self._next_update,
            "refreshes": self.refreshes,
            "failures": self.failures,
        }
