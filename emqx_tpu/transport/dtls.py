"""Minimal DTLS 1.2 PSK transport for the UDP gateways (CoAP/LwM2M).

Behavioral reference: the reference's UDP gateways run over DTLS
listeners (``apps/emqx_gateway`` DTLS listener configs, esockd dtls
[U]; SURVEY.md §2.3 gateways) with PSK identities served by
``apps/emqx_psk`` [U].  Python's ``ssl`` module has no DTLS support, so
— the same craft as the hand-rolled Kafka/MySQL/Mongo/LDAP wire
clients — this implements the protocol directly:

* **RFC 6347** DTLS 1.2 record + handshake layer (single-fragment
  messages, cookie exchange via stateless ``HelloVerifyRequest``);
* **RFC 4279** plain-PSK key exchange (no certificates);
* **RFC 5288** ``TLS_PSK_WITH_AES_128_GCM_SHA256`` (0x00A8) record
  protection, AES-GCM from the ``cryptography`` package, PRF/Finished
  from stdlib ``hmac``/``hashlib``.

Deliberate scope cuts, recorded: no fragmentation/reassembly of
handshake messages (all flights fit one datagram on loopback/typical
MTU), no retransmission timers (callers run over loopback in tests;
lost-flight recovery just restarts the handshake), no renegotiation,
no anti-replay window.  These bound the implementation at ~"esockd
dtls for one cipher" — enough for gateway parity, honest about the
rest.

Two layers:

* :class:`DtlsConnection` — sans-IO state machine (client or server).
  Feed raw datagrams with :meth:`receive`, read decrypted application
  bytes from its return value, collect outgoing datagrams from
  :meth:`take_outgoing`; :meth:`send` protects application data.
* :class:`DtlsEndpoint` — asyncio glue: wraps a
  ``DatagramTransport``, demultiplexes peers by address, exposes the
  gateway-facing ``sendto``/callback surface so
  ``CoapGateway``/``Lwm2mGateway`` swap it in for the raw transport.
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import os
import struct
import time
from typing import Callable, Dict, List, Optional, Tuple

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

log = logging.getLogger(__name__)

__all__ = ["DtlsConnection", "DtlsEndpoint", "DtlsError", "PskStore"]

DTLS10 = b"\xfe\xff"
DTLS12 = b"\xfe\xfd"
SUITE_PSK_AES128_GCM_SHA256 = 0x00A8

# record content types
CT_CCS, CT_ALERT, CT_HANDSHAKE, CT_APPDATA = 20, 21, 22, 23
# handshake message types
HT_CLIENT_HELLO, HT_SERVER_HELLO, HT_HELLO_VERIFY = 1, 2, 3
HT_SERVER_HELLO_DONE, HT_CLIENT_KEY_EXCHANGE, HT_FINISHED = 14, 16, 20


class DtlsError(Exception):
    pass


class PskStore:
    """identity -> key lookup (the ``emqx_psk`` table analog)."""

    def __init__(self, entries: Optional[Dict[str, bytes]] = None,
                 hint: str = "") -> None:
        self.entries = dict(entries or {})
        self.hint = hint

    def lookup(self, identity: bytes) -> Optional[bytes]:
        return self.entries.get(identity.decode("utf-8", "replace"))


def _prf(secret: bytes, label: bytes, seed: bytes, n: int) -> bytes:
    """TLS 1.2 PRF (P_SHA256, RFC 5246 §5)."""
    seed = label + seed
    out, a = b"", seed
    while len(out) < n:
        a = hmac.new(secret, a, hashlib.sha256).digest()
        out += hmac.new(secret, a + seed, hashlib.sha256).digest()
    return out[:n]


def _psk_premaster(psk: bytes) -> bytes:
    z = b"\x00" * len(psk)
    return struct.pack("!H", len(psk)) + z + struct.pack("!H", len(psk)) + psk


def _hs_msg(msg_type: int, body: bytes, msg_seq: int) -> bytes:
    """One single-fragment DTLS handshake message (12-byte header)."""
    ln = struct.pack("!I", len(body))[1:]
    return (bytes([msg_type]) + ln + struct.pack("!H", msg_seq)
            + b"\x00\x00\x00" + ln + body)


class _RecordCipher:
    """AES-128-GCM record protection for one direction (RFC 5288)."""

    def __init__(self, key: bytes, salt: bytes) -> None:
        self.aead = AESGCM(key)
        self.salt = salt

    def seal(self, epoch_seq: bytes, ct_type: int, plain: bytes) -> bytes:
        explicit = epoch_seq                       # epoch(2)+seq(6)
        nonce = self.salt + explicit
        aad = epoch_seq + bytes([ct_type]) + DTLS12 \
            + struct.pack("!H", len(plain))
        return explicit + self.aead.encrypt(nonce, plain, aad)

    def open(self, epoch_seq: bytes, ct_type: int, payload: bytes) -> bytes:
        if len(payload) < 24:                      # 8 nonce + 16 tag
            raise DtlsError("record too short")
        explicit, ct = payload[:8], payload[8:]
        nonce = self.salt + explicit
        aad = epoch_seq + bytes([ct_type]) + DTLS12 \
            + struct.pack("!H", len(ct) - 16)
        return self.aead.decrypt(nonce, ct, aad)


class DtlsConnection:
    """Sans-IO DTLS 1.2 PSK connection (one peer)."""

    def __init__(self, role: str, *,
                 psk_store: Optional[PskStore] = None,
                 psk_identity: str = "", psk: bytes = b"",
                 cookie_secret: bytes = b"", peer: object = None) -> None:
        assert role in ("client", "server")
        self.role = role
        self.psk_store = psk_store
        self.psk_identity = psk_identity.encode()
        self.psk = psk
        self.cookie_secret = cookie_secret or os.urandom(16)
        self.peer = peer
        self.complete = False
        self.closed = False
        self._out: List[bytes] = []                # datagrams to send
        self._msg_seq = 0                          # my next handshake seq
        self._epoch = 0
        self._seq = 0                              # record seq (this epoch)
        self._transcript: List[bytes] = []         # hashed handshake msgs
        self._client_random = b""
        self._server_random = b""
        self._cookie = b""
        self._master = b""
        self._write: Optional[_RecordCipher] = None
        self._read: Optional[_RecordCipher] = None
        self._peer_epoch = 0
        self.last_seen = time.monotonic()
        if role == "client":
            self._client_random = os.urandom(32)
            self._send_client_hello()

    # -- outgoing ------------------------------------------------------

    def take_outgoing(self) -> List[bytes]:
        out, self._out = self._out, []
        return out

    def _record(self, ct_type: int, payload: bytes) -> bytes:
        hdr_seq = struct.pack("!HQ", self._epoch, self._seq)[0:2] \
            + struct.pack("!Q", self._seq)[2:]
        self._seq += 1
        if self._epoch > 0 and ct_type != CT_CCS:
            payload = self._write.seal(hdr_seq, ct_type, payload)
        return bytes([ct_type]) + DTLS12 + hdr_seq \
            + struct.pack("!H", len(payload)) + payload

    def _ship(self, *records: bytes) -> None:
        self._out.append(b"".join(records))

    def _hs(self, msg_type: int, body: bytes, hash_it: bool = True) -> bytes:
        msg = _hs_msg(msg_type, body, self._msg_seq)
        self._msg_seq += 1
        if hash_it:
            self._transcript.append(msg)
        return self._record(CT_HANDSHAKE, msg)

    # -- handshake flights --------------------------------------------

    def _send_client_hello(self) -> None:
        body = (DTLS12 + self._client_random + b"\x00"
                + bytes([len(self._cookie)]) + self._cookie
                + struct.pack("!HH", 2, SUITE_PSK_AES128_GCM_SHA256)
                + b"\x01\x00")
        # the pre-cookie ClientHello and HelloVerifyRequest are excluded
        # from the Finished hash (RFC 6347 §4.2.1)
        self._ship(self._hs(HT_CLIENT_HELLO, body,
                            hash_it=bool(self._cookie)))

    def _handshake_hash(self) -> bytes:
        return hashlib.sha256(b"".join(self._transcript)).digest()

    def _derive(self, client: bool) -> None:
        premaster = _psk_premaster(self.psk)
        self._master = _prf(premaster, b"master secret",
                            self._client_random + self._server_random, 48)
        kb = _prf(self._master, b"key expansion",
                  self._server_random + self._client_random, 40)
        ckey, skey, csalt, ssalt = kb[0:16], kb[16:32], kb[32:36], kb[36:40]
        if client:
            self._write = _RecordCipher(ckey, csalt)
            self._read = _RecordCipher(skey, ssalt)
        else:
            self._write = _RecordCipher(skey, ssalt)
            self._read = _RecordCipher(ckey, csalt)

    def _finished_body(self, label: bytes) -> bytes:
        return _prf(self._master, label, self._handshake_hash(), 12)

    def _switch_epoch(self) -> List[bytes]:
        ccs = self._record(CT_CCS, b"\x01")
        self._epoch += 1
        self._seq = 0
        return [ccs]

    # -- incoming ------------------------------------------------------

    def receive(self, datagram: bytes) -> List[bytes]:
        """Feed one UDP datagram; returns decrypted application chunks.
        Outgoing handshake datagrams accumulate in :meth:`take_outgoing`."""
        self.last_seen = time.monotonic()
        plains: List[bytes] = []
        off = 0
        while off + 13 <= len(datagram):
            ct_type = datagram[off]
            epoch = struct.unpack("!H", datagram[off + 3:off + 5])[0]
            epoch_seq = datagram[off + 3:off + 11]
            ln = struct.unpack("!H", datagram[off + 11:off + 13])[0]
            payload = datagram[off + 13:off + 13 + ln]
            if len(payload) < ln:
                break                              # truncated datagram
            off += 13 + ln
            try:
                if epoch > 0:
                    if self._read is None:
                        continue                   # early app data: drop
                    payload = self._read.open(epoch_seq, ct_type, payload)
                self._handle_record(ct_type, payload, plains)
            except DtlsError as e:
                log.debug("dtls(%s): dropping record: %s", self.role, e)
            except Exception:
                log.debug("dtls(%s): record error", self.role,
                          exc_info=True)
        return plains

    def _handle_record(self, ct_type: int, payload: bytes,
                       plains: List[bytes]) -> None:
        if ct_type == CT_APPDATA:
            if self.complete:
                plains.append(payload)
            return
        if ct_type == CT_CCS:
            self._peer_epoch += 1
            return
        if ct_type == CT_ALERT:
            self.closed = True
            return
        if ct_type != CT_HANDSHAKE:
            raise DtlsError(f"unexpected content type {ct_type}")
        off = 0
        while off + 12 <= len(payload):
            msg_type = payload[off]
            ln = struct.unpack("!I", b"\x00" + payload[off + 1:off + 4])[0]
            msg = payload[off:off + 12 + ln]
            body = payload[off + 12:off + 12 + ln]
            if len(body) < ln:
                raise DtlsError("truncated handshake message")
            off += 12 + ln
            self._handle_handshake(msg_type, body, msg)

    # -- handshake state machine --------------------------------------

    def _handle_handshake(self, msg_type: int, body: bytes,
                          raw: bytes) -> None:
        if self.role == "server":
            self._server_handle(msg_type, body, raw)
        else:
            self._client_handle(msg_type, body, raw)

    def _expect_cookie(self, addr_tag: bytes) -> bytes:
        return hmac.new(self.cookie_secret,
                        addr_tag + self._client_random,
                        hashlib.sha256).digest()[:16]

    def _server_handle(self, msg_type: int, body: bytes, raw: bytes) -> None:
        if msg_type == HT_CLIENT_HELLO:
            if self.complete:
                return                             # retransmit: ignore
            off = 2
            self._client_random = body[off:off + 32]
            off += 32
            sid_len = body[off]
            off += 1 + sid_len
            cookie_len = body[off]
            cookie = body[off + 1:off + 1 + cookie_len]
            off += 1 + cookie_len
            n_suites = struct.unpack("!H", body[off:off + 2])[0] // 2
            suites = struct.unpack(
                f"!{n_suites}H", body[off + 2:off + 2 + n_suites * 2])
            addr_tag = repr(self.peer).encode()
            want = self._expect_cookie(addr_tag)
            if not cookie:
                # stateless round 1: hand out the cookie, keep nothing
                self._transcript.clear()
                self._ship(self._hs(HT_HELLO_VERIFY,
                                    DTLS10 + bytes([len(want)]) + want,
                                    hash_it=False))
                return
            if not hmac.compare_digest(cookie, want):
                raise DtlsError("bad cookie")
            if SUITE_PSK_AES128_GCM_SHA256 not in suites:
                raise DtlsError("no shared cipher suite")
            self._transcript.clear()
            self._transcript.append(raw)           # cookie'd CH is hashed
            self._server_random = os.urandom(32)
            sh = (DTLS12 + self._server_random + b"\x00"
                  + struct.pack("!H", SUITE_PSK_AES128_GCM_SHA256)
                  + b"\x00")
            self._ship(self._hs(HT_SERVER_HELLO, sh),
                       self._hs(HT_SERVER_HELLO_DONE, b""))
            return
        if msg_type == HT_CLIENT_KEY_EXCHANGE:
            self._transcript.append(raw)
            id_len = struct.unpack("!H", body[:2])[0]
            identity = body[2:2 + id_len]
            key = self.psk_store.lookup(identity) if self.psk_store else None
            if key is None:
                raise DtlsError(f"unknown psk identity {identity!r}")
            self.psk = key
            self.psk_identity = identity
            self._derive(client=False)
            return
        if msg_type == HT_FINISHED:
            want = self._finished_body(b"client finished")
            if not hmac.compare_digest(body, want):
                raise DtlsError("bad client Finished")
            self._transcript.append(raw)
            fin = self._finished_body(b"server finished")
            ccs = self._switch_epoch()
            self._ship(*ccs, self._hs(HT_FINISHED, fin, hash_it=False))
            self.complete = True
            return
        raise DtlsError(f"unexpected server-side handshake {msg_type}")

    def _client_handle(self, msg_type: int, body: bytes, raw: bytes) -> None:
        if msg_type == HT_HELLO_VERIFY:
            cookie_len = body[2]
            self._cookie = body[3:3 + cookie_len]
            self._transcript.clear()
            self._send_client_hello()
            return
        if msg_type == HT_SERVER_HELLO:
            self._transcript.append(raw)
            self._server_random = body[2:34]
            off = 34
            sid_len = body[off]
            off += 1 + sid_len
            suite = struct.unpack("!H", body[off:off + 2])[0]
            if suite != SUITE_PSK_AES128_GCM_SHA256:
                raise DtlsError(f"server chose unsupported suite {suite:#x}")
            return
        if msg_type == HT_SERVER_HELLO_DONE:
            self._transcript.append(raw)
            cke = struct.pack("!H", len(self.psk_identity)) \
                + self.psk_identity
            cke_rec = self._hs(HT_CLIENT_KEY_EXCHANGE, cke)
            self._derive(client=True)
            fin = self._finished_body(b"client finished")
            ccs = self._switch_epoch()
            self._ship(cke_rec, *ccs,
                       self._hs(HT_FINISHED, fin))
            return
        if msg_type == HT_FINISHED:
            want = self._finished_body(b"server finished")
            if not hmac.compare_digest(body, want):
                raise DtlsError("bad server Finished")
            self.complete = True
            return
        raise DtlsError(f"unexpected client-side handshake {msg_type}")

    # -- application data ---------------------------------------------

    def send(self, plaintext: bytes) -> None:
        if not self.complete:
            raise DtlsError("handshake incomplete")
        self._ship(self._record(CT_APPDATA, plaintext))

    def close(self) -> None:
        if not self.closed and self._epoch > 0:
            # close_notify alert (2-byte: warning, close_notify)
            self._ship(self._record(CT_ALERT, b"\x01\x00"))
        self.closed = True


class DtlsEndpoint:
    """Server-side DTLS demultiplexer over one UDP transport.

    Drop-in for the raw transport in the UDP gateways: the gateway
    calls :meth:`sendto` with plaintext; incoming datagrams route
    through per-address connections and surface as plaintext via
    ``on_plain(data, addr)``.  Idle handshakes and closed peers are
    swept by the owning gateway's usual idle logic (connections expose
    ``last_seen``)."""

    def __init__(self, transport, on_plain: Callable, psk_store: PskStore,
                 idle_timeout: float = 120.0) -> None:
        self.transport = transport
        self.on_plain = on_plain
        self.psk_store = psk_store
        self.idle_timeout = idle_timeout
        self.cookie_secret = os.urandom(16)
        self.sessions: Dict[object, DtlsConnection] = {}
        self.handshakes = 0

    # gateway-facing transport surface
    def sendto(self, data: bytes, addr) -> None:
        conn = self.sessions.get(addr)
        if conn is None or not conn.complete:
            log.debug("dtls endpoint: no session for %s; dropping send",
                      addr)
            return
        conn.send(data)
        self._flush(conn, addr)

    def get_extra_info(self, name, default=None):
        return self.transport.get_extra_info(name, default)

    def close(self) -> None:
        for addr, conn in list(self.sessions.items()):
            conn.close()
            self._flush(conn, addr)
        self.sessions.clear()
        self.transport.close()

    # datagram ingress (wired by the gateway's DatagramProtocol)
    def datagram_received(self, data: bytes, addr) -> None:
        conn = self.sessions.get(addr)
        fresh = conn is None
        if fresh:
            # not retained yet: the pre-cookie round must stay stateless
            # (RFC 6347 §4.2.1) or address-spoofed first flights pin
            # memory per source address
            conn = DtlsConnection(
                "server", psk_store=self.psk_store,
                cookie_secret=self.cookie_secret, peer=addr)
        was_complete = conn.complete
        try:
            plains = conn.receive(data)
        except Exception:
            log.debug("dtls endpoint: dropping peer %s", addr,
                      exc_info=True)
            self.sessions.pop(addr, None)
            return
        self._flush(conn, addr)
        if fresh and conn._server_random:
            # a valid cookie came back: the peer's address is verified,
            # NOW the connection earns a table slot
            self.sessions[addr] = conn
        if conn.complete and not was_complete:
            self.handshakes += 1
        if conn.closed:
            self.sessions.pop(addr, None)
        for p in plains:
            self.on_plain(p, addr)

    def sweep(self, now: Optional[float] = None) -> int:
        now = now if now is not None else time.monotonic()
        stale = [a for a, c in self.sessions.items()
                 if now - c.last_seen > self.idle_timeout]
        for a in stale:
            self.sessions.pop(a, None)
        return len(stale)

    def _flush(self, conn: DtlsConnection, addr) -> None:
        for dg in conn.take_outgoing():
            self.transport.sendto(dg, addr)
