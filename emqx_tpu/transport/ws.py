"""Minimal RFC 6455 WebSocket server framing for MQTT-over-WS.

Behavioral reference: ``emqx_ws_connection.erl`` over cowboy [U]
(SURVEY.md §2.1).  The reference delegates WS framing to cowboy; we
implement the server side of RFC 6455 directly over asyncio streams so the
transport stack stays self-contained: HTTP/1.1 Upgrade handshake with the
``mqtt`` subprotocol, masked client frames, fragmentation, ping/pong,
close.  Each binary frame carries a chunk of the MQTT byte stream (packets
may span frames; the MQTT parser reassembles).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
from typing import Optional, Tuple

log = logging.getLogger(__name__)

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BIN, OP_CLOSE, OP_PING, OP_PONG = 0x0, 0x1, 0x2, 0x8, 0x9, 0xA


class WsError(Exception):
    pass


def accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _GUID).encode()).digest()
    ).decode()


async def server_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    path: str = "/mqtt",
    max_header: int = 16384,
) -> dict:
    """Read the HTTP Upgrade request, reply 101.  Returns parsed headers."""
    raw = await reader.readuntil(b"\r\n\r\n")
    if len(raw) > max_header:
        raise WsError("oversized handshake")
    lines = raw.decode("latin-1").split("\r\n")
    try:
        method, req_path, _ = lines[0].split(" ", 2)
    except ValueError:
        raise WsError(f"bad request line {lines[0]!r}")
    headers = {}
    for ln in lines[1:]:
        if not ln:
            continue
        k, _, v = ln.partition(":")
        headers[k.strip().lower()] = v.strip()
    if method != "GET" or (path and req_path.split("?")[0] != path):
        _reject(writer, 404, "not found")
        raise WsError(f"bad path {req_path!r}")
    if (
        "websocket" not in headers.get("upgrade", "").lower()
        or "sec-websocket-key" not in headers
    ):
        _reject(writer, 400, "not a websocket upgrade")
        raise WsError("not a websocket upgrade")
    protos = [
        p.strip()
        for p in headers.get("sec-websocket-protocol", "").split(",")
        if p.strip()
    ]
    resp = [
        "HTTP/1.1 101 Switching Protocols",
        "Upgrade: websocket",
        "Connection: Upgrade",
        f"Sec-WebSocket-Accept: {accept_key(headers['sec-websocket-key'])}",
    ]
    # MQTT-over-WS requires the 'mqtt' subprotocol (MQTT spec §6)
    if "mqtt" in protos:
        resp.append("Sec-WebSocket-Protocol: mqtt")
    writer.write(("\r\n".join(resp) + "\r\n\r\n").encode())
    await writer.drain()
    return headers


def _reject(writer: asyncio.StreamWriter, code: int, msg: str) -> None:
    body = msg.encode()
    writer.write(
        (
            f"HTTP/1.1 {code} {msg}\r\nContent-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode()
        + body
    )


def encode_frame(opcode: int, payload: bytes, fin: bool = True) -> bytes:
    head = bytearray([(0x80 if fin else 0) | opcode])
    n = len(payload)
    if n < 126:
        head.append(n)
    elif n < 1 << 16:
        head.append(126)
        head += n.to_bytes(2, "big")
    else:
        head.append(127)
        head += n.to_bytes(8, "big")
    return bytes(head) + payload


async def read_frame(
    reader: asyncio.StreamReader, max_size: int = 1 << 24
) -> Tuple[int, bool, bytes]:
    """Returns (opcode, fin, unmasked payload)."""
    b = await reader.readexactly(2)
    fin = bool(b[0] & 0x80)
    if b[0] & 0x70:
        raise WsError("RSV bits set without extension")
    opcode = b[0] & 0x0F
    masked = bool(b[1] & 0x80)
    n = b[1] & 0x7F
    if n == 126:
        n = int.from_bytes(await reader.readexactly(2), "big")
    elif n == 127:
        n = int.from_bytes(await reader.readexactly(8), "big")
    if n > max_size:
        raise WsError(f"frame too large ({n} bytes)")
    if not masked:
        raise WsError("client frames must be masked")  # RFC 6455 §5.1
    mask = await reader.readexactly(4)
    data = bytearray(await reader.readexactly(n))
    for i in range(n):
        data[i] ^= mask[i & 3]
    return opcode, fin, bytes(data)


class WsStream:
    """Byte-stream adapter over WS binary frames, mirroring the small
    read/write surface :class:`~emqx_tpu.transport.connection.Connection`
    needs, so MQTT code is transport-agnostic."""

    def __init__(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._r = reader
        self._w = writer
        self._buf = bytearray()
        self._frag: Optional[int] = None  # opcode of in-progress fragment
        self.closed = False

    async def read(self, n: int) -> bytes:
        """Returns up to n bytes of MQTT stream, b'' on close.  Protocol
        violations close with status 1002 instead of raising (a client
        error is a close, not a server crash)."""
        while not self._buf and not self.closed:
            try:
                op, fin, payload = await read_frame(self._r)
            except (asyncio.IncompleteReadError, WsError, ConnectionError):
                self.closed = True
                break
            try:
                if self._consume_frame(op, fin, payload):
                    break
            except WsError:
                try:
                    self._w.write(
                        encode_frame(OP_CLOSE, (1002).to_bytes(2, "big"))
                    )
                except ConnectionError:
                    pass  # peer is gone: nothing to wave goodbye to
                self.closed = True
                break
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def _consume_frame(self, op: int, fin: bool, payload: bytes) -> bool:
        """Handle one frame; returns True when the stream is done.
        Raises WsError on client protocol violations."""
        if op == OP_PING:
            self._w.write(encode_frame(OP_PONG, payload))
            return False
        if op == OP_PONG:
            return False
        if op == OP_CLOSE:
            try:
                self._w.write(encode_frame(OP_CLOSE, payload[:2]))
            except ConnectionError:
                pass  # peer is gone: the close echo has no recipient
            self.closed = True
            return True
        if op in (OP_BIN, OP_TEXT):
            if self._frag is not None:
                raise WsError("new data frame inside fragment")
            if not fin:
                self._frag = op
        elif op == OP_CONT:
            if self._frag is None:
                raise WsError("continuation without fragment")
            if fin:
                self._frag = None
        else:
            raise WsError(f"unknown opcode {op}")
        self._buf += payload
        return False

    def write(self, data: bytes) -> None:
        self._w.write(encode_frame(OP_BIN, data))

    async def drain(self) -> None:
        await self._w.drain()

    def close(self) -> None:
        if not self.closed:
            try:
                self._w.write(encode_frame(OP_CLOSE, (1000).to_bytes(2, "big")))
            except ConnectionError:
                pass  # peer is gone: skip the goodbye, close below
        try:
            self._w.close()
        except Exception:
            log.debug("ws transport close failed", exc_info=True)

    async def wait_closed(self) -> None:
        try:
            await self._w.wait_closed()
        except Exception:
            log.debug("ws wait_closed failed", exc_info=True)

    def peername(self):
        return self._w.get_extra_info("peername")
