"""Hashed timer wheel: O(1) coarse timers for the connection plane.

Behavioral reference: Erlang/OTP's timer wheel (and esockd's use of it
for per-connection keepalive) [U].  The per-connection timer model the
PR-5 datapaths used — one ``loop.call_later`` per connection per tick —
costs one timer-heap entry, one heap pop and one scheduled callback *per
connection per second*: a 10k-connection node burns 10k heap operations
and 10k loop callbacks every second just deciding that nobody timed out.

The wheel replaces that with coarse hashed buckets:

* :meth:`call_later` / :meth:`call_repeat` insert into the bucket for
  ``ceil((now + delay) / tick)`` — an O(1) dict append, no heap;
* ``cancel()`` is O(1) — the entry is flagged dead and skipped (and
  dropped from its bucket) at expiry;
* the wheel keeps **exactly one** ``loop.call_later`` outstanding, ever:
  each advance runs every entry in the due buckets — a 10k-connection
  keepalive storm costs ONE scheduled callback whose body walks the
  bucket, not 10k separately scheduled callbacks — then re-arms for the
  next non-empty tick;
* when the last entry dies the wheel goes idle (no scheduled callback at
  all) and re-arms lazily on the next insert.

Timers fire **late, never early**: a delay rounds *up* to the next
bucket boundary, so observed latency is ``delay .. delay + tick``.
That is exactly right for keepalive (spec allows 1.5×) and retry
(interval >> tick) checks, and wrong for anything needing sub-tick
precision — which stays on ``loop.call_later``.

One wheel per event loop: the wheel is not thread-safe by design (it
lives and dies with its loop); each connection shard owns its own.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)

__all__ = ["TimerWheel", "WheelTimer"]


class WheelTimer:
    """Handle for one scheduled callback; ``cancel()`` is O(1) — the
    entry is flagged dead (and released from the live gauge) now, and
    physically dropped when its bucket expires."""

    __slots__ = ("fn", "interval", "slot", "cancelled", "wheel")

    def __init__(self, fn: Callable[[], Any], interval: Optional[float],
                 slot: int, wheel: "Optional[TimerWheel]" = None) -> None:
        self.fn = fn
        self.interval = interval   # None = one-shot; seconds = periodic
        self.slot = slot
        self.cancelled = False
        self.wheel = wheel

    def cancel(self) -> None:
        if self.cancelled:
            return
        self.cancelled = True
        self.fn = None  # drop the ref cycle (conn → timer → bound method)
        w = self.wheel
        if w is not None:
            w._live -= 1


class TimerWheel:
    """Coarse hashed buckets + one outstanding loop timer (see module
    docstring).  ``clock``/``schedule`` are injectable for tests."""

    def __init__(
        self,
        tick_s: float = 1.0,
        clock: Optional[Callable[[], float]] = None,
        metrics: Any = None,
    ) -> None:
        if tick_s <= 0:
            raise ValueError("tick_s must be positive")
        self.tick_s = tick_s
        self.metrics = metrics
        self._clock = clock if clock is not None else time.monotonic
        self._buckets: Dict[int, List[WheelTimer]] = {}
        self._live = 0          # non-cancelled entries (gauge)
        self._handle = None     # the ONE outstanding loop.call_later
        self._armed_slot: Optional[int] = None
        self._closed = False
        self.ticks = 0          # advances run (test/ops visibility)

    # ------------------------------------------------------------------

    def __len__(self) -> int:
        return self._live

    def _slot_for(self, delay: float) -> int:
        # ceil to the next bucket boundary: never fire early (landing
        # exactly on a boundary fires at that boundary's advance)
        now = self._clock()
        x = (now + max(delay, 0.0)) / self.tick_s
        slot = int(x)
        if slot < x:
            slot += 1
        cur = int(now / self.tick_s)
        return slot if slot > cur else cur + 1

    def call_later(self, delay: float, fn: Callable[[], Any]) -> WheelTimer:
        """One-shot timer after >= ``delay`` seconds (bucket-rounded)."""
        return self._insert(WheelTimer(fn, None, self._slot_for(delay),
                                       self))

    def call_repeat(self, interval: float,
                    fn: Callable[[], Any]) -> WheelTimer:
        """Periodic timer every ~``interval`` seconds (bucket-rounded,
        re-inserted after each firing, so a slow callback cannot pile
        up overlapping runs)."""
        if interval <= 0:
            raise ValueError("interval must be positive")
        return self._insert(WheelTimer(fn, interval,
                                       self._slot_for(interval), self))

    def sleep(self, delay: float) -> "asyncio.Future":
        """Awaitable one-shot: the wheel-backed replacement for periodic
        ``asyncio.sleep`` loops (gateway sweepers) — the sleeper rides a
        bucket instead of the loop's timer heap, so N sleepers cost one
        scheduled callback per tick."""
        fut = asyncio.get_running_loop().create_future()

        def _wake() -> None:
            if not fut.done():
                fut.set_result(None)

        timer = self.call_later(delay, _wake)
        # a cancelled await (task teardown) must not leave a dead entry
        # firing into a closed context
        fut.add_done_callback(
            lambda f: timer.cancel() if f.cancelled() else None)
        return fut

    def _insert(self, timer: WheelTimer) -> WheelTimer:
        if self._closed:
            timer.cancelled = True
            return timer
        bucket = self._buckets.get(timer.slot)
        if bucket is None:
            bucket = self._buckets[timer.slot] = []
        bucket.append(timer)
        self._live += 1
        self._arm()
        return timer

    # ------------------------------------------------------------------

    def _arm(self) -> None:
        """(Re)schedule the single outstanding loop timer for the
        earliest non-empty bucket."""
        if self._closed or not self._buckets:
            return
        nxt = min(self._buckets)
        if self._handle is not None:
            if self._armed_slot is not None and self._armed_slot <= nxt:
                return  # already armed at or before the earliest bucket
            self._handle.cancel()
        delay = max(nxt * self.tick_s - self._clock(), 0.0)
        try:
            loop = asyncio.get_event_loop()
        except RuntimeError:
            # no loop in this thread (pure-logic use with an injected
            # clock): stay unarmed — the next insert from loop context
            # re-arms
            self._handle = None
            self._armed_slot = None
            return
        self._armed_slot = nxt
        self._handle = loop.call_later(delay, self._advance)

    def _advance(self) -> None:
        """Run every entry in every due bucket — the one callback per
        wheel tick, regardless of how many timers are due."""
        self._handle = None
        self._armed_slot = None
        if self._closed:
            return
        self.ticks += 1
        cur = int(self._clock() / self.tick_s)
        due = [s for s in self._buckets if s <= cur]
        for slot in sorted(due):
            for timer in self._buckets.pop(slot):
                if timer.cancelled:
                    continue  # cancel() already released the gauge
                fn = timer.fn
                if timer.interval is None:
                    # one-shot consumed: mark cancelled so a late
                    # cancel() cannot double-release the gauge
                    timer.cancelled = True
                    timer.fn = None
                    self._live -= 1
                try:
                    fn()
                except Exception:
                    log.exception("timer wheel callback failed")
                if timer.interval is not None and not timer.cancelled:
                    timer.slot = self._slot_for(timer.interval)
                    self._buckets.setdefault(timer.slot,
                                             []).append(timer)
        if self.metrics is not None:
            self.metrics.set("broker.timer.wheel_conns", self._live)
        self._arm()

    def close(self) -> None:
        """Drop every timer and the outstanding loop callback."""
        self._closed = True
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        for bucket in self._buckets.values():
            for timer in bucket:
                timer.cancelled = True
        self._buckets.clear()
        self._live = 0

    def info(self) -> Dict[str, Any]:
        return {"tick_s": self.tick_s, "timers": self._live,
                "buckets": len(self._buckets), "ticks": self.ticks}
