"""Per-socket connection loop driving the IO-free Channel FSM.

Behavioral reference: ``emqx_connection.erl`` [U] (SURVEY.md §2.1, §3.2):
the socket-owner process — recv loop with activate-N-style bounded reads,
incremental frame parsing, rate limiting, keepalive/retry timers, and
serialized writes.  Here: one reader task + one writer task per socket; the
Channel stays synchronous and IO-free, this module owns all awaiting.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, List, Optional

from ..broker.channel import Channel
from ..broker.limiter import LimiterGroup
from ..mqtt import frame as F
from ..mqtt import packet as P

log = logging.getLogger(__name__)

__all__ = ["Connection", "ConnInfo", "TcpStream", "set_nodelay"]


def set_nodelay(sock) -> None:
    """TCP_NODELAY on an accepted/dialed socket (shared by the stream,
    protocol, and client paths)."""
    if sock is None:
        return
    try:
        import socket as _socket

        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    except OSError:
        pass  # best-effort opt: not a TCP socket, or already closed


@dataclass
class ConnInfo:
    peername: Any = None
    sockname: Any = None
    listener: str = "tcp:default"
    ws: bool = False
    tls: bool = False
    connected_at: float = field(default_factory=time.time)


class TcpStream:
    """Thin adapter over asyncio streams, same surface as
    :class:`~emqx_tpu.transport.ws.WsStream`."""

    def __init__(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._r = reader
        self._w = writer

    async def read(self, n: int) -> bytes:
        try:
            return await self._r.read(n)
        except ConnectionError:
            return b""

    def write(self, data: bytes) -> None:
        self._w.write(data)

    async def drain(self) -> None:
        await self._w.drain()

    def close(self) -> None:
        try:
            self._w.close()
        except Exception:
            log.debug("tcp transport close failed", exc_info=True)

    async def wait_closed(self) -> None:
        try:
            await self._w.wait_closed()
        except Exception:
            log.debug("tcp wait_closed failed", exc_info=True)

    def peername(self):
        return self._w.get_extra_info("peername")


class Connection:
    """Owns one client socket: reads bytes → Parser → Channel.handle_in,
    executes the returned actions, and flushes deliveries pushed by the
    broker.  ``recv_buf`` bounds each read (the activate-N analog: the
    connection never buffers more than one read's worth of unparsed
    input plus one partial packet).
    """

    TICK_S = 1.0

    def __init__(
        self,
        stream: Any,
        channel: Channel,
        conninfo: Optional[ConnInfo] = None,
        recv_buf: int = 65536,
        max_packet_size: int = F.MAX_REMAINING_LEN,
        limiter: Optional[LimiterGroup] = None,
        on_closed=None,
        coalesce: bool = False,
        wheel=None,
    ) -> None:
        self.stream = stream
        self.channel = channel
        self.conninfo = conninfo or ConnInfo()
        self.recv_buf = recv_buf
        # stream-path parity with the batched proto datapath: the same
        # opt-in enables the parser's ack-run + publish-run fast paths
        # (packed AckRun/PublishRun consumption below) — off, parsing
        # and handling stay the per-packet path, byte-identical
        self.coalesce = coalesce
        self.parser = F.Parser(max_packet_size=max_packet_size,
                               ack_runs=coalesce, publish_runs=coalesce)
        # hashed timer wheel (transport/timerwheel.py): when provided,
        # the keepalive/retry tick rides a shared bucket (one scheduled
        # callback per tick for every connection) instead of a
        # per-connection sleep loop task
        self.wheel = wheel
        self.limiter = limiter
        self.on_closed = on_closed
        # optional async advisory stage (exhook): awaited per packet before
        # handle_in; may mutate/tag the packet or return replacement actions
        self.intercept = None
        self._outq: asyncio.Queue = asyncio.Queue()
        self._closing = asyncio.Event()
        self._close_reason = "closed"
        self.bytes_in = 0
        self.bytes_out = 0
        self.pkts_in = 0
        self.pkts_out = 0

    # -- broker-facing -----------------------------------------------------

    def deliver(self, pubs: List[Any]) -> None:
        """Called (synchronously, on the loop) when routed messages land on
        this client's session."""
        self._run_actions(self.channel.handle_deliver(pubs))

    def kick(self, reason: str = "kicked") -> None:
        self._run_actions(self.channel.handle_takeover()
                          if reason == "takeover" else [("close", reason)])

    # -- lifecycle ---------------------------------------------------------

    async def run(self) -> None:
        """Serve until close; returns after the socket is torn down."""
        writer = asyncio.ensure_future(self._writer_loop())
        ticker = (self.wheel.call_repeat(self.TICK_S, self._tick_once)
                  if self.wheel is not None
                  else asyncio.ensure_future(self._tick_loop()))
        try:
            await self._reader_loop()
        except Exception:
            log.exception("connection crashed (%s)", self.conninfo.peername)
            self._close_reason = "internal error"
        finally:
            self._closing.set()
            await self._outq.put(None)  # unblock writer for final flush
            await writer
            ticker.cancel()
            self.channel.handle_close(self._close_reason)
            self.stream.close()
            await self.stream.wait_closed()
            if self.on_closed is not None:
                self.on_closed(self)

    async def _reader_loop(self) -> None:
        msg_bucket = byte_bucket = None
        if self.limiter is not None:
            msg_bucket, byte_bucket = self.limiter.conn_buckets(str(id(self)))
        while not self._closing.is_set():
            data = await self.stream.read(self.recv_buf)
            if not data:
                self._close_reason = "peer closed"
                return
            self.bytes_in += len(data)
            if byte_bucket is not None and not byte_bucket.unlimited:
                ok, wait = byte_bucket.consume(len(data))
                if not ok:
                    await asyncio.sleep(wait)  # flow control: pause reads
            try:
                pkts = self.parser.feed(data)
            except F.FrameError as e:
                self._frame_error(e)
                return
            for pkt in pkts:
                if type(pkt) is P.AckRun:
                    if self.channel.state != "connected":
                        for sub in pkt.expand():
                            self.pkts_in += 1
                            self._run_actions(self.channel.handle_in(sub))
                            if self._closing.is_set():
                                return
                        continue
                    # packed ack run: one batched session transition,
                    # reply burst rides the writer queue as raw bytes
                    self.pkts_in += len(pkt.pids)
                    reply, refill = self.channel.handle_ack_run(pkt)
                    if reply:
                        self._outq.put_nowait((reply, len(pkt.pids)))
                    if refill:
                        self._run_actions(
                            self.channel.handle_deliver(refill))
                    if self._closing.is_set():
                        return
                    continue
                if type(pkt) is P.PublishRun:
                    if self.channel.state != "connected" \
                            or self.intercept is not None:
                        # pre-CONNECT replay / advisory stage present:
                        # per-packet handling, byte-identical (the
                        # intercept must see each PUBLISH)
                        for sub in pkt.expand():
                            self.pkts_in += 1
                            if self.intercept is not None \
                                    and self.channel.state == "connected":
                                actions = await self.intercept(
                                    self.channel, sub)
                                if (self._closing.is_set() or
                                        self.channel.state
                                        == "disconnected"):
                                    return
                                if actions is not None:
                                    self.channel.last_rx = time.time()
                                    self._run_actions(actions)
                                    if self._closing.is_set():
                                        return
                                    continue
                            self._run_actions(self.channel.handle_in(sub))
                            if self._closing.is_set():
                                return
                        continue
                    reply, acts, rest = \
                        self.channel.handle_publish_run(pkt)
                    consumed = len(pkt.pkts) - len(rest)
                    if consumed:
                        self.pkts_in += consumed
                        if (
                            msg_bucket is not None
                            and not msg_bucket.unlimited
                        ):
                            ok, wait = msg_bucket.consume(float(consumed))
                            if not ok:
                                await asyncio.sleep(wait)
                    if reply:
                        self._outq.put_nowait((reply, consumed))
                    if acts:
                        self._run_actions(acts)
                    if self._closing.is_set():
                        return
                    for sub in rest:
                        self.pkts_in += 1
                        if (
                            msg_bucket is not None
                            and not msg_bucket.unlimited
                        ):
                            ok, wait = msg_bucket.consume(1.0)
                            if not ok:
                                await asyncio.sleep(wait)
                        self._run_actions(self.channel.handle_in(sub))
                        if self._closing.is_set():
                            return
                    continue
                self.pkts_in += 1
                if (
                    msg_bucket is not None
                    and not msg_bucket.unlimited
                    and pkt.type == P.PUBLISH
                ):
                    ok, wait = msg_bucket.consume(1.0)
                    if not ok:
                        await asyncio.sleep(wait)  # msg-rate flow control
                if self.intercept is not None and pkt.type in (
                    P.CONNECT, P.PUBLISH, P.SUBSCRIBE, P.UNSUBSCRIBE
                ):
                    actions = await self.intercept(self.channel, pkt)
                    # the await may span a takeover/kick: never hand the
                    # packet to a channel that died mid-round-trip
                    if (
                        self._closing.is_set()
                        or self.channel.state == "disconnected"
                    ):
                        return
                    if actions is not None:  # advisory deny replaces handling
                        # a denied packet still counts for keepalive
                        # (MQTT §3.1.2.10: any control packet received)
                        self.channel.last_rx = time.time()
                        self._run_actions(actions)
                        if self._closing.is_set():
                            return
                        continue
                self._run_actions(self.channel.handle_in(pkt))
                if self._closing.is_set():
                    return

    def _frame_error(self, e: F.FrameError) -> None:
        adm = self.channel.broker.admission
        if adm is not None:
            # admission feature seam: malformed-frame rate (stream-path
            # parity with proto_conn._frame_error)
            adm.note_malformed(self.channel.clientid,
                               self.conninfo.peername)
        # MQTT5 §4.13: respond DISCONNECT with the reason, then drop
        if self.channel.proto_ver == 5 and self.channel.state == "connected":
            self._send_pkt(P.Disconnect(reason_code=e.reason_code))
        self._close_reason = f"frame error: {e}"

    def _run_actions(self, actions: List[Any]) -> None:
        for act, arg in actions:
            if act == "send":
                self._send_pkt(arg)
            elif act == "close":
                self._close_reason = str(arg)
                self._closing.set()
                self._outq.put_nowait(None)
            elif act == "takeover":
                # arg is the displaced channel; route its goodbye through
                # the connection that owns it (emqx_cm takeover protocol)
                old_conn = getattr(arg, "conn", None)
                acts = arg.handle_takeover()
                if old_conn is not None and old_conn is not self:
                    old_conn._run_actions(acts)

    def _send_pkt(self, pkt: Any) -> None:
        self._outq.put_nowait(pkt)

    async def _writer_loop(self) -> None:
        """Single writer: serializes queue order, applies backpressure via
        drain() so one slow client never blocks the event loop.  Packets
        already queued coalesce into ONE stream write (ack bursts,
        retained replays, resume floods) — bytes are identical to
        per-packet writes, only the write boundaries merge."""
        while True:
            pkt = await self._outq.get()
            if pkt is None:
                if self._closing.is_set() and self._outq.empty():
                    # goodbye flushed: close the socket so a reader blocked
                    # in read() unblocks (server-initiated close)
                    try:
                        await self.stream.drain()
                    except ConnectionError:
                        pass  # peer already gone: close() below still runs
                    self.stream.close()
                    return
                continue
            try:
                # queue items are parsed packets OR (raw_bytes, npkts)
                # bursts from the ack-run path — both coalesce into one
                # stream write
                npkts = 0

                def _render(item):
                    nonlocal npkts
                    if type(item) is tuple:
                        npkts += item[1]
                        return item[0]
                    npkts += 1
                    return F.serialize(item, ver=self.channel.proto_ver)

                chunks = [_render(pkt)]
                while not self._outq.empty():
                    nxt = self._outq.get_nowait()
                    if nxt is None:
                        # re-park the close sentinel behind this flush;
                        # the goodbye packets were queued before it
                        self._outq.put_nowait(None)
                        break
                    chunks.append(_render(nxt))
                data = chunks[0] if len(chunks) == 1 else b"".join(chunks)
                self.stream.write(data)
                self.bytes_out += len(data)
                self.pkts_out += npkts
                if self._outq.empty():
                    await self.stream.drain()
            except ConnectionError:
                self._closing.set()
                return

    async def _tick_loop(self) -> None:
        while not self._closing.is_set():
            await asyncio.sleep(self.TICK_S)
            self._tick_once()

    def _tick_once(self) -> None:
        """One keepalive/retry pass — synchronous, so it runs either
        from the per-connection sleep loop or as a timer-wheel bucket
        entry (one scheduled callback per tick for ALL connections)."""
        if self._closing.is_set():
            return
        self._run_actions(self.channel.check_keepalive())
        self._run_actions(self.channel.retry_deliveries())
        if not self._closing.is_set():
            # resends queued to a live writer: commit the DUP
            # clones / age clocks; a closed connection leaves the
            # entries due for the session's next owner
            self.channel.retry_commit()

    def info(self) -> dict:
        ch = self.channel
        return {
            "clientid": ch.clientid,
            "peername": self.conninfo.peername,
            "listener": self.conninfo.listener,
            "proto_ver": ch.proto_ver,
            "connected_at": self.conninfo.connected_at,
            "keepalive": ch.keepalive,
            "recv_oct": self.bytes_in,
            "send_oct": self.bytes_out,
            "recv_pkt": self.pkts_in,
            "send_pkt": self.pkts_out,
        }
