"""Protocol-mode TCP connection: the low-overhead datapath.

Behavioral reference: ``emqx_connection.erl`` [U] — same duties as
:class:`~emqx_tpu.transport.connection.Connection` (SURVEY.md §2.1/§3.2:
recv loop, incremental parse, rate limiting, keepalive/retry timers,
serialized writes), rebuilt on ``asyncio.Protocol`` instead of streams.

Why this exists: the stream path costs ~6 event-loop callback hops per
message (reader-task wakeup, StreamReader buffering, out-queue put,
writer-task wakeup, drain) — measured as the dominant cost of BASELINE
config 1 on one core.  A Protocol collapses the whole per-packet path
into ONE synchronous call chain: ``data_received → Parser.feed →
Channel.handle_in → transport.write``.  No per-connection tasks at all;
timers ride ``loop.call_later``.

The async advisory stage (exhook / cluster takeover / TPU prefetch /
network authn) can't run synchronously — when a node installs
``intercept``, packets route through an ordered queue consumed by one
worker task, which is exactly the stream path's cost model.  Plain
nodes (no interceptors) stay on the zero-task fast path; the decision
is per-connection at accept time.

Backpressure: ``pause_writing`` buffers outgoing packets and pauses
reading (a slow consumer throttles its own socket, the activate-N
discipline); byte/message token buckets pause reading on overdraft and
resume via ``call_later``.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, List, Optional

from .. import faultinject as _fi
from ..broker.channel import Channel
from ..broker.limiter import LimiterGroup
from ..mqtt import frame as F
from ..mqtt import packet as P
from .connection import ConnInfo, set_nodelay

log = logging.getLogger(__name__)

__all__ = ["MqttProtocol"]


class MqttProtocol(asyncio.Protocol):
    TICK_S = 1.0
    # intercept-mode queue watermarks (packets): reading pauses past
    # HIGH and resumes once the worker drains below LOW
    QUEUE_HIGH_WATER = 256
    QUEUE_LOW_WATER = 64
    # ingest_parse stage histogram (observe/hist.py): the node's
    # factory points this at its plane's histogram (shard conns get
    # their shard's instance — each is written only by its own loop);
    # None keeps the parse path at zero recording calls
    _h_parse = None

    def __init__(
        self,
        channel: Channel,
        conninfo: Optional[ConnInfo] = None,
        max_packet_size: int = F.MAX_REMAINING_LEN,
        limiter: Optional[LimiterGroup] = None,
        on_closed=None,
        intercept=None,
        metrics=None,
        coalesce: bool = True,
        wheel=None,
    ) -> None:
        self.channel = channel
        self.conninfo = conninfo or ConnInfo()
        # ack-run + publish-run fast paths only on the zero-task
        # datapath: with an advisory stage the ordered queue handles
        # packets one at a time, so runs would just be re-expanded
        self.parser = F.Parser(max_packet_size=max_packet_size,
                               ack_runs=coalesce and intercept is None,
                               publish_runs=coalesce and intercept is None)
        # hashed timer wheel (transport/timerwheel.py): when the node
        # provides one, the per-connection keepalive/retry tick rides a
        # coarse bucket — one scheduled callback per wheel tick for ALL
        # connections — instead of one loop.call_later per connection
        # per second.  None keeps the PR-5 per-connection timer exactly.
        self.wheel = wheel
        self.limiter = limiter
        self.on_closed = on_closed
        self.intercept = intercept
        self.metrics = metrics
        # the batched-stack opt-in (rides broker.fanout.enable at the
        # node level): ack-burst batching, write coalescing and the
        # QoS1 wire-template cache.  Off → per-packet handling and one
        # write per packet, byte-for-byte the pre-batching datapath.
        self.coalesce = coalesce
        self.transport: Optional[asyncio.Transport] = None
        self.bytes_in = 0
        self.bytes_out = 0
        self.pkts_in = 0
        self.pkts_out = 0
        self._closed = False
        self._close_reason = "closed"
        self._paused_write = False
        self._pending_out: List[bytes] = []
        # write-coalescing buffer: while a batch is open (one TCP read's
        # worth of inbound packets, one worker iteration, one timer
        # tick), every outgoing packet lands here and flushes as ONE
        # transport write — PUBACK/PUBREC/PUBREL/PUBCOMP bursts,
        # retained replays and ack-triggered queue drains stop costing
        # one syscall per packet.  Packet bytes are identical; only the
        # write boundaries coalesce.
        self._batching = False
        self._wbuf: List[bytes] = []
        self._wbuf_pkts = 0
        self._tick_handle = None
        self._msg_bucket = None
        self._byte_bucket = None
        self._queue: Optional[asyncio.Queue] = None
        self._worker: Optional[asyncio.Task] = None
        self._paused_read_queue = False

    # -- asyncio.Protocol ----------------------------------------------

    def connection_made(self, transport) -> None:
        self.transport = transport
        set_nodelay(transport.get_extra_info("socket"))
        self.conninfo.peername = transport.get_extra_info("peername")
        self.conninfo.sockname = transport.get_extra_info("sockname")
        # the channel's auth/flapping context sees the real peer address
        self.channel.conninfo["peername"] = self.conninfo.peername
        if self.limiter is not None:
            self._msg_bucket, self._byte_bucket = \
                self.limiter.conn_buckets(str(id(self)))
        if self.intercept is not None:
            # advisory stage present: packets take the ordered-queue
            # path so async round trips can't reorder handling
            self._queue = asyncio.Queue()
            self._worker = asyncio.ensure_future(self._worker_loop())
        if self.wheel is not None:
            # wheel mode: the storm problem the jitter below works
            # around does not exist — all due connections run inside
            # ONE bucket callback per tick, so alignment is free
            self._tick_handle = self.wheel.call_later(
                self.TICK_S, self._tick)
        else:
            # jitter the first tick: connections accepted in one storm
            # would otherwise fire thousands of keepalive timers in the
            # same millisecond every second — a recurring latency spike
            self._tick_handle = asyncio.get_running_loop().call_later(
                self.TICK_S * (0.5 + (id(self) % 1024) / 1024.0),
                self._tick)

    def data_received(self, data: bytes) -> None:
        self.bytes_in += len(data)
        if self._byte_bucket is not None and not self._byte_bucket.unlimited:
            ok, wait = self._byte_bucket.consume(len(data))
            if not ok:
                self._pause_read_for(wait)
        h_parse = self._h_parse
        t0 = time.perf_counter_ns() if h_parse is not None else 0
        try:
            pkts = self.parser.feed(data)
        except F.FrameError as e:
            self._frame_error(e)
            return
        if h_parse is not None:
            # one record per transport read: wire bytes → packet objects
            h_parse.record(time.perf_counter_ns() - t0)
        if self._queue is not None:
            for pkt in pkts:
                self._queue.put_nowait(pkt)
            # backpressure the SOCKET, not just the worker: while the
            # async advisory stage is slow, unread bytes must park in
            # the kernel buffer (and the sender's window), not in an
            # unbounded parsed-packet queue — the stream path had this
            # implicitly by awaiting each packet's handling
            if self._queue.qsize() >= self.QUEUE_HIGH_WATER \
                    and not self._paused_read_queue:
                self._paused_read_queue = True
                try:
                    self.transport.pause_reading()
                except RuntimeError:
                    self._paused_read_queue = False
            return
        if not self.coalesce:
            for pkt in pkts:
                self.pkts_in += 1
                if (
                    self._msg_bucket is not None
                    and not self._msg_bucket.unlimited
                    and pkt.type == P.PUBLISH
                ):
                    ok, wait = self._msg_bucket.consume(1.0)
                    if not ok:
                        self._pause_read_for(wait)
                self._run_actions(self.channel.handle_in(pkt))
                if self._closed:
                    return
            return
        channel = self.channel
        self._batching = True
        try:
            i = 0
            n = len(pkts)
            while i < n:
                pkt = pkts[i]
                if type(pkt) is P.AckRun:
                    if channel.state != "connected":
                        # pre-CONNECT acks are a protocol error: replay
                        # per-packet so the close reason matches the
                        # slow path exactly
                        for sub in pkt.expand():
                            self.pkts_in += 1
                            self._run_actions(channel.handle_in(sub))
                            if self._closed:
                                return
                        i += 1
                        continue
                    # packed ack run off the parser fast path: ONE
                    # batched session transition for the whole burst,
                    # one reply burst, one refill cycle
                    self.pkts_in += len(pkt.pids)
                    if self.metrics is not None:
                        self.metrics.inc("broker.ack.run_parsed")
                    reply, refill = channel.handle_ack_run(pkt)
                    if reply:
                        self._send_raw(reply, len(pkt.pids))
                    if refill:
                        self.deliver(refill)
                    i += 1
                    if self._closed:
                        return
                    continue
                if type(pkt) is P.PublishRun:
                    if channel.state != "connected":
                        # pre-CONNECT publishes are a protocol error:
                        # replay per-packet so the close reason matches
                        # the slow path exactly
                        for sub in pkt.expand():
                            self.pkts_in += 1
                            self._run_actions(channel.handle_in(sub))
                            if self._closed:
                                return
                        i += 1
                        continue
                    # contiguous same-client QoS1/2 PUBLISH run: ONE
                    # amortized authz/alias pass, one PUBACK/PUBREC
                    # burst through the open write batch.  `rest` is
                    # whatever the fast path could not guarantee
                    # (pipeline refusing) — replayed per-packet,
                    # byte-identical to the slow path.
                    reply, acts, rest = channel.handle_publish_run(pkt)
                    consumed = len(pkt.pkts) - len(rest)
                    if consumed:
                        self.pkts_in += consumed
                        if self._msg_bucket is not None \
                                and not self._msg_bucket.unlimited:
                            ok, wait = self._msg_bucket.consume(
                                float(consumed))
                            if not ok:
                                self._pause_read_for(wait)
                        if self.metrics is not None:
                            self.metrics.inc("broker.ingest.publish_runs")
                    if reply:
                        self._send_raw(reply, consumed)
                    if acts:
                        self._run_actions(acts)
                    if self._closed:
                        return
                    for sub in rest:
                        self.pkts_in += 1
                        if (
                            self._msg_bucket is not None
                            and not self._msg_bucket.unlimited
                        ):
                            ok, wait = self._msg_bucket.consume(1.0)
                            if not ok:
                                self._pause_read_for(wait)
                        self._run_actions(channel.handle_in(sub))
                        if self._closed:
                            return
                    i += 1
                    continue
                if (
                    pkt.type == P.PUBACK
                    and channel.state == "connected"
                    and i + 1 < n
                    and pkts[i + 1].type == P.PUBACK
                ):
                    # PUBACK burst (a windowed consumer acks a whole
                    # TCP read in one write): ack them all, refill the
                    # window ONCE, send the refills through the bulk
                    # wire path.  (With the ack-run parser these arrive
                    # packed above; this branch covers coalesce mode
                    # with an advisory stage, where runs are disabled.)
                    j = i + 2
                    while j < n and pkts[j].type == P.PUBACK:
                        j += 1
                    self.pkts_in += j - i
                    refill = channel.handle_puback_batch(pkts[i:j])
                    if refill:
                        self.deliver(refill)
                    i = j
                    if self._closed:
                        return
                    continue
                self.pkts_in += 1
                if (
                    self._msg_bucket is not None
                    and not self._msg_bucket.unlimited
                    and pkt.type == P.PUBLISH
                ):
                    ok, wait = self._msg_bucket.consume(1.0)
                    if not ok:
                        self._pause_read_for(wait)
                self._run_actions(channel.handle_in(pkt))
                if self._closed:
                    return
                i += 1
        finally:
            self._flush_writes()

    def connection_lost(self, exc) -> None:
        if self._tick_handle is not None:
            self._tick_handle.cancel()
        if self._worker is not None:
            self._worker.cancel()
        if not self._closed:
            self._closed = True
            self._close_reason = "peer closed"
        self.channel.handle_close(self._close_reason)
        if self.on_closed is not None:
            self.on_closed(self)
        if self.limiter is not None:
            self.limiter.drop_conn(str(id(self)))

    def pause_writing(self) -> None:
        self._paused_write = True
        # a consumer that can't drain its socket must not keep feeding
        # the broker either
        if self.transport is not None:
            try:
                self.transport.pause_reading()
            except RuntimeError:
                pass  # transport already closing: nothing to pause

    def resume_writing(self) -> None:
        self._paused_write = False
        if self._pending_out:
            pending, self._pending_out = self._pending_out, []
            for data in pending:
                self.transport.write(data)
        if self.transport is not None and not self._closed \
                and not self._paused_read_queue:
            try:
                self.transport.resume_reading()
            except RuntimeError:
                pass  # transport already closing: nothing to resume

    # -- async advisory path -------------------------------------------

    async def _worker_loop(self) -> None:
        while not self._closed:
            pkt = await self._queue.get()
            if self._paused_read_queue \
                    and self._queue.qsize() <= self.QUEUE_LOW_WATER:
                self._paused_read_queue = False
                if not self._closed and not self._paused_write:
                    try:
                        self.transport.resume_reading()
                    except RuntimeError:
                        pass  # transport already closing mid-drain
            self.pkts_in += 1
            try:
                if (
                    self._msg_bucket is not None
                    and not self._msg_bucket.unlimited
                    and pkt.type == P.PUBLISH
                ):
                    ok, wait = self._msg_bucket.consume(1.0)
                    if not ok:
                        await asyncio.sleep(wait)
                if self.intercept is not None and pkt.type in (
                    P.CONNECT, P.PUBLISH, P.SUBSCRIBE, P.UNSUBSCRIBE
                ):
                    actions = await self.intercept(self.channel, pkt)
                    if self._closed or self.channel.state == "disconnected":
                        return
                    if actions is not None:
                        self.channel.last_rx = time.time()
                        self._batching = self.coalesce
                        try:
                            self._run_actions(actions)
                        finally:
                            self._flush_writes()
                        continue
                self._batching = self.coalesce
                try:
                    self._run_actions(self.channel.handle_in(pkt))
                finally:
                    self._flush_writes()
            except asyncio.CancelledError:
                return  # connection closing: the worker exits with it
            except Exception:
                log.exception("protocol worker crashed (%s)",
                              self.conninfo.peername)
                self._do_close("internal error")
                return

    # -- broker-facing surface (same contract as Connection) -----------

    def deliver(self, pubs: List[Any]) -> None:
        """Routed deliveries.  The fanout pipeline hands MANY publishes
        per call, so this path serializes them all and issues ONE
        transport write (vs one syscall per message), and QoS0 publishes
        cache their wire bytes on the Message — a B-subscriber fan-out
        of a shared (zero-copy) message serializes once, not B times.
        On the batched stack (``coalesce``), QoS1/2 publishes cache a
        wire TEMPLATE: a fan-out leg differs from its siblings only in
        the 2 packet-id bytes, so one serialize + a per-leg patch
        replaces B full serializer passes.  The generic action path
        still serves everything else."""
        if self._closed or self.transport is None:
            return
        channel = self.channel
        ver = channel.proto_ver
        chunks: List[bytes] = []
        for p in pubs:
            data = None
            m = p.msg
            if p.pid is None:
                cache = m.__dict__.get("_wire")
                if cache is not None:
                    data = cache.get(ver)
            elif self.coalesce and not m.dup:
                cache = m.__dict__.get("_wire1")
                ent = cache.get(ver) if cache is not None else None
                if ent is not None:
                    tpl, off = ent
                    buf = bytearray(tpl)
                    buf[off] = p.pid >> 8
                    buf[off + 1] = p.pid & 0xFF
                    data = bytes(buf)
            if data is None:
                try:
                    data = F.serialize(channel._to_publish_pkt(p), ver=ver)
                except Exception:
                    log.exception("serialize failed (%s)",
                                  self.conninfo.peername)
                    continue
                if p.pid is None and not m.dup:
                    cache = m.__dict__.get("_wire")
                    if cache is None:
                        cache = m.__dict__["_wire"] = {}
                    cache[ver] = data
                elif self.coalesce and not m.dup:
                    # packet id sits right after the topic string in
                    # both v4 and v5 (§2.2.1 / §3.3.2.2): fixed header
                    # byte + remaining-length varint + 2-byte topic
                    # length + topic
                    vi = 1
                    while data[vi] & 0x80:
                        vi += 1
                    hdr = vi + 1
                    off = hdr + 2 + ((data[hdr] << 8) | data[hdr + 1])
                    cache = m.__dict__.get("_wire1")
                    if cache is None:
                        cache = m.__dict__["_wire1"] = {}
                    cache[ver] = (data, off)
            chunks.append(data)
        if not chunks:
            return
        self.pkts_out += len(chunks)
        data = chunks[0] if len(chunks) == 1 else b"".join(chunks)
        self.bytes_out += len(data)
        if _fi._injector is not None and not self._batching:
            # chaos seam: the fanout emit path writes here directly
            # (outside an inbound batch) — same drop/dup semantics as
            # the coalesced flush
            act = _fi._injector.act("transport.write")
            if act == "drop":
                return
            if act == "dup" and not self._paused_write \
                    and self.transport is not None:
                self.transport.write(data)
            if act == "raise":
                raise _fi.InjectedFault("transport.write")
        if self._batching:
            # deliveries landing re-entrantly while an inbound batch is
            # being handled (publisher subscribed to its own topic) stay
            # FIFO with the buffered acks and share their flush write
            self._wbuf.append(data)
            self._wbuf_pkts += len(chunks)
        elif self._paused_write:
            self._pending_out.append(data)
        else:
            self.transport.write(data)

    def kick(self, reason: str = "kicked") -> None:
        self._run_actions(self.channel.handle_takeover()
                          if reason == "takeover" else [("close", reason)])

    def _run_actions(self, actions: List[Any]) -> None:
        for act, arg in actions:
            if act == "send":
                self._send_pkt(arg)
            elif act == "close":
                self._do_close(str(arg))
            elif act == "takeover":
                old_conn = getattr(arg, "conn", None)
                acts = arg.handle_takeover()
                if old_conn is not None and old_conn is not self:
                    old_conn._run_actions(acts)

    # pid-only ack heads whose wire shape is fixed 4 bytes (PUBREL
    # carries its mandatory 0b0010 flags)
    _ACK_HEADS = {P.PUBACK: P.PUBACK << 4, P.PUBREC: P.PUBREC << 4,
                  P.PUBREL: (P.PUBREL << 4) | 2, P.PUBCOMP: P.PUBCOMP << 4}

    def _send_pkt(self, pkt: Any) -> None:
        if self._closed or self.transport is None:
            return
        head = self._ACK_HEADS.get(pkt.type) if self.coalesce else None
        if head is not None and type(pkt) is P.PubAck and (
            self.channel.proto_ver != 5
            or (pkt.reason_code == 0 and not pkt.properties)
        ):
            # serializer-free pid-only ack: identical 4 bytes (a v3/v4
            # wire never carries the rc; v5 rc-0/no-props is pid-only)
            pid = pkt.packet_id
            self._send_raw(bytes((head, 2, pid >> 8, pid & 0xFF)), 1)
            return
        try:
            data = F.serialize(pkt, ver=self.channel.proto_ver)
        except Exception:
            log.exception("serialize failed (%s)", self.conninfo.peername)
            return
        self.bytes_out += len(data)
        self.pkts_out += 1
        if self._batching:
            self._wbuf.append(data)
            self._wbuf_pkts += 1
        elif self._paused_write:
            self._pending_out.append(data)
        else:
            self.transport.write(data)

    def _send_raw(self, data: bytes, npkts: int) -> None:
        """Queue pre-serialized wire bytes (ack reply bursts, template
        resends) through the same batching/backpressure states as
        :meth:`_send_pkt`."""
        if self._closed or self.transport is None or not data:
            return
        self.bytes_out += len(data)
        self.pkts_out += npkts
        if self._batching:
            self._wbuf.append(data)
            self._wbuf_pkts += npkts
        elif self._paused_write:
            self._pending_out.append(data)
        else:
            self.transport.write(data)

    def _flush_writes(self) -> None:
        """Close the write batch: ONE transport write for everything
        buffered since it opened (ack bursts coalesce here)."""
        self._batching = False
        buf = self._wbuf
        if not buf:
            self._wbuf_pkts = 0
            return
        data = buf[0] if len(buf) == 1 else b"".join(buf)
        del buf[:]
        if self._wbuf_pkts > 1 and self.metrics is not None:
            self.metrics.inc("broker.ack.coalesced_writes")
        self._wbuf_pkts = 0
        if _fi._injector is not None:
            # chaos seam: lose or duplicate one coalesced flush on the
            # wire — the session retry machinery must heal the gap
            act = _fi._injector.act("transport.write")
            if act == "drop":
                return
            if act == "dup" and not self._paused_write \
                    and self.transport is not None:
                self.transport.write(data)
            if act == "raise":
                raise _fi.InjectedFault("transport.write")
        if self._paused_write:
            self._pending_out.append(data)
        elif self.transport is not None:
            self.transport.write(data)

    def _do_close(self, reason: str) -> None:
        if self._closed:
            return
        self._closed = True
        self._close_reason = reason
        if self.transport is not None:
            # flush the goodbye even under write pressure —
            # transport.write() only buffers while paused, and close()
            # tears down after the send buffer drains; dropping it
            # would turn a takeover DISCONNECT into a bare TCP reset.
            # _pending_out (paused-period backlog) predates the open
            # write batch, so it flushes first.
            for data in self._pending_out:
                self.transport.write(data)
            self._pending_out.clear()
            for data in self._wbuf:
                self.transport.write(data)
            self._wbuf.clear()
            self._wbuf_pkts = 0
            self.transport.close()

    def _frame_error(self, e: F.FrameError) -> None:
        adm = self.channel.broker.admission
        if adm is not None:
            # admission feature seam: malformed-frame rate.  Safe from
            # a shard loop — note_malformed only appends to a deque,
            # drained by the scorer on the main loop.
            adm.note_malformed(self.channel.clientid,
                               self.conninfo.peername)
        if self.channel.proto_ver == 5 and self.channel.state == "connected":
            self._send_pkt(P.Disconnect(reason_code=e.reason_code))
        self._do_close(f"frame error: {e}")

    def _pause_read_for(self, wait: float) -> None:
        if self.transport is None or self._closed:
            return
        try:
            self.transport.pause_reading()
        except RuntimeError:
            return  # transport already closing: no pacing needed

        def _resume():
            # a limiter resume must not undo queue/write backpressure —
            # those resume themselves when their own condition clears
            if self.transport is not None and not self._closed \
                    and not self._paused_write \
                    and not self._paused_read_queue:
                try:
                    self.transport.resume_reading()
                except RuntimeError:
                    pass  # transport closed while the pause timer ran

        asyncio.get_running_loop().call_later(max(wait, 0.001), _resume)

    def _tick(self) -> None:
        if self._closed:
            return
        try:
            self._batching = self.coalesce
            try:
                self._run_actions(self.channel.check_keepalive())
                if self.coalesce:
                    # batched resend: template-patched wire bytes, one
                    # coalesced flush for the whole tick
                    for chunk in self.channel.retry_wire_batch():
                        self._send_raw(chunk, 1)
                else:
                    self._run_actions(self.channel.retry_deliveries())
            finally:
                self._flush_writes()
            if not self._closed:
                # the flush reached the transport: commit the DUP
                # clones / age clocks (a raised write or a close mid-
                # tick leaves the entries due, so the next tick
                # re-offers them)
                self.channel.retry_commit()
        except Exception:
            log.exception("tick failed (%s)", self.conninfo.peername)
        if not self._closed:
            if self.wheel is not None:
                self._tick_handle = self.wheel.call_later(
                    self.TICK_S, self._tick)
            else:
                self._tick_handle = asyncio.get_running_loop().call_later(
                    self.TICK_S, self._tick)

    def info(self) -> dict:
        ch = self.channel
        return {
            "clientid": ch.clientid,
            "peername": self.conninfo.peername,
            "listener": self.conninfo.listener,
            "proto_ver": ch.proto_ver,
            "connected_at": self.conninfo.connected_at,
            "keepalive": ch.keepalive,
            "recv_oct": self.bytes_in,
            "send_oct": self.bytes_out,
            "recv_pkt": self.pkts_in,
            "send_pkt": self.pkts_out,
        }
