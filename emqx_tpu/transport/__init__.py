"""Network transport: listeners + per-connection asyncio loops.

Behavioral reference: ``esockd`` acceptor pools + ``emqx_connection.erl`` /
``emqx_ws_connection.erl`` [U] (SURVEY.md §1 L2/L3).  The reference runs one
Erlang process per socket; we run one asyncio task pair (reader + writer)
per socket on a shared event loop — the idiomatic Python analog with the
same isolation property (a crashing connection kills only itself).
"""

from .connection import Connection, ConnInfo
from .listener import Listener, Listeners

__all__ = ["Connection", "ConnInfo", "Listener", "Listeners"]
