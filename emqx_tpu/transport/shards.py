"""Connection-plane sharding: N worker event loops behind SO_REUSEPORT.

Behavioral reference: esockd's acceptor pool + per-connection process
model [U] (SURVEY.md §3.1) — the reference scales its connection plane
by running many acceptor/connection processes over one listen socket.
Here each **shard** is a worker thread running its own asyncio loop
with its own ``SO_REUSEPORT`` listener on the broker port (the kernel
load-balances accepted connections across shards), its own
:class:`~emqx_tpu.transport.timerwheel.TimerWheel` and its own limiter
group.  The shard loop runs everything per-connection: accept, frame
parse, keepalive/retry ticks, ack handling, QoS window state and the
serialize+write of deliveries — the costs that used to crowd the main
loop's ready queue at 1k+ real clients (BENCH_r05 config1: e2e p50
2.8 s of queueing on ONE loop).

What stays on the main loop is the broker core: routing tables,
session registry, hooks, the fanout pipeline, retained/delayed
services.  The two planes meet at two **batched MPSC handoffs**
(:class:`Handoff`): many shard threads → one ``call_soon_threadsafe``
per drain into the main loop (publish offers, CONNECT/SUBSCRIBE
marshals, close notifications), and one inbox per shard for the
reverse delivery path (routed publishes posted back to the owning
shard, batched the same way).  ``call_soon_threadsafe`` fires once per
drain, not once per message.

Thread-safety model (the part the ``loop-thread-taint`` staticcheck
rule polices):

* **broker state is main-loop-only.**  Every packet that touches it
  (CONNECT/auth, SUBSCRIBE/UNSUBSCRIBE, DISCONNECT, AUTH, anything
  pre-CONNECT, and PUBLISH whenever ``client.authorize`` hooks exist)
  marshals through the handoff and runs ``Channel.handle_in`` on the
  main loop; the resulting actions post back to the owning shard.
  While a marshal is in flight the shard queues that connection's
  subsequent packets behind it — per-connection packet order is
  preserved exactly.
* **session state is mutex-protected.**  A shard-owned
  :class:`~emqx_tpu.broker.session.Session` is touched from its shard
  (acks, QoS2 receiver state, retry peeks) and from the main loop
  (fanout ``Session.deliver``): both sides take the channel's
  ``mutex`` (an ``RLock``; ``Session.mutex`` is the same object).
  Lock hold times are one handled packet batch — microseconds — and
  neither side ever blocks on another lock while holding it.
* **publishes are affine-free.**  The shard fast path builds the
  :class:`Message`, acks, and hands the message to the main loop
  (fanout offer / ``Broker.publish`` fallback) through the handoff —
  one wire-level contract: PUBACK means "broker took responsibility",
  exactly the fanout pipeline's semantics (shards require
  ``broker.fanout.enable``).

Shards register as supervised children (``broker.shard.<i>``) with the
existing degraded-escalation policy: a crashed/killed shard loop closes
its sockets, the supervisor respawns a fresh loop + listener on the
same port, and the surviving shards keep serving — the chaos suite
kills one mid-QoS1-traffic and asserts exactly-once delivery holds.

Not supported with shards on (the pool refuses to start and the
listener falls back to the single-loop path): the async advisory stage
(exhook / cluster takeover / TPU prefetch / async auth backends) and
TLS listeners.  Plain sync auth chains work — publishes then take the
marshal path (``hooks.has("client.authorize")`` checked per connect).
"""

from __future__ import annotations

import asyncio
import logging
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .. import faultinject as _fi
from .. import topic as T
from ..broker.channel import Channel
from ..broker.message import make_message
from ..mqtt import frame as F
from ..mqtt import packet as P
from .connection import ConnInfo
from .proto_conn import MqttProtocol
from .timerwheel import TimerWheel

log = logging.getLogger(__name__)

__all__ = ["Handoff", "Shard", "ShardPool", "ShardChannel"]


class Handoff:
    """Batched MPSC cross-loop queue: any thread may ``put``; items
    drain on the consumer loop with ONE ``call_soon_threadsafe`` per
    drain (not per item).  The ``shard.handoff`` chaos seam rides the
    drain: an injected ``drop`` loses one drained batch, ``raise``
    surfaces :class:`InjectedFault` to the consumer's error handling."""

    def __init__(self, loop: asyncio.AbstractEventLoop,
                 consume: Callable[[List[Any]], None],
                 name: str = "handoff") -> None:
        self._loop = loop
        self._consume = consume
        self.name = name
        self._dq: deque = deque()
        self._armed = False
        self._lock = threading.Lock()
        self.drains = 0
        self.items = 0

    def put(self, item: Any) -> None:
        with self._lock:
            self._dq.append(item)
            if self._armed:
                return
            self._armed = True
        try:
            self._loop.call_soon_threadsafe(self._drain)
        except RuntimeError:
            # consumer loop is gone (shard died / node stopping): the
            # items are dropped with it — QoS1/2 heals via retry, QoS0
            # is best-effort by contract
            with self._lock:
                self._armed = False
                self._dq.clear()

    def depth(self) -> int:
        return len(self._dq)

    def _drain(self) -> None:
        with self._lock:
            items = list(self._dq)
            self._dq.clear()
            self._armed = False
        if not items:
            return
        self.drains += 1
        self.items += len(items)
        if _fi._injector is not None:
            act = _fi._injector.act("shard.handoff")
            if act == "drop":
                return
            if act == "raise":
                raise _fi.InjectedFault("shard.handoff")
        self._consume(items)


# ---------------------------------------------------------------------------
# the shard-side channel
# ---------------------------------------------------------------------------

# packet types a connected shard channel handles locally (session-affine
# state only; no broker tables)
_SHARD_LOCAL = frozenset((
    P.PUBACK, P.PUBREC, P.PUBREL, P.PUBCOMP, P.PINGREQ,
))


class ShardChannel(Channel):
    """Channel variant whose broker-touching packets marshal to the
    main loop (see module docstring).  Lives on a shard loop."""

    def __init__(self, pool: "ShardPool", shard: "Shard",
                 *args: Any, **kw: Any) -> None:
        super().__init__(*args, **kw)
        self.pool = pool
        self.shard = shard
        self.mutex = threading.RLock()
        # FIFO of packets parked behind an in-flight marshal (None =
        # no marshal in flight) — preserves per-connection order across
        # the shard/main boundary
        self._marshal_q: Optional[deque] = None
        # True while no client.authorize hooks exist (refreshed per
        # marshal on the main loop): publishes then skip the hook fold
        # entirely and stay on the shard fast path
        self._fast_pub = False
        self._close_posted = False

    # -- shard-loop surface -------------------------------------------

    def handle_in(self, pkt: Any) -> List[Any]:
        if self._marshal_q is not None:
            self._marshal_q.append(pkt)
            return []
        if self.state == "connected":
            t = pkt.type
            if t == P.PUBLISH and self._fast_pub:
                with self.mutex:
                    return super().handle_in(pkt)
            if t in _SHARD_LOCAL:
                with self.mutex:
                    return super().handle_in(pkt)
        # CONNECT / SUBSCRIBE / UNSUBSCRIBE / DISCONNECT / AUTH,
        # anything pre-CONNECT, and PUBLISH under an authz chain: runs
        # on the main loop; replies return via the shard inbox
        self._marshal_q = deque()
        self.pool.marshal(self, pkt)
        return []

    def handle_ack_run(self, run: Any):
        with self.mutex:
            return super().handle_ack_run(run)

    def handle_puback_batch(self, pkts: List[Any]):
        with self.mutex:
            return super().handle_puback_batch(pkts)

    def handle_publish_run(self, run: Any):
        if self.state != "connected" or not self._fast_pub \
                or self._marshal_q is not None:
            # per-packet discipline: rides the marshal queue ordering
            return b"", [], run.pkts
        with self.mutex:
            sess = self.session
            qos = run.qos
            ack_head = P.PUBREC << 4 if qos == 2 else P.PUBACK << 4
            valid: Dict[str, bool] = {}
            out = bytearray()
            actions: List[Any] = []
            offer = self.pool.offer
            for pkt in run.pkts:
                topic = self._resolve_alias(pkt)
                if topic is None:
                    actions.append(("close", "topic alias invalid"))
                    return bytes(out), actions, []
                ok = valid.get(topic)
                if ok is None:
                    ok = valid[topic] = T.is_valid(topic, "name")
                pid = pkt.packet_id
                if not ok:
                    if self.proto_ver == 5:
                        out += F.serialize(P.PubAck(
                            P.PUBREC if qos == 2 else P.PUBACK, pid,
                            P.RC.TOPIC_NAME_INVALID), ver=5)
                    else:
                        out += bytes((ack_head, 2, pid >> 8, pid & 0xFF))
                    continue
                msg = make_message(
                    self.clientid, topic, pkt.payload, qos=qos,
                    retain=pkt.retain, properties=dict(pkt.properties),
                )
                if qos == 2:
                    st = sess.publish_qos2(pid, msg)
                    if st == "full" and self.proto_ver == 5:
                        out += F.serialize(P.PubAck(
                            P.PUBREC, pid, P.RC.QUOTA_EXCEEDED), ver=5)
                        continue
                    if st == "ok":
                        offer(msg)
                else:
                    offer(msg)
                out += bytes((ack_head, 2, pid >> 8, pid & 0xFF))
            return bytes(out), actions, []

    def check_keepalive(self, now: Optional[float] = None):
        with self.mutex:
            return super().check_keepalive(now)

    def retry_deliveries(self, now: Optional[float] = None):
        with self.mutex:
            return super().retry_deliveries(now)

    def retry_wire_batch(self, now: Optional[float] = None):
        with self.mutex:
            return super().retry_wire_batch(now)

    def retry_commit(self) -> None:
        with self.mutex:
            super().retry_commit()

    def _handle_publish(self, pkt: P.Publish) -> List[Any]:
        """Shard fast path (only reached with ``_fast_pub``, i.e. no
        ``client.authorize`` hooks): alias/validity checks and the QoS2
        receiver transition run here; the message crosses to the main
        loop through the batched handoff, which offers it to the fanout
        pipeline (or ``Broker.publish`` on refusal).  Ack semantics are
        the fanout pipeline's: ack now, deliver from the batch."""
        topic = self._resolve_alias(pkt)
        if topic is None:
            return [("close", "topic alias invalid")]
        if not T.is_valid(topic, "name"):
            return self._puback_for(pkt, P.RC.TOPIC_NAME_INVALID)
        msg = make_message(
            self.clientid, topic, pkt.payload, qos=pkt.qos,
            retain=pkt.retain, properties=dict(pkt.properties),
        )
        if pkt.qos == 2:
            st = self.session.publish_qos2(pkt.packet_id, msg)
            if st == "full":
                return [("send", P.PubAck(P.PUBREC, pkt.packet_id,
                                          P.RC.QUOTA_EXCEEDED))]
            if st == "ok":
                self.pool.offer(msg)
            return [("send", P.PubAck(P.PUBREC, pkt.packet_id))]
        self.pool.offer(msg)
        if pkt.qos == 1:
            return [("send", P.PubAck(P.PUBACK, pkt.packet_id))]
        return []

    def handle_close(self, reason: str = "closed") -> None:
        """Transport died on the shard loop: the will publish, session
        close and hooks all touch broker state → marshal."""
        if self._close_posted:
            return
        self._close_posted = True
        self.pool.post_close(self, reason)

    # -- shard-loop continuation after a marshal round trip ------------

    def marshal_done(self, conn: Any, actions: List[Any]) -> None:
        """Runs on the shard loop with the main-loop verdict: apply the
        actions, then replay any packets that queued behind the
        marshal (stopping again if one of them re-marshals)."""
        batching = conn is not None and conn.coalesce \
            and conn.transport is not None
        if batching:
            conn._batching = True
        try:
            if conn is not None:
                conn._run_actions(actions)
            q = self._marshal_q
            self._marshal_q = None
            while q:
                pkt = q.popleft()
                acts = self.handle_in(pkt)
                if conn is not None and not conn._closed:
                    conn._run_actions(acts)
                if self._marshal_q is not None:
                    # re-marshalled: the rest stays parked behind it
                    self._marshal_q.extend(q)
                    break
        finally:
            if batching:
                conn._flush_writes()


class _ShardProtocol(MqttProtocol):
    """MqttProtocol + handoff backpressure: when the shard→main handoff
    backs up past the high-water mark, pause this socket briefly — the
    kernel buffer (and the peer's window) absorbs the burst instead of
    an unbounded cross-thread queue."""

    shard: Optional["Shard"] = None

    def data_received(self, data: bytes) -> None:
        super().data_received(data)
        shard = self.shard
        if shard is not None and \
                shard.pool.handoff.depth() > shard.pool.HANDOFF_HIGH_WATER:
            self._pause_read_for(0.02)


# ---------------------------------------------------------------------------
# shards
# ---------------------------------------------------------------------------


class Shard:
    """One worker thread: its own event loop, SO_REUSEPORT listener,
    timer wheel, limiter group and delivery inbox."""

    def __init__(self, pool: "ShardPool", index: int) -> None:
        self.pool = pool
        self.index = index
        self.name = f"broker.shard.{index}"
        # stage-level latency observatory: the shard plane writes its
        # OWN histogram set from its own loop thread (single writer);
        # node.hist_sets() merges it with the main plane at read time.
        # None (obs.hist.enable off) keeps the shard at zero records.
        node_hists = getattr(pool.node, "hists", None)
        self.hists = None
        if node_hists is not None:
            from ..observe.hist import HistSet

            self.hists = HistSet(self.name)
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.thread: Optional[threading.Thread] = None
        self.wheel: Optional[TimerWheel] = None
        self.inbox: Optional[Handoff] = None
        self.limiter = None
        self.server: Optional[asyncio.AbstractServer] = None
        self.conns: set = set()
        self.accepted = 0
        self.port = 0
        self._started: Optional[threading.Event] = None
        self._dead_evt: Optional[asyncio.Event] = None  # main-loop event
        self._stopping = False
        self._child = None

    # -- lifecycle (called on the MAIN loop) ---------------------------

    async def start(self, host: str, port: int) -> int:
        self._stopping = False
        self._dead_evt = asyncio.Event()
        self._started = threading.Event()
        self.conns.clear()  # a respawn starts with a clean registry
        self.loop = asyncio.new_event_loop()
        self.inbox = Handoff(self.loop, self._consume_inbox,
                             name=f"{self.name}.inbox")
        self.thread = threading.Thread(
            target=self._thread_main, name=self.name, daemon=True)
        self.thread.start()
        ok = await asyncio.to_thread(self._started.wait, 5.0)
        if not ok:
            raise RuntimeError(f"{self.name}: loop did not start")
        fut = asyncio.run_coroutine_threadsafe(
            self._bind(host, port), self.loop)
        self.port = await asyncio.wrap_future(fut)
        return self.port

    async def stop(self) -> None:
        self._stopping = True
        loop, thread = self.loop, self.thread
        if loop is None or thread is None or not thread.is_alive():
            return
        try:
            loop.call_soon_threadsafe(loop.stop)
        except RuntimeError:
            pass  # loop already closed (shard died on its own):
            #     the join below reaps the thread either way
        await asyncio.to_thread(thread.join, 5.0)

    def kill(self) -> bool:
        """Chaos surface: stop the shard loop from outside, as a crash
        would.  The supervised child notices and respawns."""
        loop, thread = self.loop, self.thread
        if loop is None or thread is None or not thread.is_alive():
            return False
        try:
            loop.call_soon_threadsafe(loop.stop)
        except RuntimeError:
            return False
        return True

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    async def _supervised_run(self) -> None:
        """The supervised-child body (main loop): (re)spawn the worker
        thread if it is down, then watch for its death."""
        if not self.alive():
            await self.start(self.pool.host, self.pool.port)
        await self._dead_evt.wait()
        if not self._stopping:
            raise RuntimeError(f"{self.name}: shard loop exited")

    # -- worker thread -------------------------------------------------

    def _thread_main(self) -> None:
        loop = self.loop
        asyncio.set_event_loop(loop)
        self.wheel = TimerWheel()
        from ..broker.limiter import LimiterGroup
        cfg = self.pool.config
        self.limiter = LimiterGroup(
            max_conn_rate=cfg.get("limiter.max_conn_rate"),
            max_messages_rate=cfg.get("limiter.max_messages_rate"),
            max_bytes_rate=cfg.get("limiter.max_bytes_rate"),
        ) if cfg is not None else None
        self._started.set()
        try:
            loop.run_forever()
        finally:
            try:
                loop.run_until_complete(self._cleanup())
            except Exception:
                log.exception("%s: cleanup failed", self.name)
            try:
                loop.close()
            except Exception:
                log.debug("%s: loop close failed", self.name, exc_info=True)
            self.pool.notify_dead(self)

    async def _bind(self, host: str, port: int) -> int:
        # SO_REUSEPORT: all shards bind the broker port; the kernel
        # load-balances accepted connections across their loops (the
        # esockd acceptor-pool analog listener.py's comment gestures at)
        self.server = await self.loop.create_server(
            self._make_protocol, host, port, reuse_port=True)
        socks = self.server.sockets or []
        return socks[0].getsockname()[1] if socks else port

    async def _cleanup(self) -> None:
        if self.wheel is not None:
            self.wheel.close()
        if self.server is not None:
            self.server.close()
            self.server = None
        for conn in list(self.conns):
            try:
                conn._do_close("shard stopped")
            except Exception:
                log.debug("%s: conn close failed", self.name, exc_info=True)
        # one beat so transports flush their goodbyes before close
        await asyncio.sleep(0)

    def _make_protocol(self):
        pool = self.pool
        if not pool.accept_allowed():
            from .listener import _ShedProtocol
            return _ShedProtocol()
        proto = pool.make_protocol(self)
        self.accepted += 1
        orig_made = proto.connection_made
        orig_lost = proto.connection_lost

        def made(transport):
            self.conns.add(proto)
            orig_made(transport)

        def lost(exc):
            self.conns.discard(proto)
            orig_lost(exc)

        proto.connection_made = made
        proto.connection_lost = lost
        return proto

    # -- cross-loop surface (any thread) -------------------------------

    def post(self, fn: Callable[[], Any]) -> None:
        """Run ``fn`` on the shard loop (batched with deliveries)."""
        self.inbox.put(("call", fn))

    def post_deliver(self, conn: Any, pubs: List[Any]) -> None:
        """Reverse delivery path: routed publishes for a shard-owned
        connection, serialized+written on the shard loop."""
        self.inbox.put(("dlv", conn, pubs))

    def post_actions(self, chan: ShardChannel, conn: Any,
                     actions: List[Any]) -> None:
        self.inbox.put(("acts", chan, conn, actions))

    def _consume_inbox(self, items: List[Any]) -> None:
        """Shard-loop drain of the inbox — one callback per batch."""
        for it in items:
            tag = it[0]
            try:
                if tag == "dlv":
                    conn = it[1]
                    if not conn._closed:
                        conn.deliver(it[2])
                elif tag == "acts":
                    it[1].marshal_done(it[2], it[3])
                else:  # "call"
                    it[1]()
            except Exception:
                log.exception("%s: inbox item failed", self.name)

    def info(self) -> Dict[str, Any]:
        return {
            "index": self.index, "alive": self.alive(),
            "connections": len(self.conns), "accepted": self.accepted,
            "wheel": (self.wheel.info() if self.wheel is not None
                      else None),
        }


class ShardPool:
    """The N shards of one listener + the shard→main handoff + the
    main-loop marshal handlers.  Owned by the node, attached to the
    TCP listener."""

    HANDOFF_HIGH_WATER = 8192

    def __init__(self, node: Any, n: int) -> None:
        self.node = node
        self.config = getattr(node, "config", None)
        self.n = n
        self.host = ""
        self.port = 0
        self.shards = [Shard(self, i) for i in range(n)]
        self.handoff: Optional[Handoff] = None
        self._main_loop: Optional[asyncio.AbstractEventLoop] = None
        self.running = False

    # -- lifecycle (main loop) ----------------------------------------

    async def start(self, host: str, port: int) -> int:
        """Bind every shard's SO_REUSEPORT listener (shard 0 resolves
        ``:0`` to a concrete port for the rest), register the shards as
        supervised children, and open the handoff."""
        self._main_loop = asyncio.get_running_loop()
        self.handoff = Handoff(self._main_loop,
                               self._consume, name="shard.handoff")
        self.host = host
        self.port = await self.shards[0].start(host, port)
        for shard in self.shards[1:]:
            await shard.start(host, self.port)
        sup = getattr(self.node, "supervisor", None)
        if sup is not None:
            for shard in self.shards:
                shard._child = sup.start_child(
                    shard.name, shard._supervised_run,
                    restart="permanent", drain=shard.stop)
        self.running = True
        metrics = self._metrics()
        if metrics is not None:
            metrics.set("broker.conn.shards", self.n)
        log.info("connection plane sharded: %d loops on %s:%d",
                 self.n, host, self.port)
        return self.port

    async def stop(self) -> None:
        if not self.running:
            return
        self.running = False
        for shard in self.shards:
            if shard._child is not None:
                child, shard._child = shard._child, None
                try:
                    await child.stop()   # cancels the watcher, drains
                except Exception:
                    log.debug("shard child stop failed", exc_info=True)
            else:
                await shard.stop()
        metrics = self._metrics()
        if metrics is not None:
            metrics.set("broker.conn.shards", 0)

    def _metrics(self):
        observed = getattr(self.node, "observed", None)
        return getattr(observed, "metrics", None)

    def notify_dead(self, shard: Shard) -> None:
        """Called from a dying worker thread (its loop already closed):
        flip the main-loop death event so the supervised watcher
        restarts the shard (or, on orderly stop, just returns)."""
        evt = shard._dead_evt
        loop = getattr(self, "_main_loop", None)
        if evt is None or loop is None:
            return
        try:
            loop.call_soon_threadsafe(evt.set)
        except RuntimeError:
            pass  # main loop already gone (interpreter shutdown)

    # -- accept-side helpers (called on shard loops) -------------------

    def accept_allowed(self) -> bool:
        listener = getattr(self, "listener", None)
        if listener is None:
            return True
        # racy cross-thread read of the aggregate count: sheds are
        # approximate by design, exactly like esockd's per-acceptor view
        if listener.current_connections >= listener.max_connections:
            listener.shed_count += 1
            return False
        return True

    def make_protocol(self, shard: Shard):
        return self.node.make_shard_protocol(shard)

    def conn_count(self) -> int:
        return sum(len(s.conns) for s in self.shards)

    # -- shard → main handoff ------------------------------------------

    def offer(self, msg: Any) -> None:
        self.handoff.put(("pub", msg))

    def marshal(self, chan: ShardChannel, pkt: Any) -> None:
        self.handoff.put(("chan", chan, pkt))

    def post_close(self, chan: ShardChannel, reason: str) -> None:
        self.handoff.put(("close", chan, reason))

    def conn_closed(self, proto: Any) -> None:
        """proto_conn's ``on_closed`` callback (runs on the shard
        loop): the registry cleanup happens on the main loop."""
        self.handoff.put(("closed", proto))

    def _consume(self, items: List[Any]) -> None:
        """Main-loop drain: contiguous publish runs batch into the
        fanout pipeline; marshals/closes interleave in FIFO order so
        per-connection ordering is preserved end to end."""
        pubs: List[Any] = []
        for it in items:
            tag = it[0]
            if tag == "pub":
                pubs.append(it[1])
                continue
            if pubs:
                self._publish_batch(pubs)
                pubs = []
            try:
                if tag == "chan":
                    self._main_handle(it[1], it[2])
                elif tag == "close":
                    self._main_close(it[1], it[2])
                elif tag == "closed":
                    self._main_conn_closed(it[1])
            except Exception:
                log.exception("shard handoff item failed (%s)", tag)
        if pubs:
            self._publish_batch(pubs)

    def _publish_batch(self, msgs: List[Any]) -> None:
        broker = self.node.broker
        fanout = broker.fanout
        adm = broker.admission
        for m in msgs:
            try:
                if adm is not None:
                    # admission feature seam for the shard ingest: the
                    # shard loops never touch admission state — every
                    # fast-path publish is noted here, on the main-loop
                    # side of the handoff, exactly once
                    adm.note_publish(m.sender, m.topic, len(m.payload))
                if fanout is None or not fanout.offer(m):
                    broker.publish(m)
            except Exception:
                log.exception("shard publish failed")

    def _main_handle(self, chan: ShardChannel, pkt: Any) -> None:
        """One marshaled packet, handled with full broker access on the
        main loop; the verdict posts back to the owning shard."""
        node = self.node
        with chan.mutex:
            try:
                actions = Channel.handle_in(chan, pkt)
            except Exception:
                log.exception("marshaled packet handling failed")
                actions = [("close", "internal error")]
            sess = chan.session
            if sess is not None and sess.mutex is None:
                # main-loop deliveries and shard-loop acks now exclude
                # each other through the channel's own lock
                sess.mutex = chan.mutex
            chan._fast_pub = not node.broker.hooks.has("client.authorize")
        out: List[Any] = []
        for act, arg in actions:
            if act == "takeover":
                self._takeover(arg)
                continue
            out.append((act, arg))
        conn = chan.conn
        cid = chan.clientid
        if cid is not None and chan.state == "connected" \
                and node.connections.get(cid) is not conn:
            node.connections[cid] = conn
        chan.shard.post_actions(chan, conn, out)

    def _takeover(self, old_chan: Any) -> None:
        """A shard client's CONNECT displaced ``old_chan``: run the
        goodbye on whichever loop owns the old connection."""
        old_conn = getattr(old_chan, "conn", None)
        old_shard = getattr(old_chan, "shard", None)
        if old_shard is not None and old_shard.alive():
            def _go():
                with old_chan.mutex:
                    acts = old_chan.handle_takeover()
                if old_conn is not None:
                    old_conn._run_actions(acts)
            old_shard.post(_go)
            return
        acts = old_chan.handle_takeover()
        if old_conn is not None:
            old_conn._run_actions(acts)

    def _main_close(self, chan: ShardChannel, reason: str) -> None:
        with chan.mutex:
            Channel.handle_close(chan, reason)

    def _main_conn_closed(self, proto: Any) -> None:
        node = self.node
        node._all_conns.discard(proto)
        cid = proto.channel.clientid
        if cid is not None and node.connections.get(cid) is proto:
            del node.connections[cid]

    # -- observability -------------------------------------------------

    def wheel_conns(self) -> int:
        total = 0
        for s in self.shards:
            w = s.wheel
            if w is not None:
                total += len(w)
        return total

    def info(self) -> List[Dict[str, Any]]:
        return [s.info() for s in self.shards]
