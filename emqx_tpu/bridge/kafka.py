"""Kafka producer bridge over a minimal wire-protocol client.

Behavioral reference: ``apps/emqx_bridge_kafka`` [U] (SURVEY.md §2.3) —
the reference's flagship data bridge: rule-engine output → buffered
worker → Kafka topic, with per-message key/value templates and
partition dispatch.

The wire client is dependency-free and speaks exactly what a producer
needs, pinned to broker-era-stable versions:

* ``Metadata`` v1 (api 3) — partition leaders for the target topic;
* ``Produce`` v3 (api 0) — record batches v2 (magic 2): zigzag-varint
  records, CRC-32C (Castagnoli, software table — no snappy/crc32c
  package in this environment, SURVEY §2.4), acks=1.

Compression is not attempted (attributes=0): snappy/lz4 are not in the
environment's package set, and Kafka accepts uncompressed batches from
any producer.  Partitioning is murmur-free: explicit ``partition`` in
the rendered item, else key-hash (crc32c of the key) mod partitions,
else round-robin — deployments needing Java-client-compatible
murmur2 placement set explicit partitions.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Any, Dict, List, Optional, Tuple

from ..wire import LazyTcpClient
from .resource import Connector, SendError

log = logging.getLogger(__name__)

__all__ = ["crc32c", "KafkaConnector", "render_kafka", "KafkaError"]


class KafkaError(Exception):
    pass


# -- CRC-32C (Castagnoli), software table ------------------------------------

def _crc_table() -> List[int]:
    poly = 0x82F63B78
    tab = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        tab.append(c)
    return tab


# built once at import: lazy init would race between the event loop and
# asyncio.to_thread (record_batch of big batches runs in a worker)
_CRC32C_TABLE: List[int] = _crc_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    tab = _CRC32C_TABLE
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = tab[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


# -- wire primitives ---------------------------------------------------------

def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack("!h", -1)
    b = s.encode()
    return struct.pack("!h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack("!i", -1)
    return struct.pack("!i", len(b)) + b


def _varint(v: int) -> bytes:
    """Zigzag varint (Kafka record fields)."""
    z = (v << 1) ^ (v >> 63)
    out = bytearray()
    while (z & ~0x7F) != 0:
        out.append((z & 0x7F) | 0x80)
        z >>= 7
    out.append(z & 0x7F)
    return bytes(out)


def read_varint(data: bytes, off: int) -> Tuple[int, int]:
    shift = z = 0
    while True:
        b = data[off]
        off += 1
        z |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), off


def _record(offset_delta: int, ts_delta: int, key: Optional[bytes],
            value: bytes) -> bytes:
    body = (b"\x00"                                    # attributes
            + _varint(ts_delta) + _varint(offset_delta)
            + (_varint(-1) if key is None
               else _varint(len(key)) + key)
            + _varint(len(value)) + value
            + _varint(0))                              # no headers
    return _varint(len(body)) + body


def record_batch(records: List[Tuple[Optional[bytes], bytes]],
                 base_ts_ms: Optional[int] = None) -> bytes:
    """Record batch v2 (magic 2), uncompressed, producer-id-less."""
    ts = int(base_ts_ms if base_ts_ms is not None else time.time() * 1e3)
    recs = b"".join(
        _record(i, 0, k, v) for i, (k, v) in enumerate(records))
    n = len(records)
    after_crc = (
        struct.pack("!hiqqqhii", 0, n - 1, ts, ts, -1, -1, -1, n) + recs
    )
    crc = crc32c(after_crc)
    head = struct.pack("!iBI", -1, 2, crc)             # epoch, magic, crc
    body = head + after_crc
    return struct.pack("!qi", 0, len(body)) + body     # baseOffset, len


def parse_record_batch(data: bytes) -> List[Tuple[Optional[bytes], bytes]]:
    """Decode one batch (test servers + loopback verification); checks
    the CRC."""
    base_off, blen = struct.unpack_from("!qi", data, 0)
    epoch, magic, crc = struct.unpack_from("!iBI", data, 12)
    if magic != 2:
        raise KafkaError(f"unsupported magic {magic}")
    after = data[21:12 + blen]
    if crc32c(after) != crc:
        raise KafkaError("record batch crc mismatch")
    (attrs, last_delta, t0, t1, pid, peph, seq,
     n) = struct.unpack_from("!hiqqqhii", after, 0)
    off = struct.calcsize("!hiqqqhii")
    out = []
    for _ in range(n):
        _, off = read_varint(after, off)               # record length
        off += 1                                       # attributes
        _, off = read_varint(after, off)               # ts delta
        _, off = read_varint(after, off)               # offset delta
        klen, off = read_varint(after, off)
        key = None
        if klen >= 0:
            key = after[off:off + klen]
            off += klen
        vlen, off = read_varint(after, off)
        val = after[off:off + vlen]
        off += vlen
        nh, off = read_varint(after, off)
        for _ in range(nh):                            # skip headers
            hk, off = read_varint(after, off)
            off += hk
            hv, off = read_varint(after, off)
            off += max(0, hv)
        out.append((key, val))
    return out


RETRIABLE_ERRORS = {5, 6, 7, 9, 19}  # leader/broker transitions, timeouts


class KafkaClient(LazyTcpClient):
    """One async connection to a bootstrap broker: Metadata + Produce."""

    def __init__(self, server: str = "127.0.0.1:9092", *,
                 client_id: str = "emqx_tpu", timeout: float = 5.0) -> None:
        super().__init__(server, 9092, timeout)
        self.client_id = client_id
        self._corr = 0

    async def _request(self, api_key: int, api_version: int,
                       body: bytes, expect_response: bool = True) -> bytes:
        return await self._guarded(
            lambda: self._request_locked(api_key, api_version, body,
                                         expect_response))

    async def _request_locked(self, api_key, api_version, body,
                              expect_response=True):
        self._corr += 1
        head = (struct.pack("!hhi", api_key, api_version, self._corr)
                + _str(self.client_id))
        msg = head + body
        self._writer.write(struct.pack("!i", len(msg)) + msg)
        await self._writer.drain()
        if not expect_response:     # acks=0: Kafka sends NO response
            return b""
        (ln,) = struct.unpack("!i", await self._reader.readexactly(4))
        payload = await self._reader.readexactly(ln)
        (corr,) = struct.unpack_from("!i", payload, 0)
        if corr != self._corr:
            raise KafkaError(f"correlation mismatch {corr}!={self._corr}")
        return payload[4:]

    # -- Metadata v1 --------------------------------------------------------

    async def partitions(self, topic: str) -> int:
        body = struct.pack("!i", 1) + _str(topic)
        p = await self._request(3, 1, body)
        off = 0
        (nb,) = struct.unpack_from("!i", p, off)
        off += 4
        for _ in range(nb):                            # brokers
            off += 4                                   # node_id
            (sl,) = struct.unpack_from("!h", p, off)
            off += 2 + sl + 4                          # host, port
            (rl,) = struct.unpack_from("!h", p, off)   # rack
            off += 2 + max(0, rl)
        off += 4                                       # controller id
        (nt,) = struct.unpack_from("!i", p, off)
        off += 4
        for _ in range(nt):
            (err,) = struct.unpack_from("!h", p, off)
            off += 2
            (sl,) = struct.unpack_from("!h", p, off)
            off += 2
            name = p[off:off + sl].decode()
            off += sl
            off += 1                                   # is_internal
            (np_,) = struct.unpack_from("!i", p, off)
            off += 4
            if name == topic:
                if err not in (0, 5):                  # 5: leader election
                    raise KafkaError(f"metadata error {err} for {topic}")
                return max(1, np_)
            for _ in range(np_):                       # skip partitions
                off += 2 + 4 + 4                       # err, id, leader
                (nr,) = struct.unpack_from("!i", p, off)
                off += 4 + 4 * nr
                (ni,) = struct.unpack_from("!i", p, off)
                off += 4 + 4 * ni
        raise KafkaError(f"topic {topic} not in metadata")

    # -- Produce v3 ---------------------------------------------------------

    async def produce(self, topic: str, partition: int,
                      records: List[Tuple[Optional[bytes], bytes]],
                      acks: int = 1) -> int:
        """Send one batch; returns the base offset assigned (-1 for
        acks=0, which Kafka leaves unanswered on the wire)."""
        if sum(len(v) + len(k or b"") for k, v in records) > 65536:
            # the software CRC-32C is a per-byte Python loop; keep big
            # batches off the event loop (broker keepalives run there)
            batch = await asyncio.to_thread(record_batch, records)
        else:
            batch = record_batch(records)
        body = (_str(None)                             # transactional_id
                + struct.pack("!hi", acks, int(self.timeout * 1e3))
                + struct.pack("!i", 1) + _str(topic)
                + struct.pack("!i", 1)
                + struct.pack("!i", partition) + _bytes(batch))
        p = await self._request(0, 3, body, expect_response=acks != 0)
        if acks == 0:
            return -1
        off = 0
        (nt,) = struct.unpack_from("!i", p, off)
        off += 4
        for _ in range(nt):
            (sl,) = struct.unpack_from("!h", p, off)
            off += 2 + sl
            (np_,) = struct.unpack_from("!i", p, off)
            off += 4
            for _ in range(np_):
                pid, err, base = struct.unpack_from("!ihq", p, off)
                off += 4 + 2 + 8 + 8                   # + log_append_time
                if err:
                    raise SendError(
                        f"kafka produce error {err} on {topic}/{pid}",
                        retryable=err in RETRIABLE_ERRORS)
                return base
        raise KafkaError("empty produce response")


def render_kafka(conf: Dict[str, Any], output: Dict[str, Any],
                 columns: Dict[str, Any]) -> Dict[str, Any]:
    """Rule output -> one Kafka item: templated key/value, optional
    explicit partition.  Templates go through the rule engine's shared
    ``render_template`` (single-scan, missing fields render empty,
    dotted paths) — a hand-rolled replace loop would re-scan substituted
    payload bytes and let clients inject other fields' placeholders."""
    from ..rule_engine.runtime import render_template

    key_tpl = conf.get("key_template", "${clientid}")
    val_tpl = conf.get("value_template")
    if val_tpl:
        value = render_template(val_tpl, output, columns).encode()
    else:
        payload = output.get("payload", columns.get("payload", b""))
        value = payload if isinstance(payload, bytes) else \
            str(payload).encode()
    key = render_template(key_tpl, output, columns).encode() or None
    item = {"key": key, "value": value}
    if "partition" in conf:
        item["partition"] = int(conf["partition"])
    return item


class KafkaConnector(Connector):
    """Buffered-worker connector: batches items into record batches."""

    def __init__(self, conf: Dict[str, Any], name: str = "") -> None:
        self.conf = conf
        self.name = name
        self.topic = conf.get("topic", "emqx")
        self.acks = int(conf.get("acks", 1))
        self.client = KafkaClient(
            conf.get("server", "127.0.0.1:9092"),
            client_id=conf.get("client_id", f"emqx_tpu:{name}"),
            timeout=float(conf.get("timeout", 5.0)))
        self.n_partitions = 1
        self._rr = 0

    async def start(self) -> None:
        self.n_partitions = await self.client.partitions(self.topic)

    async def stop(self) -> None:
        await self.client.close()

    async def health(self) -> bool:
        try:
            self.n_partitions = await self.client.partitions(self.topic)
            return True
        except Exception:
            return False

    def _partition_of(self, item: Dict[str, Any]) -> int:
        if "partition" in item:
            return int(item["partition"]) % self.n_partitions
        key = item.get("key")
        if key:
            return crc32c(key) % self.n_partitions
        self._rr += 1
        return self._rr % self.n_partitions

    async def send(self, items: List[Dict[str, Any]]) -> Optional[int]:
        """Returns the REJECTED count per the Connector contract (0 —
        Kafka acks a batch wholesale; errors raise SendError carrying
        the undelivered items, so partitions acked before a failure are
        never re-produced)."""
        by_part: Dict[int, List[Dict[str, Any]]] = {}
        for it in items:
            by_part.setdefault(self._partition_of(it), []).append(it)
        pending = dict(by_part)
        for part, group in by_part.items():
            try:
                await self.client.produce(
                    self.topic, part,
                    [(it.get("key"), it["value"]) for it in group],
                    acks=self.acks)
            except SendError as e:
                remaining = [it for g in pending.values() for it in g]
                raise SendError(str(e), retryable=e.retryable,
                                remaining=remaining) from e
            except Exception as e:
                remaining = [it for g in pending.values() for it in g]
                raise SendError(str(e), retryable=True,
                                remaining=remaining) from e
            del pending[part]
        return 0
