"""Kafka producer bridge over a minimal wire-protocol client.

Behavioral reference: ``apps/emqx_bridge_kafka`` [U] (SURVEY.md §2.3) —
the reference's flagship data bridge: rule-engine output → buffered
worker → Kafka topic, with per-message key/value templates and
partition dispatch.

The wire client is dependency-free and speaks exactly what a producer
needs, pinned to broker-era-stable versions:

* ``Metadata`` v1 (api 3) — partition leaders for the target topic;
* ``Produce`` v3 (api 0) — record batches v2 (magic 2): zigzag-varint
  records, CRC-32C (Castagnoli — hardware SSE4.2 via
  ``native/snappy.cpp`` when the toolchain is present, else the
  software table below), acks=1.

Compression: ``conf["compression"]`` = ``"snappy"`` (xerial-framed
blocks via the in-repo ``native/snappy.cpp`` codec — the
snappy-erlang-nif analog, SURVEY §2.4), ``"lz4"`` (in-repo
``native/lz4.cpp`` block codec + LZ4 frame format, interop-tested
against system liblz4), ``"gzip"`` (stdlib zlib) or ``"zstd"``
(in-repo ``native/zstd.py``: greedy LZ77, fitted/predefined/RLE FSE
sequence tables, Huffman literals, repeat offsets — real ratio,
decodable by every zstd implementation).  Fetch decodes all FOUR
codecs — zstd through the full RFC 8878 decoder in
``native/zstd.cpp`` (Huffman literals, FSE sequences, repeat
offsets, xxh64 checksums), interop-tested against system libzstd —
so Java-producer batches ingest whole; a toolchain-less host decodes
the same format through the pure-Python fallback (minus xxh64
verification).  Partitioning is murmur-free:
explicit ``partition`` in the rendered item, else key-hash (crc32c of
the key) mod partitions, else round-robin — deployments needing
Java-client-compatible murmur2 placement set explicit partitions.
"""

from __future__ import annotations

import asyncio
import gzip
import logging
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..wire import LazyTcpClient
from .resource import Connector, SendError

log = logging.getLogger(__name__)

__all__ = ["crc32c", "KafkaConnector", "render_kafka", "KafkaError"]


class KafkaError(Exception):
    pass


# -- CRC-32C (Castagnoli), software table ------------------------------------

def _crc_table() -> List[int]:
    poly = 0x82F63B78
    tab = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        tab.append(c)
    return tab


# built once at import: lazy init would race between the event loop and
# asyncio.to_thread (record_batch of big batches runs in a worker)
_CRC32C_TABLE: List[int] = _crc_table()

# native codec probed at import for the same reason (forces the one-time
# .so build/load before any worker threads exist)
from ..native import snappy as _sz  # noqa: E402
from ..native import lz4 as _lz4  # noqa: E402
from ..native import zstd as _zs  # noqa: E402

_NATIVE_CRC = _sz.available()
_lz4.available()    # same: force the one-time .so build/load up front
_zs.available()


def crc32c(data: bytes, crc: int = 0) -> int:
    if _NATIVE_CRC:
        return _sz.crc32c(data, crc)
    tab = _CRC32C_TABLE
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = tab[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


# -- wire primitives ---------------------------------------------------------

def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack("!h", -1)
    b = s.encode()
    return struct.pack("!h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack("!i", -1)
    return struct.pack("!i", len(b)) + b


def _varint(v: int) -> bytes:
    """Zigzag varint (Kafka record fields)."""
    z = (v << 1) ^ (v >> 63)
    out = bytearray()
    while (z & ~0x7F) != 0:
        out.append((z & 0x7F) | 0x80)
        z >>= 7
    out.append(z & 0x7F)
    return bytes(out)


def read_varint(data: bytes, off: int) -> Tuple[int, int]:
    shift = z = 0
    while True:
        b = data[off]
        off += 1
        z |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), off


def _record(offset_delta: int, ts_delta: int, key: Optional[bytes],
            value: bytes) -> bytes:
    body = (b"\x00"                                    # attributes
            + _varint(ts_delta) + _varint(offset_delta)
            + (_varint(-1) if key is None
               else _varint(len(key)) + key)
            + _varint(len(value)) + value
            + _varint(0))                              # no headers
    return _varint(len(body)) + body


_CODEC_BITS = {None: 0, "none": 0, "gzip": 1, "snappy": 2,
               "lz4": 3, "zstd": 4}


def record_batch(records: List[Tuple[Optional[bytes], bytes]],
                 base_ts_ms: Optional[int] = None,
                 base_offset: int = 0,
                 compression: Optional[str] = None) -> bytes:
    """Record batch v2 (magic 2), producer-id-less; optional snappy
    (xerial framing, as the Java client emits), lz4 (frame format) or
    gzip compression of the records section."""
    ts = int(base_ts_ms if base_ts_ms is not None else time.time() * 1e3)
    recs = b"".join(
        _record(i, 0, k, v) for i, (k, v) in enumerate(records))
    attrs = _CODEC_BITS[compression]
    if attrs == 1:
        recs = gzip.compress(recs)
    elif attrs == 2:
        recs = _sz.compress_xerial(recs)
    elif attrs == 3:
        recs = _lz4.compress_frame(recs)
    elif attrs == 4:
        recs = _zs.compress_frame(recs)
    n = len(records)
    after_crc = (
        struct.pack("!hiqqqhii", attrs, n - 1, ts, ts, -1, -1, -1, n) + recs
    )
    crc = crc32c(after_crc)
    head = struct.pack("!iBI", -1, 2, crc)             # epoch, magic, crc
    body = head + after_crc
    return struct.pack("!qi", base_offset, len(body)) + body


def parse_batches(data: bytes) -> Tuple[
        List[Tuple[int, Optional[bytes], bytes]], int, int]:
    """Decode a CONCATENATED batch stream (a Fetch response's records
    field) -> ([(offset, key, value)], next_fetch_offset, n_skipped).
    Truncated trailing bytes (partial batch at max_bytes) are ignored,
    as consumers must.  gzip/snappy/lz4/zstd batches decode; control
    batches (and zstd only when no native decoder could be built) are
    SKIPPED but still advance the fetch offset via the header's
    lastOffsetDelta — a skip must never stall the consumer;
    ``n_skipped`` lets callers log the gap."""
    out: List[Tuple[int, Optional[bytes], bytes]] = []
    next_off = 0
    skipped = 0
    pos = 0
    while pos + 12 <= len(data):
        base, blen = struct.unpack_from("!qi", data, pos)
        if pos + 12 + blen > len(data):
            break                                      # partial batch
        last_delta, recs = _parse_batch_full(data[pos:pos + 12 + blen])
        if recs is None:
            skipped += 1
        else:
            out.extend((base + d, k, v) for d, k, v in recs)
        next_off = base + last_delta + 1
        pos += 12 + blen
    return out, next_off, skipped


def _parse_batch_full(data: bytes) -> Tuple[
        int, Optional[List[Tuple[int, Optional[bytes], bytes]]]]:
    """One batch -> (lastOffsetDelta, records|None).  Records carry
    their own offset DELTAS (compacted topics have sparse deltas — a
    dense enumerate() would re-fetch the same batch forever).  None
    records = compressed/control batch (undecodable/marker)."""
    base_off, blen = struct.unpack_from("!qi", data, 0)
    epoch, magic, crc = struct.unpack_from("!iBI", data, 12)
    if magic != 2:
        raise KafkaError(f"unsupported magic {magic}")
    after = data[21:12 + blen]
    if crc32c(after) != crc:
        raise KafkaError("record batch crc mismatch")
    (attrs, last_delta, t0, t1, pid, peph, seq,
     n) = struct.unpack_from("!hiqqqhii", after, 0)
    codec = attrs & 0x07
    off = struct.calcsize("!hiqqqhii")
    if attrs & 0x20:                   # control batch: NEVER surface its
        return last_delta, None        # markers as data, any codec
    if codec in (1, 2, 3, 4):
        # the records section (everything after the fixed header) is
        # one compressed blob; CRC above already covered the compressed
        # form, so a decode failure here is a producer bug, not wire
        # damage — surface it
        try:
            if codec == 1:
                body = gzip.decompress(after[off:])
            elif codec == 2:
                body = _sz.decompress_xerial(after[off:])
            elif codec == 3:
                body = _lz4.decompress_frame(after[off:])
            else:
                # native decoder, or the full-format python fallback;
                # RuntimeError kept as a belt-and-braces skip for any
                # future unsupported-construct signal
                try:
                    body = _zs.decompress_frame(after[off:])
                except RuntimeError:
                    return last_delta, None
            after = after[:off] + body
        except (ValueError, OSError, EOFError, zlib.error) as e:
            # zlib.error/EOFError: corrupt/truncated deflate body — must
            # land in KafkaError or the ingress poll loop misclassifies
            # it and restarts into the same poisoned offset forever
            raise KafkaError(f"batch decompress failed (codec {codec}): {e}")
    elif codec:                        # codecs 5+: unknown/reserved
        return last_delta, None
    out: List[Tuple[int, Optional[bytes], bytes]] = []
    for _ in range(n):
        _, off = read_varint(after, off)               # record length
        off += 1                                       # attributes
        _, off = read_varint(after, off)               # ts delta
        delta, off = read_varint(after, off)           # offset delta
        klen, off = read_varint(after, off)
        key = None
        if klen >= 0:
            key = after[off:off + klen]
            off += klen
        vlen, off = read_varint(after, off)
        if vlen >= 0:
            val = after[off:off + vlen]
            off += vlen
        else:
            val = b""              # tombstone (null value) — a negative
                                   # slice would rewind the cursor and
                                   # corrupt every following record
        nh, off = read_varint(after, off)
        for _ in range(nh):                            # skip headers
            hk, off = read_varint(after, off)
            off += hk
            hv, off = read_varint(after, off)
            off += max(0, hv)
        out.append((delta, key, val))
    return last_delta, out


def parse_record_batch(data: bytes) -> List[Tuple[Optional[bytes], bytes]]:
    """Decode one batch (test servers + loopback verification); checks
    the CRC."""
    _, recs = _parse_batch_full(data)
    if recs is None:
        raise KafkaError("compressed/control batch")
    return [(k, v) for _, k, v in recs]


RETRIABLE_ERRORS = {5, 6, 7, 9, 19}  # leader/broker transitions, timeouts


class KafkaClient(LazyTcpClient):
    """One async connection to a bootstrap broker: Metadata + Produce."""

    def __init__(self, server: str = "127.0.0.1:9092", *,
                 client_id: str = "emqx_tpu", timeout: float = 5.0) -> None:
        super().__init__(server, 9092, timeout)
        self.client_id = client_id
        self._corr = 0

    async def _request(self, api_key: int, api_version: int,
                       body: bytes, expect_response: bool = True) -> bytes:
        return await self._guarded(
            lambda: self._request_locked(api_key, api_version, body,
                                         expect_response))

    async def _request_locked(self, api_key, api_version, body,
                              expect_response=True):
        self._corr += 1
        head = (struct.pack("!hhi", api_key, api_version, self._corr)
                + _str(self.client_id))
        msg = head + body
        self._writer.write(struct.pack("!i", len(msg)) + msg)
        await self._writer.drain()
        if not expect_response:     # acks=0: Kafka sends NO response
            return b""
        (ln,) = struct.unpack("!i", await self._reader.readexactly(4))
        payload = await self._reader.readexactly(ln)
        (corr,) = struct.unpack_from("!i", payload, 0)
        if corr != self._corr:
            raise KafkaError(f"correlation mismatch {corr}!={self._corr}")
        return payload[4:]

    # -- Metadata v1 --------------------------------------------------------

    async def partitions(self, topic: str) -> int:
        body = struct.pack("!i", 1) + _str(topic)
        p = await self._request(3, 1, body)
        off = 0
        (nb,) = struct.unpack_from("!i", p, off)
        off += 4
        for _ in range(nb):                            # brokers
            off += 4                                   # node_id
            (sl,) = struct.unpack_from("!h", p, off)
            off += 2 + sl + 4                          # host, port
            (rl,) = struct.unpack_from("!h", p, off)   # rack
            off += 2 + max(0, rl)
        off += 4                                       # controller id
        (nt,) = struct.unpack_from("!i", p, off)
        off += 4
        for _ in range(nt):
            (err,) = struct.unpack_from("!h", p, off)
            off += 2
            (sl,) = struct.unpack_from("!h", p, off)
            off += 2
            name = p[off:off + sl].decode()
            off += sl
            off += 1                                   # is_internal
            (np_,) = struct.unpack_from("!i", p, off)
            off += 4
            if name == topic:
                if err not in (0, 5):                  # 5: leader election
                    raise KafkaError(f"metadata error {err} for {topic}")
                return max(1, np_)
            for _ in range(np_):                       # skip partitions
                off += 2 + 4 + 4                       # err, id, leader
                (nr,) = struct.unpack_from("!i", p, off)
                off += 4 + 4 * nr
                (ni,) = struct.unpack_from("!i", p, off)
                off += 4 + 4 * ni
        raise KafkaError(f"topic {topic} not in metadata")

    # -- Produce v3 ---------------------------------------------------------

    async def produce(self, topic: str, partition: int,
                      records: List[Tuple[Optional[bytes], bytes]],
                      acks: int = 1,
                      compression: Optional[str] = None) -> int:
        """Send one batch; returns the base offset assigned (-1 for
        acks=0, which Kafka leaves unanswered on the wire)."""
        if sum(len(v) + len(k or b"") for k, v in records) > 65536:
            # without the native codec the CRC-32C is a per-byte Python
            # loop; keep big batches off the event loop either way
            # (broker keepalives run there)
            batch = await asyncio.to_thread(
                record_batch, records, None, 0, compression)
        else:
            batch = record_batch(records, compression=compression)
        body = (_str(None)                             # transactional_id
                + struct.pack("!hi", acks, int(self.timeout * 1e3))
                + struct.pack("!i", 1) + _str(topic)
                + struct.pack("!i", 1)
                + struct.pack("!i", partition) + _bytes(batch))
        p = await self._request(0, 3, body, expect_response=acks != 0)
        if acks == 0:
            return -1
        off = 0
        (nt,) = struct.unpack_from("!i", p, off)
        off += 4
        for _ in range(nt):
            (sl,) = struct.unpack_from("!h", p, off)
            off += 2 + sl
            (np_,) = struct.unpack_from("!i", p, off)
            off += 4
            for _ in range(np_):
                pid, err, base = struct.unpack_from("!ihq", p, off)
                off += 4 + 2 + 8 + 8                   # + log_append_time
                if err:
                    raise SendError(
                        f"kafka produce error {err} on {topic}/{pid}",
                        retryable=err in RETRIABLE_ERRORS)
                return base
        raise KafkaError("empty produce response")

    # -- ListOffsets v1 -----------------------------------------------------

    async def list_offset(self, topic: str, partition: int,
                          at: int = -1) -> int:
        """-1 = latest, -2 = earliest (the Kafka sentinel timestamps)."""
        body = (struct.pack("!i", -1)                  # replica_id
                + struct.pack("!i", 1) + _str(topic)
                + struct.pack("!i", 1)
                + struct.pack("!iq", partition, at))
        p = await self._request(2, 1, body)
        off = 4                                        # topic array len
        (sl,) = struct.unpack_from("!h", p, off)
        off += 2 + sl + 4                              # name + part count
        pid, err, ts, offset = struct.unpack_from("!ihqq", p, off)
        if err:
            raise KafkaError(f"list_offsets error {err}")
        return offset

    # -- Fetch v4 -----------------------------------------------------------

    async def fetch(self, topic: str, partition: int, offset: int,
                    max_wait_ms: int = 500, max_bytes: int = 1 << 20
                    ) -> Tuple[List[Tuple[int, Optional[bytes], bytes]],
                               int]:
        """-> ([(offset, key, value)], next_offset)."""
        body = (struct.pack("!iiiiB", -1, max_wait_ms, 1, max_bytes, 0)
                + struct.pack("!i", 1) + _str(topic)
                + struct.pack("!i", 1)
                + struct.pack("!iqi", partition, offset, max_bytes))
        p = await self._request(1, 4, body)
        off = 4                                        # throttle
        off += 4                                       # topic array len
        (sl,) = struct.unpack_from("!h", p, off)
        off += 2 + sl + 4                              # name + part count
        pid, err, hwm, lso = struct.unpack_from("!ihqq", p, off)
        off += 4 + 2 + 8 + 8
        (n_aborted,) = struct.unpack_from("!i", p, off)
        off += 4 + max(0, n_aborted) * 16
        (rlen,) = struct.unpack_from("!i", p, off)
        off += 4
        if err:
            e = KafkaError(f"fetch error {err} on {topic}/{pid}")
            e.code = err
            raise e
        if rlen <= 0:
            return [], offset
        records, next_off, skipped = parse_batches(p[off:off + rlen])
        if skipped:
            log.warning(
                "fetch %s/%d: skipped %d batch(es) — control marker, "
                "reserved codec, or zstd without the native decoder",
                topic, pid, skipped)
        # batches can start before the requested offset (compaction);
        # drop the leading overlap
        records = [(o, k, v) for o, k, v in records if o >= offset]
        return records, max(next_off, offset)


def render_kafka(conf: Dict[str, Any], output: Dict[str, Any],
                 columns: Dict[str, Any]) -> Dict[str, Any]:
    """Rule output -> one Kafka item: templated key/value, optional
    explicit partition.  Templates go through the rule engine's shared
    ``render_template`` (single-scan, missing fields render empty,
    dotted paths) — a hand-rolled replace loop would re-scan substituted
    payload bytes and let clients inject other fields' placeholders."""
    from ..rule_engine.runtime import render_template

    key_tpl = conf.get("key_template", "${clientid}")
    val_tpl = conf.get("value_template")
    if val_tpl:
        value = render_template(val_tpl, output, columns).encode()
    else:
        payload = output.get("payload", columns.get("payload", b""))
        value = payload if isinstance(payload, bytes) else \
            str(payload).encode()
    key = render_template(key_tpl, output, columns).encode() or None
    item = {"key": key, "value": value}
    if "partition" in conf:
        item["partition"] = int(conf["partition"])
    return item


class KafkaConnector(Connector):
    """Buffered-worker connector: batches items into record batches.

    ``conf["ingress"]`` turns on the consumer side (the
    emqx_bridge_kafka_consumer analog): ``{topic?, partitions?: [..],
    start: "latest"|"earliest", local_topic, payload?, local_qos?,
    poll_interval?}`` — fetched records republish through
    ``local_publish``.  Plain Fetch (no consumer-group coordination: one
    broker node owns the bridge; cluster takeover restarts it)."""

    def __init__(self, conf: Dict[str, Any], name: str = "",
                 local_publish: Optional[Any] = None) -> None:
        self.conf = conf
        self.name = name
        self.local_publish = local_publish
        self.topic = conf.get("topic", "emqx")
        self.acks = int(conf.get("acks", 1))
        self.compression = conf.get("compression") or None
        if self.compression not in _CODEC_BITS:
            raise ValueError(
                f"kafka bridge {name}: unsupported compression "
                f"{self.compression!r} (snappy/lz4/gzip/none)")
        self.client = KafkaClient(
            conf.get("server", "127.0.0.1:9092"),
            client_id=conf.get("client_id", f"emqx_tpu:{name}"),
            timeout=float(conf.get("timeout", 5.0)))
        self.n_partitions = 1
        self._rr = 0
        self._poll_task: Optional[asyncio.Task] = None
        self.consumed = 0
        self.offsets: Dict[int, int] = {}

    async def start(self) -> None:
        ing = self.conf.get("ingress")
        try:
            self.n_partitions = await self.client.partitions(self.topic)
        except KafkaError:
            # ingress-only bridges may not have (or need) the egress
            # topic; the consumer must still start
            if not ing:
                raise
            log.warning("kafka bridge %s: egress topic %r has no "
                        "metadata (ingress continues)", self.name,
                        self.topic)
        if ing and self.local_publish is not None \
                and self._poll_task is None:
            # transient supervised child when the owning BufferedWorker
            # runs under a node supervision tree: a poll loop that dies
            # past its own backoff restarts instead of silently
            # stopping ingress; clean return (stop) ends supervision
            sup = self.supervisor
            if sup is not None:
                self._poll_task = sup.start_child(
                    f"bridge.kafka.{self.name}.poll",
                    lambda: self._poll_forever(ing), restart="transient")
            else:
                self._poll_task = asyncio.create_task(
                    self._poll_forever(ing))

    async def stop(self) -> None:
        if self._poll_task is not None:
            self._poll_task.cancel()
            try:
                await self._poll_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                log.debug("kafka ingress %s poll task exit", self.name,
                          exc_info=True)
            self._poll_task = None
        await self.client.close()

    # -- consumer side ------------------------------------------------------

    async def _poll_forever(self, ing: Dict[str, Any]) -> None:
        """Supervisor: the poll loop must survive broker restarts,
        half-closed sockets (IncompleteReadError) and startup races —
        any death restarts it with backoff.  (The producer-side
        health() does not cover this task.)"""
        backoff = 0.5
        while True:
            try:
                await self._poll_loop(ing)
                return                       # only via CancelledError
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                log.warning("kafka ingress %s loop died (%s: %s); "
                            "restarting in %.1fs", self.name,
                            type(e).__name__, e, backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

    async def _poll_loop(self, ing: Dict[str, Any]) -> None:
        from ..rule_engine.runtime import render_template

        # a dedicated connection: fetch long-polls (max_wait) must not
        # block the producer's requests behind the per-client lock
        consumer = KafkaClient(
            self.conf.get("server", "127.0.0.1:9092"),
            client_id=f"emqx_tpu:{self.name}:consumer",
            timeout=float(self.conf.get("timeout", 5.0)))
        topic = ing.get("topic", self.topic)
        interval = float(ing.get("poll_interval", 0.2))
        start = str(ing.get("start", "latest"))
        at = -2 if start == "earliest" else -1
        try:
            nparts = await consumer.partitions(topic)
            parts = [int(p) for p in ing.get(
                "partitions", range(nparts))]
            for p in parts:
                if p not in self.offsets:
                    self.offsets[p] = await consumer.list_offset(
                        topic, p, at)
            while True:
                got = 0
                for p in parts:
                    try:
                        records, nxt = await consumer.fetch(
                            topic, p, self.offsets[p])
                    except KafkaError as e:
                        if getattr(e, "code", None) == 1:
                            # OFFSET_OUT_OF_RANGE: retention deleted our
                            # position — re-seek (auto.offset.reset)
                            self.offsets[p] = await consumer.list_offset(
                                topic, p, at)
                            log.warning(
                                "kafka ingress %s: offset out of range "
                                "on %s/%d; reset to %d", self.name,
                                topic, p, self.offsets[p])
                            continue
                        log.warning("kafka ingress %s fetch: %s",
                                    self.name, e)
                        await asyncio.sleep(interval)
                        continue
                    except (OSError, EOFError,
                            asyncio.TimeoutError) as e:
                        log.warning("kafka ingress %s fetch: %s",
                                    self.name, e)
                        await asyncio.sleep(interval)
                        continue
                    for o, k, v in records:
                        cols = {"topic": topic, "partition": p,
                                "offset": o,
                                "key": (k or b"").decode("utf-8",
                                                         "replace"),
                                "value": v}
                        ltopic = render_template(
                            ing.get("local_topic",
                                    "kafka/${topic}/${partition}"),
                            cols, cols)
                        payload_t = ing.get("payload")
                        payload = (render_template(
                            payload_t, cols, cols).encode()
                            if payload_t else v)
                        try:
                            self.local_publish(
                                ltopic, payload,
                                qos=int(ing.get("local_qos", 0)))
                            self.consumed += 1
                        except Exception:
                            log.exception("kafka ingress %s publish",
                                          self.name)
                    got += len(records)
                    self.offsets[p] = nxt
                if not got:
                    await asyncio.sleep(interval)
        finally:
            # errors propagate to _poll_forever, which restarts us
            await consumer.close()

    async def health(self) -> bool:
        try:
            self.n_partitions = await self.client.partitions(self.topic)
            return True
        except Exception:
            return False

    def _partition_of(self, item: Dict[str, Any]) -> int:
        if "partition" in item:
            return int(item["partition"]) % self.n_partitions
        key = item.get("key")
        if key:
            return crc32c(key) % self.n_partitions
        self._rr += 1
        return self._rr % self.n_partitions

    async def send(self, items: List[Dict[str, Any]]) -> Optional[int]:
        """Returns the REJECTED count per the Connector contract (0 —
        Kafka acks a batch wholesale; errors raise SendError carrying
        the undelivered items, so partitions acked before a failure are
        never re-produced)."""
        by_part: Dict[int, List[Dict[str, Any]]] = {}
        for it in items:
            by_part.setdefault(self._partition_of(it), []).append(it)
        pending = dict(by_part)
        for part, group in by_part.items():
            try:
                await self.client.produce(
                    self.topic, part,
                    [(it.get("key"), it["value"]) for it in group],
                    acks=self.acks, compression=self.compression)
            except SendError as e:
                remaining = [it for g in pending.values() for it in g]
                raise SendError(str(e), retryable=e.retryable,
                                remaining=remaining) from e
            except Exception as e:
                remaining = [it for g in pending.values() for it in g]
                raise SendError(str(e), retryable=True,
                                remaining=remaining) from e
            del pending[part]
        return 0
