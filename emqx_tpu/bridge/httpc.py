"""Minimal asyncio HTTP/1.1 client for egress boundaries.

Used by the webhook bridge and the HTTP authn/authz backends.  The
environment pins the dependency set (no aiohttp/httpx), and the broker
needs only simple request/response semantics: one request per call,
`Content-Length` or close-delimited bodies, no TLS verification knobs
beyond an optional ssl context.

Behavioral reference: the reference reaches HTTP services through its
pooled ehttpc client (`apps/emqx_connector/src/emqx_connector_http.erl`
[U]); pooling here is a per-call connection — webhook/auth throughput on
the broker control path does not justify a pool manager, and the
buffered bridge worker batches above this layer anyway.
"""

from __future__ import annotations

import asyncio
import logging
import ssl as ssl_mod
from typing import Dict, Optional, Tuple
from urllib.parse import urlsplit

log = logging.getLogger(__name__)

__all__ = ["HttpResponse", "request", "HttpError"]

_MAX_BODY = 8 * 1024 * 1024
_MAX_HEADER = 64 * 1024


class HttpError(Exception):
    pass


class HttpResponse:
    def __init__(self, status: int, headers: Dict[str, str], body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    def __repr__(self) -> str:  # pragma: no cover
        return f"<HttpResponse {self.status} {len(self.body)}B>"


def _parse_url(url: str) -> Tuple[str, str, int, str, bool]:
    u = urlsplit(url)
    if u.scheme not in ("http", "https"):
        raise HttpError(f"unsupported scheme {u.scheme!r}")
    tls = u.scheme == "https"
    host = u.hostname or "localhost"
    port = u.port or (443 if tls else 80)
    path = u.path or "/"
    if u.query:
        path += "?" + u.query
    return u.scheme, host, port, path, tls


def _clean(s: str) -> str:
    """Strip CR/LF/NUL from header material: values are routinely rendered
    from message-derived templates (topic/payload may legally contain
    control bytes), and raw interpolation would be header injection."""
    return s.replace("\r", "").replace("\n", "").replace("\x00", "")


async def request(
    method: str,
    url: str,
    *,
    headers: Optional[Dict[str, str]] = None,
    body: bytes = b"",
    timeout: float = 5.0,
    ssl: Optional[ssl_mod.SSLContext] = None,
    verify: bool = True,
) -> HttpResponse:
    """One HTTP/1.1 request.  Raises HttpError on malformed responses,
    asyncio.TimeoutError past the deadline, OSError on connect failure.
    HTTPS verifies certificates by default; ``verify=False`` (or a custom
    ``ssl`` context) opts out for self-signed test endpoints."""
    _, host, port, path, tls = _parse_url(url)
    if tls and ssl is None:
        ssl = ssl_mod.create_default_context()
        if not verify:
            ssl.check_hostname = False
            ssl.verify_mode = ssl_mod.CERT_NONE

    async def _go() -> HttpResponse:
        reader, writer = await asyncio.open_connection(
            host, port, ssl=ssl if tls else None
        )
        try:
            hdrs = {
                "host": f"{host}:{port}",
                "connection": "close",
                "content-length": str(len(body)),
            }
            for k, v in (headers or {}).items():
                hdrs[_clean(k.lower())] = _clean(v)
            head = f"{_clean(method.upper())} {_clean(path)} HTTP/1.1\r\n" + "".join(
                f"{k}: {v}\r\n" for k, v in hdrs.items()
            )
            # utf-8, not latin-1: header values are rendered from
            # message-derived templates and may carry any code point; a
            # codec error here would poison the bridge's retry loop
            writer.write(head.encode("utf-8") + b"\r\n" + body)
            await writer.drain()

            status_line = await reader.readline()
            parts = status_line.decode("latin-1", "replace").split(None, 2)
            if len(parts) < 2 or not parts[1].isdigit():
                raise HttpError(f"bad status line {status_line!r}")
            status = int(parts[1])
            resp_headers: Dict[str, str] = {}
            total = 0
            while True:
                line = await reader.readline()
                total += len(line)
                if total > _MAX_HEADER:
                    raise HttpError("header block too large")
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin-1", "replace").partition(":")
                resp_headers[k.strip().lower()] = v.strip()

            te = resp_headers.get("transfer-encoding", "").lower()
            if "chunked" in te:
                chunks = []
                got = 0
                while True:
                    size_line = await reader.readline()
                    try:
                        size = int(size_line.strip().split(b";")[0], 16)
                    except ValueError:
                        raise HttpError(f"bad chunk size {size_line!r}")
                    if size == 0:
                        await reader.readline()  # trailing CRLF
                        break
                    got += size
                    if got > _MAX_BODY:
                        raise HttpError("body too large")
                    chunks.append(await reader.readexactly(size))
                    await reader.readexactly(2)  # CRLF
                data = b"".join(chunks)
            elif "content-length" in resp_headers:
                n = int(resp_headers["content-length"])
                if n > _MAX_BODY:
                    raise HttpError("body too large")
                data = await reader.readexactly(n)
            else:
                # close-delimited body: read() returns per-segment, so
                # loop to EOF (or the size cap) to avoid truncation
                chunks = []
                got = 0
                while got < _MAX_BODY:
                    part = await reader.read(_MAX_BODY - got)
                    if not part:
                        break
                    chunks.append(part)
                    got += len(part)
                data = b"".join(chunks)
            return HttpResponse(status, resp_headers, data)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                log.debug("http connection close failed", exc_info=True)

    return await asyncio.wait_for(_go(), timeout)
