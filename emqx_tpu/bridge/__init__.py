"""Data bridges: buffered egress/ingress to external systems.

The emqx bridge/connector/resource family (SURVEY.md §2.3) rebuilt on
asyncio: :mod:`resource` is the buffered-worker backbone,
:mod:`mqtt_bridge`, :mod:`webhook` and :mod:`kafka` (wire-protocol
producer) are the connectors, :mod:`manager` wires bridges into rules
and REST.
"""

from .db import (
    InfluxBridgeConnector, MongoBridgeConnector, PostgresBridgeConnector,
    RedisBridgeConnector,
)
from .kafka import KafkaConnector, crc32c, render_kafka
from .manager import Bridge, BridgeManager
from .resource import BufferedWorker, Connector, SendError

__all__ = [
    "Bridge", "BridgeManager", "BufferedWorker", "Connector", "SendError",
    "KafkaConnector", "crc32c", "render_kafka",
    "RedisBridgeConnector", "PostgresBridgeConnector",
    "MongoBridgeConnector", "InfluxBridgeConnector",
]
