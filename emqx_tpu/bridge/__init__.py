"""Data bridges: buffered egress/ingress to external systems.

The emqx bridge/connector/resource family (SURVEY.md §2.3) rebuilt on
asyncio: :mod:`resource` is the buffered-worker backbone,
:mod:`mqtt_bridge` and :mod:`webhook` are the first two connectors,
:mod:`manager` wires bridges into rules and REST.
"""

from .manager import Bridge, BridgeManager
from .resource import BufferedWorker, Connector, SendError

__all__ = [
    "Bridge", "BridgeManager", "BufferedWorker", "Connector", "SendError",
]
