"""Buffered resource workers: the egress backbone of every data bridge.

Behavioral reference: ``apps/emqx_resource`` [U] (SURVEY.md §2.3) — each
bridge owns a buffer worker that absorbs bursts, batches egress, retries
with backoff while the remote is down, and exposes health + metrics.
The reference runs a pool of buffer workers per resource; here one
asyncio worker per resource suffices (no scheduler contention to spread;
the event loop interleaves).

Delivery semantics: at-least-once into the remote while the buffer
holds; oldest messages drop first on overflow (``max_queue``), and
expired messages (``ttl``) drop at dequeue — both counted, mirroring the
reference's ``dropped.queue_full`` / ``dropped.expired`` metrics.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Awaitable, Callable, Deque, Dict, List, Optional, Tuple

from .. import faultinject as _fi

log = logging.getLogger(__name__)

__all__ = ["Connector", "SendError", "BufferedWorker"]


class SendError(Exception):
    """Raised by a connector when a send fails mid-batch.

    ``done`` = leading items fully PROCESSED (delivered or permanently
    rejected) — the worker never re-sends them; ``rejected`` = how many
    of those processed items were permanent rejects (counted failed, the
    rest success).  ``retryable=True`` requeues ``batch[done:]`` for
    redelivery; ``False`` drops it (counted failed).

    ``remaining`` (optional) replaces the ``done`` prefix with an
    EXPLICIT undelivered-item list (identity-matched) for connectors
    that process a batch out of order — e.g. Kafka's per-partition
    regrouping, where a later partition can fail after an earlier one
    was acked and a prefix count would requeue already-delivered
    records."""

    def __init__(self, msg: str, retryable: bool = True, done: int = 0,
                 rejected: int = 0, remaining: Optional[List[Any]] = None):
        super().__init__(msg)
        self.retryable = retryable
        self.done = done
        self.rejected = rejected
        self.remaining = remaining


class Connector:
    """Connector contract: owns the remote connection.

    Lifecycle: ``start`` → (``send`` | ``health``)* → ``stop``.  ``send``
    raises :class:`SendError` (or any exception, treated retryable) on
    failure; the worker handles backoff and re-delivery.

    ``supervisor`` is injected by :meth:`BufferedWorker.start` before
    ``start()`` runs; connectors owning long-lived loops register them
    there (kafka ingress poll) instead of spawning raw tasks.
    """

    supervisor: Optional[Any] = None

    async def start(self) -> None:  # pragma: no cover - interface
        pass

    async def stop(self) -> None:  # pragma: no cover - interface
        pass

    async def health(self) -> bool:
        return True

    async def send(self, items: List[Any]) -> Optional[int]:  # pragma: no cover
        """Deliver ``items`` in order.  Return the count of permanently-
        rejected items (None/0 = all delivered); raise :class:`SendError`
        on an interrupting failure."""
        raise NotImplementedError


class BufferedWorker:
    """One buffering/retry/health loop wrapped around a Connector."""

    def __init__(
        self,
        connector: Connector,
        *,
        name: str = "resource",
        max_queue: int = 10_000,
        batch_size: int = 32,
        ttl: Optional[float] = None,
        retry_base: float = 0.05,
        retry_max: float = 5.0,
        max_retries: Optional[int] = None,
        health_interval: float = 5.0,
    ) -> None:
        self.connector = connector
        self.name = name
        self.max_queue = max_queue
        self.batch_size = batch_size
        self.ttl = ttl
        self.retry_base = retry_base
        self.retry_max = retry_max
        self.max_retries = max_retries
        self.health_interval = health_interval

        self.status = "stopped"  # stopped|connecting|connected|disconnected
        # set by BridgeManager when the node carries a supervision tree:
        # worker/health loops then run as supervised children
        self.supervisor: Optional[Any] = None
        self.metrics: Dict[str, int] = {
            "matched": 0, "success": 0, "failed": 0, "retried": 0,
            "dropped": 0, "dropped.queue_full": 0, "dropped.expired": 0,
        }
        self._q: Deque[Tuple[float, Any]] = deque()
        self._wakeup = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        self._stopping = False

    # -- producer side -----------------------------------------------------

    def enqueue(self, item: Any) -> bool:
        """Queue one item for egress; drops the OLDEST on overflow so the
        buffer always holds the freshest window (reference drop policy)."""
        self.metrics["matched"] += 1
        if len(self._q) >= self.max_queue:
            self._q.popleft()
            self.metrics["dropped"] += 1
            self.metrics["dropped.queue_full"] += 1
        self._q.append((time.monotonic(), item))
        self._wakeup.set()
        return True

    @property
    def queuing(self) -> int:
        return len(self._q)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._tasks:
            return
        self._stopping = False
        self.status = "connecting"
        # connectors with their own long-lived loops (kafka ingress
        # poll) register them as supervised children too
        self.connector.supervisor = self.supervisor
        try:
            await self.connector.start()
            self.status = "connected"
        except Exception as e:
            log.warning("resource %s connect failed: %s", self.name, e)
            self.status = "disconnected"
        if self.supervisor is not None:
            self._tasks = [
                self.supervisor.start_child(
                    f"bridge.{self.name}", self._run),
                self.supervisor.start_child(
                    f"bridge.{self.name}.health", self._health_loop),
            ]
        else:
            self._tasks = [
                asyncio.create_task(self._run(), name=f"bridge-{self.name}"),
                asyncio.create_task(
                    self._health_loop(), name=f"bridge-{self.name}-health"
                ),
            ]

    async def stop(self) -> None:
        self._stopping = True
        self._wakeup.set()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                log.debug("resource %s worker exit", self.name,
                          exc_info=True)
        self._tasks = []
        try:
            await self.connector.stop()
        except Exception:
            log.debug("resource %s connector stop failed", self.name,
                      exc_info=True)
        self.status = "stopped"

    # -- worker loop -------------------------------------------------------

    def _take_batch(self) -> List[Tuple[float, Any]]:
        now = time.monotonic()
        batch: List[Tuple[float, Any]] = []
        while self._q and len(batch) < self.batch_size:
            ts, item = self._q[0]
            if self.ttl is not None and now - ts > self.ttl:
                self._q.popleft()
                self.metrics["dropped"] += 1
                self.metrics["dropped.expired"] += 1
                continue
            self._q.popleft()
            batch.append((ts, item))
        return batch

    def _requeue(self, batch: List[Tuple[float, Any]]) -> None:
        # failed batch returns to the FRONT (order-preserving redelivery)
        # with ORIGINAL enqueue stamps, so the ttl clock keeps running
        # across retries and old messages still expire while the remote
        # is down
        for entry in reversed(batch):
            self._q.appendleft(entry)

    async def _run(self) -> None:
        backoff = self.retry_base
        retries = 0
        while not self._stopping:
            if not self._q:
                self._wakeup.clear()
                await self._wakeup.wait()
                continue
            batch = self._take_batch()
            if not batch:
                continue
            try:
                try:
                    if _fi._injector is not None:
                        # chaos seam: a raised sink fault rides the
                        # normal retryable-SendError path (backoff +
                        # front-requeue); a delay simulates a slow
                        # remote
                        act = _fi._injector.act("bridge.sink")
                        if act == "raise":
                            raise SendError(
                                "injected fault: bridge.sink",
                                retryable=True)
                        if act == "delay":
                            await _fi._injector.pause()
                    rejected = await self.connector.send(
                        [item for _, item in batch]
                    ) or 0
                except asyncio.CancelledError:
                    # shutdown/update mid-send: the in-flight batch goes
                    # back to the buffer so a queue migration sees it
                    self._requeue(batch)
                    raise
                self.metrics["success"] += len(batch) - rejected
                self.metrics["failed"] += rejected
                backoff = self.retry_base
                retries = 0
                if self.status != "connected":
                    self.status = "connected"
            except asyncio.CancelledError:
                raise
            except Exception as e:
                retryable = getattr(e, "retryable", True)
                remaining = getattr(e, "remaining", None)
                if remaining is not None:
                    keep = {id(it) for it in remaining}
                    delivered = len(batch) - len(keep)
                    rej = min(getattr(e, "rejected", 0), delivered)
                    self.metrics["success"] += delivered - rej
                    self.metrics["failed"] += rej
                    batch = [bi for bi in batch if id(bi[1]) in keep]
                else:
                    done = min(getattr(e, "done", 0), len(batch))
                    rej = min(getattr(e, "rejected", 0), done)
                    if done:
                        self.metrics["success"] += done - rej
                        self.metrics["failed"] += rej
                        batch = batch[done:]
                if retryable and (
                    self.max_retries is None or retries < self.max_retries
                ):
                    self._requeue(batch)
                    self.metrics["retried"] += len(batch)
                    retries += 1
                    self.status = "disconnected"
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, self.retry_max)
                else:
                    self.metrics["failed"] += len(batch)
                    retries = 0
                    log.warning(
                        "resource %s dropped batch of %d: %s",
                        self.name, len(batch), e,
                    )

    async def _health_loop(self) -> None:
        while not self._stopping:
            await asyncio.sleep(self.health_interval)
            try:
                ok = await self.connector.health()
            except Exception:
                ok = False
            if ok:
                if self.status == "disconnected":
                    self.status = "connected"
            else:
                if self.status == "connected":
                    self.status = "disconnected"
                # nudge a reconnect; connectors make start() idempotent
                try:
                    await self.connector.start()
                except Exception:
                    log.debug("resource %s reconnect attempt failed",
                              self.name, exc_info=True)

    def info(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "status": self.status,
            "queuing": self.queuing,
            "metrics": dict(self.metrics),
        }
