"""HTTP webhook bridge: rule/event egress to an HTTP endpoint.

Behavioral reference: ``apps/emqx_bridge_http`` [U] (SURVEY.md §2.3) —
each forwarded event renders url/headers/body templates and issues one
HTTP request; 2xx is success, 429/5xx and transport errors are
retryable, other 4xx drop the item (the request is wrong, retrying
can't fix it).
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, List

from ..rule_engine.runtime import render_template
from . import httpc
from .resource import Connector, SendError

log = logging.getLogger(__name__)

__all__ = ["WebhookConnector", "render_webhook"]


def render_webhook(
    conf: Dict[str, Any], output: Dict[str, Any], columns: Dict[str, Any]
) -> Dict[str, Any]:
    """Render one webhook request from rule output + event columns."""
    body_tpl = conf.get("body")
    if body_tpl:
        body = render_template(body_tpl, output, columns).encode()
    else:
        def enc(v: Any) -> Any:
            if isinstance(v, bytes):
                return v.decode("utf-8", "replace")
            return v
        body = json.dumps(
            {k: enc(v) for k, v in output.items()}, default=str
        ).encode()
    headers = {
        k: render_template(str(v), output, columns)
        for k, v in (conf.get("headers") or {}).items()
    }
    headers.setdefault("content-type", "application/json")
    return {
        "url": render_template(conf.get("url", ""), output, columns),
        "method": conf.get("method", "POST"),
        "headers": headers,
        "body": body,
    }


class WebhookConnector(Connector):
    def __init__(self, conf: Dict[str, Any], name: str = "webhook") -> None:
        self.conf = conf
        self.name = name

    async def health(self) -> bool:
        # a webhook has no session to probe; health is per-request
        return True

    async def send(self, items: List[Dict[str, Any]]) -> int:
        """Per-item delivery.  Transport errors and 5xx/429 raise
        retryable SendError with exact positional accounting (``done`` =
        items processed, ``rejected`` = permanent rejects among them) so
        the worker resumes from the failed item; other 4xx reject only
        THAT item (the request itself is wrong — retrying can't fix it)
        and the rest of the batch is still attempted.  Returns the total
        reject count when the batch completes."""
        timeout = float(self.conf.get("request_timeout", 5.0))
        verify = bool(self.conf.get("ssl_verify", True))
        rejected = 0
        for i, it in enumerate(items):
            try:
                resp = await httpc.request(
                    it.get("method", "POST"),
                    it["url"],
                    headers=it.get("headers"),
                    body=it.get("body", b""),
                    timeout=timeout,
                    verify=verify,
                )
            except (OSError, httpc.HttpError, TimeoutError) as e:
                raise SendError(f"webhook request failed: {e}",
                                done=i, rejected=rejected) from e
            if resp.status >= 500 or resp.status == 429:
                raise SendError(f"webhook HTTP {resp.status}",
                                done=i, rejected=rejected)
            if resp.status >= 300:
                log.warning("webhook %s rejected item: HTTP %d",
                            self.name, resp.status)
                rejected += 1
        return rejected
