"""MQTT data bridge: egress (local → remote broker) and ingress
(remote → local), over the repo's own MQTT client.

Behavioral reference: ``apps/emqx_bridge_mqtt`` [U] (SURVEY.md §2.3) —
a bridge holds one outbound MQTT connection; egress renders
topic/payload/qos templates per forwarded message; ingress subscribes on
the remote and republishes into the local broker with a topic mapping.
Reconnect/backoff/buffering live in the BufferedWorker around this
connector.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from ..client import Client, InboundMessage, MqttError
from ..rule_engine.runtime import render_template
from .resource import Connector, SendError

log = logging.getLogger(__name__)

__all__ = ["MqttConnector", "render_egress"]


def render_egress(
    conf: Dict[str, Any], output: Dict[str, Any], columns: Dict[str, Any]
) -> Dict[str, Any]:
    """Render one egress publish from rule output + event columns."""
    topic = render_template(conf.get("remote_topic", "${topic}"),
                            output, columns)
    payload = render_template(conf.get("payload", "${payload}"),
                              output, columns)
    return {
        "topic": topic,
        "payload": payload.encode() if isinstance(payload, str) else payload,
        "qos": int(conf.get("remote_qos", 0)),
        "retain": bool(conf.get("retain", False)),
    }


class MqttConnector(Connector):
    """One remote-broker MQTT connection shared by egress and ingress.

    ``conf`` keys: server ("host:port"), clientid, username, password,
    proto_ver, keepalive, ingress: {remote_topic, remote_qos, local_topic,
    local_qos, payload} — ingress messages republish into ``local_publish``.
    """

    def __init__(
        self,
        conf: Dict[str, Any],
        local_publish: Optional[Any] = None,
        name: str = "mqtt",
    ) -> None:
        self.conf = conf
        self.name = name
        self.local_publish = local_publish
        self.client: Optional[Client] = None
        self._lock = asyncio.Lock()

    def _make_client(self) -> Client:
        server = self.conf.get("server", "127.0.0.1:1883")
        host, _, port = server.partition(":")
        ingress = self.conf.get("ingress")
        on_message = self._on_ingress if ingress else None
        return Client(
            clientid=self.conf.get("clientid", f"bridge-{self.name}"),
            host=host or "127.0.0.1",
            port=int(port or 1883),
            proto_ver=int(self.conf.get("proto_ver", 4)),
            username=self.conf.get("username"),
            password=(
                self.conf["password"].encode()
                if isinstance(self.conf.get("password"), str)
                else self.conf.get("password")
            ),
            keepalive=int(self.conf.get("keepalive", 60)),
            clean_start=bool(self.conf.get("clean_start", True)),
            on_message=on_message,
        )

    async def start(self) -> None:
        async with self._lock:
            if self.client is not None and self.client.connected:
                return
            if self.client is not None:
                await self.client.close()
            self.client = self._make_client()
            await self.client.connect(timeout=float(
                self.conf.get("connect_timeout", 5.0)))
            ingress = self.conf.get("ingress")
            if ingress:
                await self.client.subscribe(
                    ingress.get("remote_topic", "#"),
                    qos=int(ingress.get("remote_qos", 0)),
                )

    async def stop(self) -> None:
        async with self._lock:
            if self.client is not None:
                await self.client.close()
                self.client = None

    async def health(self) -> bool:
        return self.client is not None and self.client.connected

    async def send(self, items: List[Dict[str, Any]]) -> None:
        cl = self.client
        if cl is None or not cl.connected:
            # the worker retries after start() succeeds via health loop —
            # but try an inline reconnect first so a bounced remote heals
            # on the next batch, not the next health tick
            try:
                await self.start()
                cl = self.client
            except Exception as e:
                raise SendError(f"mqtt bridge not connected: {e}") from e
        assert cl is not None
        for i, it in enumerate(items):
            try:
                await cl.publish(
                    it["topic"], it["payload"],
                    qos=it.get("qos", 0), retain=it.get("retain", False),
                )
            except (MqttError, OSError, asyncio.TimeoutError) as e:
                # delivered prefix stays delivered; worker resumes at i
                raise SendError(f"mqtt publish failed: {e}", done=i) from e

    # -- ingress -----------------------------------------------------------

    def _on_ingress(self, msg: InboundMessage) -> None:
        if self.local_publish is None:
            return
        ingress = self.conf.get("ingress") or {}
        cols = {
            "topic": msg.topic,
            "payload": msg.payload,
            "qos": msg.qos,
            "retain": msg.retain,
        }
        topic = render_template(
            ingress.get("local_topic", "${topic}"), cols, cols
        )
        payload_t = ingress.get("payload")
        payload = (
            render_template(payload_t, cols, cols).encode()
            if payload_t else msg.payload
        )
        try:
            self.local_publish(
                topic, payload,
                qos=int(ingress.get("local_qos", msg.qos)),
                retain=bool(ingress.get("retain", msg.retain)),
            )
        except Exception:
            log.exception("bridge %s ingress publish failed", self.name)
