"""Bridge registry: CRUD, lifecycle, rule-action resolution.

Behavioral reference: ``apps/emqx_bridge`` [U] (SURVEY.md §2.3) —
bridges are named ``<type>:<name>`` resources; rules reference them as
action strings; each bridge owns a buffered worker (emqx_resource
analog) and exposes status + metrics over REST.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

from .db import (
    InfluxBridgeConnector, MongoBridgeConnector, MysqlBridgeConnector,
    PostgresBridgeConnector, RedisBridgeConnector, render_influx,
    render_mongo, render_mysql, render_pg, render_redis,
)
from .kafka import KafkaConnector, render_kafka
from .mqtt_bridge import MqttConnector, render_egress
from .resource import BufferedWorker, Connector
from .webhook import WebhookConnector, render_webhook

log = logging.getLogger(__name__)

__all__ = ["Bridge", "BridgeManager"]

_SECRET_KEYS = ("password", "authorization", "secret", "token", "api_key")


def _redact(conf: Any) -> Any:
    """Deep-copy ``conf`` with credential-bearing values masked — the
    reference redacts sensitive bridge fields in every REST response."""
    if isinstance(conf, dict):
        return {
            k: ("******" if any(s in k.lower() for s in _SECRET_KEYS)
                else _redact(v))
            for k, v in conf.items()
        }
    if isinstance(conf, list):
        return [_redact(v) for v in conf]
    return conf


class Bridge:
    """One configured bridge: connector + buffered worker + renderer."""

    def __init__(
        self,
        btype: str,
        name: str,
        conf: Dict[str, Any],
        connector: Connector,
        renderer: Callable[[Dict, Dict, Dict], Dict[str, Any]],
    ) -> None:
        self.type = btype
        self.name = name
        self.conf = conf
        self.enable = bool(conf.get("enable", True))
        self.connector = connector
        self.renderer = renderer
        rconf = conf.get("resource_opts") or {}
        self.worker = BufferedWorker(
            connector,
            name=f"{btype}:{name}",
            max_queue=int(rconf.get("max_queue", 10_000)),
            batch_size=int(rconf.get("batch_size", 32)),
            ttl=rconf.get("ttl"),
            retry_base=float(rconf.get("retry_base", 0.05)),
            retry_max=float(rconf.get("retry_max", 5.0)),
            max_retries=rconf.get("max_retries"),
            health_interval=float(rconf.get("health_interval", 5.0)),
        )

    @property
    def id(self) -> str:
        return f"{self.type}:{self.name}"

    def forward(self, output: Dict[str, Any], columns: Dict[str, Any]) -> None:
        """Rule-action entry: render one egress item and buffer it."""
        if not self.enable:
            return
        self.worker.enqueue(self.renderer(self.conf, output, columns))

    def info(self) -> Dict[str, Any]:
        return {
            "type": self.type,
            "name": self.name,
            "enable": self.enable,
            "status": self.worker.status,
            "queuing": self.worker.queuing,
            "metrics": dict(self.worker.metrics),
            **_redact(self.conf),
        }


class BridgeManager:
    """All bridges of a node; resolves rule actions ``"<type>:<name>"``."""

    TYPES = ("mqtt", "webhook", "kafka", "redis", "pgsql", "mysql",
             "mongodb", "influxdb")

    def __init__(self, node: Any = None) -> None:
        self.node = node
        self.bridges: Dict[str, Bridge] = {}
        if node is not None and getattr(node, "rule_engine", None) is not None:
            node.rule_engine.bridge_resolver = self.resolve_action

    # -- construction ------------------------------------------------------

    def _build(self, btype: str, name: str, conf: Dict[str, Any]) -> Bridge:
        local_publish = None
        if self.node is not None:
            def local_publish(topic, payload, qos=0, retain=False):
                from ..broker.message import make_message

                self.node.broker.publish(make_message(
                    f"bridge:{name}", topic, payload,
                    qos=qos, retain=retain,
                ))
        if btype == "mqtt":
            conn = MqttConnector(conf, local_publish=local_publish, name=name)
            return Bridge(btype, name, conf, conn, render_egress)
        if btype == "webhook":
            return Bridge(btype, name, conf, WebhookConnector(conf, name),
                          render_webhook)
        if btype == "kafka":
            return Bridge(btype, name, conf,
                          KafkaConnector(conf, name,
                                         local_publish=local_publish),
                          render_kafka)
        if btype == "redis":
            return Bridge(btype, name, conf,
                          RedisBridgeConnector(conf, name), render_redis)
        if btype == "pgsql":
            return Bridge(btype, name, conf,
                          PostgresBridgeConnector(conf, name), render_pg)
        if btype == "mysql":
            return Bridge(btype, name, conf,
                          MysqlBridgeConnector(conf, name), render_mysql)
        if btype == "mongodb":
            return Bridge(btype, name, conf,
                          MongoBridgeConnector(conf, name), render_mongo)
        if btype == "influxdb":
            return Bridge(btype, name, conf,
                          InfluxBridgeConnector(conf, name), render_influx)
        raise ValueError(f"unknown bridge type {btype!r}")

    # -- CRUD --------------------------------------------------------------

    def register(self, btype: str, name: str, conf: Dict[str, Any]) -> Bridge:
        """Synchronous create without starting the worker: enqueue works
        immediately (the buffer is plain host state); the caller starts
        the worker when a loop is available.  Used by data import."""
        bid = f"{btype}:{name}"
        if bid in self.bridges:
            raise ValueError(f"bridge {bid} exists")
        br = self._build(btype, name, conf)
        br.worker.supervisor = getattr(self.node, "supervisor", None)
        self.bridges[bid] = br
        return br

    async def create(self, btype: str, name: str, conf: Dict[str, Any]) -> Bridge:
        br = self.register(btype, name, conf)
        if br.enable:
            await br.worker.start()
        return br

    async def update(self, bid: str, conf: Dict[str, Any]) -> Bridge:
        old = self.bridges[bid]
        btype, _, name = bid.partition(":")
        # build (and thereby validate) the replacement BEFORE touching the
        # running bridge: a bad conf leaves the old bridge untouched
        br = self._build(btype, name, conf)
        br.worker.supervisor = getattr(self.node, "supervisor", None)
        await old.worker.stop()
        # migrate the buffered backlog (original enqueue stamps) so an
        # update while the remote is down doesn't drop the window
        br.worker._q.extend(old.worker._q)
        old.worker._q.clear()
        self.bridges[bid] = br
        if br.enable:
            await br.worker.start()
        return br

    async def delete(self, bid: str) -> bool:
        br = self.bridges.pop(bid, None)
        if br is None:
            return False
        await br.worker.stop()
        return True

    async def set_enable(self, bid: str, enable: bool) -> None:
        br = self.bridges[bid]
        br.enable = enable
        br.conf["enable"] = enable
        if enable and br.worker.status == "stopped":
            await br.worker.start()
        elif not enable:
            await br.worker.stop()

    def get(self, bid: str) -> Optional[Bridge]:
        return self.bridges.get(bid)

    def list(self) -> List[Bridge]:
        return list(self.bridges.values())

    async def stop_all(self) -> None:
        for br in self.bridges.values():
            await br.worker.stop()

    # -- rule-engine boundary ----------------------------------------------

    def resolve_action(self, action: str) -> Optional[Callable]:
        """Map a rule action string ``"<type>:<name>"`` to a forwarder."""
        br = self.bridges.get(action)
        if br is None:
            return None
        return br.forward

    # -- persistence (data export/import) ----------------------------------

    def export_config(self) -> List[Dict[str, Any]]:
        """Serializable bridge set; the restore side lives in
        ``storage/backup.py`` (register-or-skip with deferred worker
        start — one restore path, not two)."""
        return [
            {"type": b.type, "name": b.name, "conf": dict(b.conf)}
            for b in self.bridges.values()
        ]
