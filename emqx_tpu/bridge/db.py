"""Database egress bridges: Redis, PostgreSQL, MongoDB, InfluxDB.

Behavioral reference: ``apps/emqx_bridge_redis``, ``emqx_bridge_pgsql``,
``emqx_bridge_mongodb``, ``emqx_bridge_influxdb`` [U] (SURVEY.md §2.3) —
rule output → buffered worker → templated write into the store.  Each
connector reuses the corresponding minimal wire client that the auth
backends / http layer already ship (RESP2, PG v3 extended query with
bind parameters, OP_MSG/BSON, HTTP line protocol) — one protocol
implementation per store, shared between auth and bridges.

Templating: ``${field}`` through the rule engine's shared
``render_template`` (single scan, dotted paths).  The PostgreSQL bridge
templates VALUES through **bind parameters**, never SQL splicing.
"""

from __future__ import annotations

import json
import logging
import re
from typing import Any, Dict, List, Optional

from .resource import Connector, SendError

log = logging.getLogger(__name__)

__all__ = [
    "RedisBridgeConnector", "render_redis",
    "PostgresBridgeConnector", "render_pg",
    "MysqlBridgeConnector", "render_mysql",
    "MongoBridgeConnector", "render_mongo",
    "InfluxBridgeConnector", "render_influx",
]


def _render(tpl: str, output: Dict[str, Any], columns: Dict[str, Any]):
    from ..rule_engine.runtime import render_template

    return render_template(tpl, output, columns)


# ---------------------------------------------------------------------------
# Redis: templated command, e.g. ["LPUSH", "q:${topic}", "${payload}"]
# ---------------------------------------------------------------------------

def render_redis(conf: Dict[str, Any], output: Dict[str, Any],
                 columns: Dict[str, Any]) -> Dict[str, Any]:
    cmd_tpl = conf.get("command", ["LPUSH", "emqx:${topic}", "${payload}"])
    return {"cmd": [_render(str(part), output, columns)
                    for part in cmd_tpl]}


class RedisBridgeConnector(Connector):
    def __init__(self, conf: Dict[str, Any], name: str = "") -> None:
        from ..auth.redis import RespClient

        self.client = RespClient(
            conf.get("server", "127.0.0.1:6379"),
            password=conf.get("password"),
            database=int(conf.get("database", 0)),
            timeout=float(conf.get("timeout", 5.0)))

    async def start(self) -> None:
        await self.client.cmd("PING")

    async def stop(self) -> None:
        await self.client.aclose()

    async def health(self) -> bool:
        try:
            return (await self.client.cmd("PING")) in ("PONG", b"PONG")
        except Exception:
            return False

    async def send(self, items: List[Dict[str, Any]]) -> Optional[int]:
        for i, it in enumerate(items):
            try:
                await self.client.cmd(*it["cmd"])
            except Exception as e:
                raise SendError(f"redis bridge: {e}", done=i) from e
        return 0


# ---------------------------------------------------------------------------
# PostgreSQL: INSERT with bind parameters
# ---------------------------------------------------------------------------

def render_pg(conf: Dict[str, Any], output: Dict[str, Any],
              columns: Dict[str, Any]) -> Dict[str, Any]:
    """Each parameter template renders per message; the SQL itself is
    static (compiled once with $1..$n placeholders)."""
    params = [
        _render(str(p), output, columns)
        for p in conf.get("parameters",
                          ["${clientid}", "${topic}", "${payload}"])
    ]
    return {"params": params}


class PostgresBridgeConnector(Connector):
    DEFAULT_SQL = ("INSERT INTO mqtt_messages (clientid, topic, payload) "
                   "VALUES (${1}, ${2}, ${3})")

    def __init__(self, conf: Dict[str, Any], name: str = "") -> None:
        from ..auth.postgres import PgClient

        self.client = PgClient(
            conf.get("server", "127.0.0.1:5432"),
            user=conf.get("user", "postgres"),
            password=conf.get("password"),
            database=conf.get("database", "postgres"),
            timeout=float(conf.get("timeout", 5.0)))
        # accept both ${n} placeholders and native $n
        self.sql = re.sub(r"\$\{(\d+)\}", r"$\1",
                          conf.get("sql", self.DEFAULT_SQL))

    async def start(self) -> None:
        await self.client.query("SELECT 1")

    async def stop(self) -> None:
        await self.client.close()

    async def health(self) -> bool:
        try:
            await self.client.query("SELECT 1")
            return True
        except Exception:
            return False

    async def send(self, items: List[Dict[str, Any]]) -> Optional[int]:
        for i, it in enumerate(items):
            try:
                await self.client.query(self.sql, tuple(it["params"]))
            except Exception as e:
                raise SendError(f"pg bridge: {e}", done=i) from e
        return 0


# ---------------------------------------------------------------------------
# MySQL: INSERT via COM_QUERY with escaped literals
# ---------------------------------------------------------------------------

def render_mysql(conf: Dict[str, Any], output: Dict[str, Any],
                 columns: Dict[str, Any]) -> Dict[str, Any]:
    """Values render per message and are spliced as ESCAPED QUOTED
    literals (auth/mysql.escape_literal — injection-tested); the SQL
    template uses ${1}..${n} positions."""
    params = [
        _render(str(p), output, columns)
        for p in conf.get("parameters",
                          ["${clientid}", "${topic}", "${payload}"])
    ]
    return {"params": params}


class MysqlBridgeConnector(Connector):
    DEFAULT_SQL = ("INSERT INTO mqtt_messages (clientid, topic, payload) "
                   "VALUES (${1}, ${2}, ${3})")

    def __init__(self, conf: Dict[str, Any], name: str = "") -> None:
        from ..auth.mysql import MysqlClient

        self.client = MysqlClient(
            conf.get("server", "127.0.0.1:3306"),
            user=conf.get("user", "root"),
            password=conf.get("password", ""),
            database=conf.get("database", "mqtt"),
            timeout=float(conf.get("timeout", 5.0)))
        self.sql = conf.get("sql", self.DEFAULT_SQL)

    def _statement(self, params: List[str],
                   no_backslash_escapes: bool = False) -> str:
        # single-pass: sequential replace would re-scan spliced values,
        # letting a payload containing ${n} smuggle another field.
        # Escaping honors the connection's probed @@sql_mode — under
        # NO_BACKSLASH_ESCAPES a doubled backslash would be stored as
        # corrupted payload data.  send() renders via query_with_mode,
        # i.e. only after the (re)connected session's probe resolved.
        from ..auth.mysql import escape_literal

        def sub(m):
            i = int(m.group(1)) - 1
            if not 0 <= i < len(params):
                return m.group(0)
            return "'" + escape_literal(
                params[i],
                no_backslash_escapes=no_backslash_escapes) + "'"

        return re.sub(r"\$\{(\d+)\}", sub, self.sql)

    async def start(self) -> None:
        await self.client.query("SELECT 1")

    async def stop(self) -> None:
        await self.client.close()

    async def health(self) -> bool:
        try:
            await self.client.query("SELECT 1")
            return True
        except Exception:
            return False

    async def send(self, items: List[Dict[str, Any]]) -> Optional[int]:
        for i, it in enumerate(items):
            try:
                params = it["params"]
                await self.client.query_with_mode(
                    lambda nbe, p=params: self._statement(p, nbe))
            except Exception as e:
                raise SendError(f"mysql bridge: {e}", done=i) from e
        return 0


# ---------------------------------------------------------------------------
# MongoDB: insert documents
# ---------------------------------------------------------------------------

def render_mongo(conf: Dict[str, Any], output: Dict[str, Any],
                 columns: Dict[str, Any]) -> Dict[str, Any]:
    tpl = conf.get("payload_template")
    if tpl:
        doc = {k: _render(str(v), output, columns)
               for k, v in tpl.items()}
    else:
        doc = {}
        for k, v in {**columns, **output}.items():
            if isinstance(v, bytes):
                v = v.decode("utf-8", "replace")
            if isinstance(v, (str, int, float, bool, type(None))):
                doc[k] = v
            else:
                doc[k] = json.dumps(v, default=str)
    return {"doc": doc}


class MongoBridgeConnector(Connector):
    def __init__(self, conf: Dict[str, Any], name: str = "") -> None:
        from ..auth.mongo import MongoClient

        self.client = MongoClient(
            conf.get("server", "127.0.0.1:27017"),
            database=conf.get("database", "mqtt"),
            timeout=float(conf.get("timeout", 5.0)),
            username=conf.get("username", ""),
            password=conf.get("password", ""),
            auth_source=conf.get("auth_source", "admin"))
        self.collection = conf.get("collection", "mqtt_messages")

    async def start(self) -> None:
        await self.client.command({"ping": 1})

    async def stop(self) -> None:
        await self.client.close()

    async def health(self) -> bool:
        try:
            await self.client.command({"ping": 1})
            return True
        except Exception:
            return False

    async def send(self, items: List[Dict[str, Any]]) -> Optional[int]:
        docs = [it["doc"] for it in items]
        try:
            reply = await self.client.command(
                {"insert": self.collection, "documents": docs})
        except Exception as e:
            raise SendError(f"mongo bridge: {e}") from e
        n = int(reply.get("n", 0))
        if n < len(docs):
            # partially applied server-side: the leading n are stored
            raise SendError(f"mongo insert applied {n}/{len(docs)}",
                            done=n)
        return 0


# ---------------------------------------------------------------------------
# InfluxDB: v2 write API, line protocol
# ---------------------------------------------------------------------------

def _lp_escape(s: str, *, field_key: bool = False) -> str:
    out = s.replace("\\", "\\\\").replace(",", "\\,").replace(" ", "\\ ")
    if field_key:
        out = out.replace("=", "\\=")
    return out


def render_influx(conf: Dict[str, Any], output: Dict[str, Any],
                  columns: Dict[str, Any]) -> Dict[str, Any]:
    """One line-protocol line: measurement,tags fields [timestamp]."""
    measurement = _render(conf.get("measurement", "mqtt"), output, columns)
    tags = "".join(
        f",{_lp_escape(k, field_key=True)}="
        f"{_lp_escape(_render(str(v), output, columns), field_key=True)}"
        for k, v in (conf.get("tags") or {"topic": "${topic}"}).items())
    fields = []
    for k, v in (conf.get("fields") or {"payload": "${payload}"}).items():
        rv = _render(str(v), output, columns)
        # strict numeric literal only: Python float() also accepts
        # "nan"/"inf"/"1_2", which InfluxDB rejects with a 400 that
        # would permanently drop the whole batch
        if re.fullmatch(r"[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?", rv):
            fields.append(f"{_lp_escape(k, field_key=True)}={rv}")
        else:
            quoted = rv.replace("\\", "\\\\").replace('"', '\\"')
            fields.append(f'{_lp_escape(k, field_key=True)}="{quoted}"')
    line = f"{_lp_escape(measurement)}{tags} {','.join(fields)}"
    return {"line": line}


class InfluxBridgeConnector(Connector):
    def __init__(self, conf: Dict[str, Any], name: str = "") -> None:
        base = conf.get("server", "http://127.0.0.1:8086")
        bucket = conf.get("bucket", "mqtt")
        org = conf.get("org", "emqx")
        self.url = (f"{base}/api/v2/write?bucket={bucket}&org={org}"
                    f"&precision=ms")
        self.headers = {"content-type": "text/plain; charset=utf-8"}
        tok = conf.get("token")
        if tok:
            self.headers["authorization"] = f"Token {tok}"
        self.timeout = float(conf.get("timeout", 5.0))

    async def health(self) -> bool:
        from . import httpc

        try:
            r = await httpc.request(
                "POST", self.url, headers=self.headers, body=b"",
                timeout=self.timeout)
            return r.status < 500
        except Exception:
            return False

    async def send(self, items: List[Dict[str, Any]]) -> Optional[int]:
        from . import httpc

        body = "\n".join(it["line"] for it in items).encode()
        try:
            r = await httpc.request("POST", self.url,
                                    headers=self.headers, body=body,
                                    timeout=self.timeout)
        except Exception as e:
            raise SendError(f"influx bridge: {e}") from e
        if r.status >= 500:
            raise SendError(f"influx write {r.status}")
        if r.status >= 400:
            # bad line protocol: permanent — reject the whole batch
            raise SendError(f"influx write {r.status}", retryable=False,
                            done=len(items), rejected=len(items))
        return 0
